"""Multi-tenant isolation A/B: the tenant plane on vs the open pool,
plus a default-path pin probe.

The ISSUE 17 acceptance artifact. One seeded MULTI-STREAM open-loop
trace (tools/loadgen.py ``multi_stream_times`` — an 'interactive'
stream at a modest fraction of measured capacity interleaved with a
'batch' flood offered at >= 3x its weighted fair share) is driven
through two servers built from the SAME warmed engine:

* ``isolated`` — ``TenantPolicy`` on: WFQ weights interactive:3 /
  batch:1 with priority classes, and a small pool-wide admission quota
  on batch so the flood fast-fails at the door instead of occupying
  the queue.
* ``open`` — the tenant plane OFF (requests submitted untagged, the
  byte-identical default path): one shared FIFO queue and the global
  admission limit, exactly what every request saw before this plane
  existed.

Bars (pinned by tests/test_artifacts.py::
test_tenant_ab_artifact_schema):

* **isolated keeps interactive clean** — interactive p99 within the
  SLO (default: 20x one measured dispatch) and ZERO interactive sheds,
  while batch floods at >= 3x its fair share (``bar_flood_factor``);
* **open twin breaches** — the same interactive stream behind the same
  flood with no isolation blows its SLO (p99 over the bar and/or
  interactive requests shed by the shared queue) [full mode];
* **quota coherence** — every batch quota shed in the isolated arm is
  a tenant-tagged ``tenant_quota_shed`` event, count-for-count;
* **default path pinned** — the open arm's event stream and summary
  carry ZERO tenant-plane footprint: no tenant-named events, no
  tenant/tenants fields, no per-tenant rollup. Untagged traffic is
  byte-for-byte the pre-plane serving path.

Usage::

    JAX_PLATFORMS=cpu python tools/tenant_ab.py \
        --out docs/artifacts/tenant_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BAR_FLOOD_FACTOR = 3.0  # batch offered load vs its weighted fair share
WEIGHTS = "interactive:3,batch:1"


def _ensure_xla_flags() -> None:
    import sys as _sys

    if "jax" in _sys.modules:
        print("tenant_ab: note — jax already imported; flags unchanged")
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_multi_thread_eigen" not in flags:
        flags += (
            " --xla_cpu_multi_thread_eigen=false"
            " intra_op_parallelism_threads=1"
        )
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_engine(max_batch: int):
    """A mid-size GNOT on the single-bucket Darcy64 schema (the
    autoscale_ab sizing): dispatches are compute-heavy — XLA with the
    GIL released — so the capacity probe means what it says, and ONE
    bucket makes the WFQ/priority drain the only arbiter of order."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.serve import InferenceEngine
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(max(16, max_batch), seed=0, grid_n=8)
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=96, n_mlp_num_layers=2,
        n_mlp_hidden_dim=96, n_input_hidden_dim=96, n_expert=2, n_head=2,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    params = init_params(model, collate(samples), 0)
    return InferenceEngine(model, params, batch_size=max_batch), samples


def _pct(lat: list[float], q: float) -> float | None:
    """Exact client-side percentile over the resolved latencies (the
    artifact's bar values; the summary's histogram estimate is the
    cross-checked secondary view)."""
    return float(np.percentile(lat, q)) if lat else None


def _arm(
    name: str,
    engine,
    samples,
    trace,
    *,
    tagged: bool,
    policy_specs: dict | None,
    max_batch: int,
    max_wait_ms: float,
    queue_limit: int,
):
    """One open-loop replay of the shared interleaved trace through a
    fresh server over the warmed engine. Per-tenant outcomes are
    tallied CLIENT-SIDE from the trace's tenant labels — identically
    in both arms, so the open twin (which submits untagged) is measured
    on exactly the same axis."""
    import loadgen

    from gnot_tpu.serve import InferenceServer, TenantPolicy
    from gnot_tpu.utils.metrics import MetricsSink

    policy = (
        TenantPolicy.from_specs(**policy_specs) if policy_specs else None
    )
    metrics_path = os.path.join(
        tempfile.mkdtemp(prefix=f"tenant_ab_{name}_"), "serve.jsonl"
    )
    offsets = [t for t, _ in trace]
    with MetricsSink(metrics_path) as sink:
        server = InferenceServer(
            engine,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            queue_limit=queue_limit,
            sink=sink,
            tenants=policy,
        ).start()

        def submit(i):
            kw = {"tenant": trace[i][1]} if tagged else {}
            return server.submit(samples[i % len(samples)], **kw)

        t0 = time.perf_counter()
        futures = loadgen.replay(submit, offsets)
        results = [f.result(timeout=600) for f in futures]
        wall = time.perf_counter() - t0
        summary = server.drain()
    events = [json.loads(l) for l in open(metrics_path)]
    per: dict[str, dict] = {}
    lat: dict[str, list] = {}
    for (_, tenant), r in zip(trace, results):
        st = per.setdefault(
            tenant, {"submitted": 0, "completed": 0, "shed": {}}
        )
        st["submitted"] += 1
        if r.ok:
            st["completed"] += 1
            lat.setdefault(tenant, []).append(r.latency_ms)
        else:
            st["shed"][r.reason] = st["shed"].get(r.reason, 0) + 1
    for t, st in per.items():
        st["shed_total"] = sum(st["shed"].values())
        st["p50_ms"] = _pct(lat.get(t, []), 50)
        st["p99_ms"] = _pct(lat.get(t, []), 99)
    rec = {
        "arm": name,
        "tagged": tagged,
        "policy": policy_specs or None,
        "submitted": len(results),
        "completed": sum(r.ok for r in results),
        "shed": summary["shed"],
        "wall_s": round(wall, 2),
        "achieved_rps": round(sum(r.ok for r in results) / wall, 1),
        "tenants": {t: per[t] for t in sorted(per)},
    }
    return rec, summary, events


def _default_pin(events: list[dict], summary: dict) -> dict:
    """The byte-identical default-path probe, read off the OPEN arm's
    own artifacts: untagged traffic through the current code must leave
    ZERO tenant-plane footprint — no tenant-named events, no
    tenant/tenants fields on any record, no per-tenant summary rollup.
    Any nonzero count here means the plane leaked into the default
    path."""
    tenant_events = sum(
        1 for e in events if "tenant" in (e.get("event") or "")
    )
    tenant_fields = sum(
        1 for e in events if "tenant" in e or "tenants" in e
    )
    return {
        "probe": "default_pin",
        "events_scanned": len(events),
        "tenant_named_events": tenant_events,
        "tenant_fields": tenant_fields,
        "summary_has_tenants": "tenants" in summary,
        "bar": 0,
    }


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--duration_s", type=float, default=16.0)
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--max_wait_ms", type=float, default=4.0)
    p.add_argument("--queue_limit", type=int, default=256)
    p.add_argument(
        "--interactive_mult", type=float, default=0.3,
        help="interactive offered load as a fraction of measured "
             "capacity (comfortably under its 3/4 weighted share)"
    )
    p.add_argument(
        "--batch_mult", type=float, default=0.9,
        help="batch offered load as a fraction of measured capacity "
             "(0.9 = 3.6x its 1/4 weighted fair share; the flood)"
    )
    p.add_argument(
        "--quota_mult", type=int, default=2,
        help="batch admission quota in multiples of max_batch"
    )
    p.add_argument(
        "--slo_p99_ms", type=float, default=0.0,
        help="interactive p99 SLO; 0 = auto (20x one measured dispatch)"
    )
    p.add_argument(
        "--max_arrivals", type=int, default=4500,
        help="cap on total trace arrivals — on fast hosts the window "
             "shrinks instead of the storm growing unboundedly"
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="short window + small storm (CI smoke, not the "
                        "committed artifact)")
    args = p.parse_args(argv)
    if args.quick:
        args.duration_s = min(args.duration_s, 4.0)
        args.max_arrivals = min(args.max_arrivals, 1200)

    _ensure_xla_flags()

    import loadgen

    engine, samples = _build_engine(args.max_batch)
    engine.warmup(samples, rows=args.max_batch)

    # Capacity probe: one full-batch dispatch rate sets the trace
    # scale — the flood must genuinely exceed the pool's ability to
    # serve both streams.
    key = engine.bucket_key(samples[0])
    t0 = time.perf_counter()
    for s in samples[:8]:
        engine.infer(
            [s], pad_nodes=key[0], pad_funcs=key[1], rows=args.max_batch
        )
    dispatch_s = (time.perf_counter() - t0) / 8
    cap = args.max_batch / dispatch_s
    slo_ms = args.slo_p99_ms or round(20 * dispatch_s * 1e3, 1)
    interactive_rps = args.interactive_mult * cap
    batch_rps = args.batch_mult * cap
    # batch's weighted fair share under interactive:3,batch:1 is 1/4
    # of capacity; the flood factor is offered/entitled.
    flood_factor = batch_rps / (cap / 4)
    offered = interactive_rps + batch_rps
    duration_s = min(args.duration_s, args.max_arrivals / offered)
    print(
        f"tenant_ab: dispatch {dispatch_s * 1e3:.1f} ms -> capacity "
        f"~{cap:.0f}/s; interactive {interactive_rps:.0f}/s, batch "
        f"flood {batch_rps:.0f}/s ({flood_factor:.1f}x fair share), "
        f"SLO p99 {slo_ms}ms, window {duration_s:.1f}s"
    )

    # THE shared trace: both arms replay this one interleaved schedule
    # — same tenants, same instants (the A/B's control variable).
    trace = loadgen.multi_stream_times(
        {
            "interactive": {"pattern": "steady", "base_rps": interactive_rps},
            "batch": {"pattern": "steady", "base_rps": batch_rps},
        },
        duration_s=duration_s,
        seed=args.seed,
    )
    n_batch = sum(1 for _, t in trace if t == "batch")
    print(
        f"tenant_ab: {len(trace)} arrivals on the shared trace "
        f"({len(trace) - n_batch} interactive / {n_batch} batch)"
    )

    specs = {
        "weights": WEIGHTS,
        "quotas": f"batch:{args.quota_mult * args.max_batch}",
    }
    common = dict(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_limit=args.queue_limit,
    )
    records: list[dict] = []
    failures: list[str] = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    iso, iso_summary, iso_events = _arm(
        "isolated", engine, samples, trace,
        tagged=True, policy_specs=specs, **common,
    )
    records.append(iso)
    it, bt = iso["tenants"]["interactive"], iso["tenants"]["batch"]
    print(
        f"  isolated  interactive p99={it['p99_ms']:.1f}ms "
        f"shed={it['shed_total']}; batch {bt['completed']}/"
        f"{bt['submitted']} ok shed={bt['shed']}"
    )

    open_, open_summary, open_events = _arm(
        "open", engine, samples, trace,
        tagged=False, policy_specs=None, **common,
    )
    records.append(open_)
    oi, ob = open_["tenants"]["interactive"], open_["tenants"]["batch"]
    print(
        f"  open      interactive p99={oi['p99_ms'] and round(oi['p99_ms'], 1)}ms "
        f"shed={oi['shed_total']}; batch {ob['completed']}/"
        f"{ob['submitted']} ok shed={ob['shed']}"
    )

    pin = _default_pin(open_events, open_summary)
    records.append(pin)

    # Isolated-arm cross-checks: the server's own per-tenant rollup and
    # the tenant-tagged quota shed stream agree with the client-side
    # tallies count-for-count.
    roll = iso_summary.get("tenants") or {}
    for t in ("interactive", "batch"):
        got, obs = roll.get(t) or {}, iso["tenants"][t]
        check(
            got.get("requests") == obs["submitted"]
            and got.get("completed") == obs["completed"]
            and (got.get("shed") or {}) == obs["shed"],
            f"isolated arm: summary rollup for {t} {got} != observed "
            f"{obs}",
        )
    n_quota_events = sum(
        1 for e in iso_events if e.get("event") == "tenant_quota_shed"
    )
    check(
        n_quota_events == bt["shed"].get("shed_tenant_quota", 0)
        and all(
            e.get("tenant") == "batch"
            for e in iso_events
            if e.get("event") == "tenant_quota_shed"
        ),
        f"isolated arm: {n_quota_events} tenant_quota_shed events don't "
        f"match batch quota sheds {bt['shed']}",
    )

    open_breached = bool(
        (oi["p99_ms"] or 0) > slo_ms or oi["shed_total"] > 0
    )
    summary = {
        "summary": "tenant_ab",
        "quick": bool(args.quick),
        "trace": "multi_stream:steady+steady",
        "duration_s": round(duration_s, 2),
        "arrivals": len(trace),
        "capacity_rps": round(cap, 1),
        "interactive_rps": round(interactive_rps, 1),
        "batch_rps": round(batch_rps, 1),
        "flood_factor": round(flood_factor, 2),
        "bar_flood_factor": BAR_FLOOD_FACTOR,
        "slo_p99_ms": slo_ms,
        "weights": WEIGHTS,
        "batch_quota": args.quota_mult * args.max_batch,
        "isolated_interactive_p99_ms": it["p99_ms"],
        "isolated_interactive_shed": it["shed_total"],
        "isolated_batch_quota_sheds": bt["shed"].get(
            "shed_tenant_quota", 0
        ),
        "open_interactive_p99_ms": oi["p99_ms"],
        "open_interactive_shed": oi["shed_total"],
        "open_breached": open_breached,
        "pin_tenant_footprint": pin["tenant_named_events"]
        + pin["tenant_fields"]
        + int(pin["summary_has_tenants"]),
    }
    records.append(summary)

    check(
        flood_factor >= BAR_FLOOD_FACTOR,
        f"batch flood {flood_factor:.2f}x under the "
        f"{BAR_FLOOD_FACTOR}x fair-share bar — the storm is vacuous",
    )
    check(
        it["shed_total"] == 0,
        f"isolated arm shed {it['shed_total']} interactive requests "
        f"({it['shed']}) — isolation failed",
    )
    check(
        it["p99_ms"] is not None and it["p99_ms"] <= slo_ms,
        f"isolated arm interactive p99 {it['p99_ms']}ms over the "
        f"{slo_ms}ms SLO",
    )
    check(
        bt["shed"].get("shed_tenant_quota", 0) >= 1,
        f"batch flood never hit its quota in the isolated arm: "
        f"{bt['shed']}",
    )
    if not args.quick:
        # The breach bar holds on the committed (full-window) trace;
        # --quick may end before the open arm's shared queue has grown
        # past the SLO, so the CI smoke checks wiring + the isolation
        # invariants only.
        check(
            open_breached,
            f"open twin did not breach: interactive p99 "
            f"{oi['p99_ms']}ms vs SLO {slo_ms}ms, shed "
            f"{oi['shed_total']}",
        )
    check(
        summary["pin_tenant_footprint"] == 0,
        f"default path carries tenant-plane footprint: {pin}",
    )

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(
        f"tenant_ab: interactive p99 isolated "
        f"{it['p99_ms']:.1f}ms (shed {it['shed_total']}) vs open "
        f"{oi['p99_ms'] and round(oi['p99_ms'], 1)}ms (shed "
        f"{oi['shed_total']}) under a {flood_factor:.1f}x batch flood; "
        f"quota sheds {summary['isolated_batch_quota_sheds']}, default "
        f"pin footprint {summary['pin_tenant_footprint']}; wrote "
        f"{args.out}"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary = dict(summary)
    summary["failures"] = failures
    return summary


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
