"""Metrics-plane overhead A/B: serve-storm throughput with the live
metrics plane OFF vs ON.

The acceptance bar for the metrics plane (docs/observability.md "Live
metrics", mirroring the tracing/telemetry subsystems) is <=2%
throughput cost with the publisher running. The ON arm is the WHOLE
plane at its real sites: a ``MetricsRegistry`` attached to the server
(per-request histogram records, per-bucket series, shed/dispatch
counters, snapshot-time gauges), a ``MetricsPublisher`` thread
polling it on a sub-second interval (JSONL time series + Prometheus
exposition + ``metrics_snapshot`` events to a live sink), and the
``SLOEvaluator`` burning every snapshot — against an OFF arm running
the identical storm with no registry. Timed windows are best-of-N and
interleaved off/on like tools/tracing_ab.py, so ambient machine-load
drift hits both arms alike.

Usage::

    JAX_PLATFORMS=cpu python tools/metrics_ab.py \
        --n 400 --repeats 3 --out docs/artifacts/metrics_overhead_ab.jsonl

Emits one JSONL record per arm plus a summary record with
``overhead_frac``; committed as docs/artifacts/metrics_overhead_ab.jsonl
and schema-pinned by tests/test_artifacts.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _window(
    engine, traffic, *, on: bool, interval_s: float, max_batch: int
) -> tuple[float, dict]:
    """One timed storm window: submit -> all resolved, on a fresh
    server over the shared warm engine. Returns (seconds, info)."""
    from gnot_tpu.obs.metrics import (
        MetricsPublisher,
        MetricsRegistry,
        SLOEvaluator,
        SLOObjective,
    )
    from gnot_tpu.serve import InferenceServer
    from gnot_tpu.utils.metrics import MetricsSink

    tmp = tempfile.mkdtemp(prefix="metrics_ab_")
    registry = publisher = None
    info: dict = {}
    # BOTH arms write the ordinary event stream (queue_depth per
    # dispatch, serve_summary at drain): the sink is the deployment's
    # baseline, not part of the metrics plane — the A/B isolates what
    # the registry + publisher + evaluator ADD on top of it.
    sink = MetricsSink(os.path.join(tmp, "events.jsonl"))
    if on:
        registry = MetricsRegistry()
        publisher = MetricsPublisher(
            registry,
            interval_s=interval_s,
            sink=sink,
            series_path=os.path.join(tmp, "series.jsonl"),
            exposition_path=os.path.join(tmp, "expo.prom"),
            evaluator=SLOEvaluator([
                SLOObjective("shed_fraction", "shed_frac", 0.05,
                             fast_window_s=0.5, slow_window_s=2.0),
                SLOObjective("breaker_open", "breaker_open", 1.0,
                             fast_window_s=0.5, slow_window_s=2.0),
            ]),
        )
    server = InferenceServer(
        engine, max_batch=max_batch, max_wait_ms=2.0,
        queue_limit=4 * len(traffic), metrics=registry, sink=sink,
    ).start()
    if publisher is not None:
        publisher.start()
    t0 = time.perf_counter()
    futures = [server.submit(s) for s in traffic]
    for f in futures:
        r = f.result(timeout=120)
        assert r.ok, r.reason
    seconds = time.perf_counter() - t0
    server.drain()
    if publisher is not None:
        info["snapshots"] = publisher.close()["seq"]
    sink.close()
    return seconds, info


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=400, help="requests per window")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--interval_s", type=float, default=0.25,
                   help="publisher cadence in the ON arm")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    import jax

    from serve_smoke import build_engine
    from gnot_tpu.data import datasets

    platform = jax.devices()[0].platform
    engine = build_engine(max_batch=args.max_batch)
    # Uniform darcy64 traffic: ONE bucket, warmed up front, so the
    # windows time dispatch + the metrics plane — never a compile.
    traffic = datasets.synth_darcy2d(args.n, seed=0, grid_n=8)
    engine.warmup(traffic[: args.max_batch], rows=args.max_batch)

    best = {"off": float("inf"), "on": float("inf")}
    snapshots = 0
    for _ in range(max(1, args.repeats)):
        # Interleaved off/on (the telemetry/tracing A/B methodology):
        # ambient load drift cancels across arms.
        sec_off, _ = _window(
            engine, traffic, on=False, interval_s=args.interval_s,
            max_batch=args.max_batch,
        )
        sec_on, info = _window(
            engine, traffic, on=True, interval_s=args.interval_s,
            max_batch=args.max_batch,
        )
        best["off"] = min(best["off"], sec_off)
        best["on"] = min(best["on"], sec_on)
        snapshots = max(snapshots, info.get("snapshots", 0))

    records = []
    for arm in ("off", "on"):
        records.append({
            "arm": f"metrics_{arm}",
            "requests": args.n,
            "seconds": round(best[arm], 4),
            "requests_per_s": round(args.n / best[arm], 2),
            "platform": platform,
            "max_batch": args.max_batch,
            "interval_s": args.interval_s,
            "repeats": args.repeats,
            **({"snapshots": snapshots} if arm == "on" else {}),
        })
    rps_off = records[0]["requests_per_s"]
    rps_on = records[1]["requests_per_s"]
    records.append({
        "summary": "metrics_overhead",
        "config": "darcy64_storm",
        "requests_per_s_off": rps_off,
        "requests_per_s_on": rps_on,
        "snapshots_on": snapshots,
        "overhead_frac": round(1.0 - rps_on / rps_off, 4),
        "bar": "overhead_frac <= 0.02 with the publisher running",
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
