"""Honest per-step timing probe for the tunnel-attached chip.

A thin CLI over ``bench.time_scan_marginal`` — the one copy of the
estimator: K-step scanned programs at two lengths, marginal ms/step
(the constant dispatch/tunnel round-trip cancels in the difference),
HARD-FETCH sync (``block_until_ready`` has been observed returning
early on the axon platform), transient-error retries.

Usage: python tools/honest_probe.py [--dtype bfloat16] [--attention_impl xla]
       [--ffn_impl xla] [--config ns2d] [--n_points 1024] [--batch_size 4]
       [--k1 25] [--k2 100] [--windows 3]
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser()
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--attention_impl", default="xla")
    p.add_argument("--ffn_impl", default="xla")
    p.add_argument("--config", default="ns2d")
    p.add_argument("--n_points", type=int, default=1024)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--k1", type=int, default=25)
    p.add_argument("--k2", type=int, default=100)
    p.add_argument("--windows", type=int, default=3)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--flat_params", action="store_true",
                   help="flat [P]-vector state layout")
    p.add_argument("--n_attn_layers", type=int, default=0,
                   help="override depth (0 = reference default)")
    args = p.parse_args()

    import bench

    overrides = (
        {"n_attn_layers": args.n_attn_layers} if args.n_attn_layers else None
    )
    step, state, batch, mc = bench.build(
        args.dtype, args.attention_impl, args.n_points, args.batch_size,
        args.ffn_impl, args.config, args.remat, args.flat_params, overrides,
    )
    per = bench.time_scan_marginal(
        step, state, batch, jnp.asarray(1e-3, jnp.float32), jax.devices()[0],
        args.k1, args.k2, args.windows,
    )
    label = f"{args.dtype} attn={args.attention_impl} ffn={args.ffn_impl} {args.config}"
    if args.n_attn_layers:
        label += f" layers={args.n_attn_layers}"
    if args.flat_params:
        label += " flat"
    print(
        f"{label}: {per * 1e3:.2f} ms/step  "
        f"{batch.n_real_points / per / 1e6:.3f}M pts/s"
    )


if __name__ == "__main__":
    main()
