"""Per-op time breakdown of the headline training step (VERDICT r4 #2).

Captures a ``jax.profiler`` device trace of the NS2d-1k bf16 jitted
train step (the BENCH headline workload: reference-default
architecture, B=4, L=1024) and aggregates the ``/device:TPU:0``
"XLA Ops" timeline into a per-op table: MXU work (dot/fusion-with-dot)
vs elementwise fusions vs copies vs everything else.  The trace is a
one-dispatch K-step ``lax.scan`` (same program bench.py times), so the
breakdown describes exactly the step the headline MFU comes from.

Writes (committed under docs/artifacts/):
  * ``profile_breakdown.json`` — the aggregated table + totals;
  * the raw ``*.xplane.pb`` stays under --trace_dir for ad-hoc
    Perfetto/XProf inspection (too big to commit).

Usage:  python tools/profile_step.py [--k 20] [--dtype bfloat16]
        [--out docs/artifacts/profile_breakdown.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def family(event_name: str) -> str:
    """Instruction-family key: the HLO instruction name with ``%`` and
    the uniquifying ``.N`` suffix stripped (XLA names instructions
    after their opcode or a descriptive fused pattern, e.g.
    ``%multiply_add_fusion.645`` -> ``multiply_add_fusion``)."""
    base = event_name.split(" = ")[0].lstrip("%")
    return re.sub(r"[.\d]+$", "", base)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--k", type=int, default=20, help="steps in the traced scan")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--config", default="ns2d")
    p.add_argument("--n_points", type=int, default=1024)
    p.add_argument("--trace_dir", default="/tmp/gnot_profile")
    p.add_argument("--out", default="docs/artifacts/profile_breakdown.json")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--flat_params", action="store_true",
                   help="profile the flat [P]-vector state layout")
    args = p.parse_args()
    if args.flat_params and args.out == p.get_default("out"):
        # Layout-suffixed default: never clobber the committed
        # tree-layout artifact with flat-layout numbers.
        args.out = args.out.replace(".json", "_flat.json")

    import jax
    import jax.numpy as jnp

    import bench

    step, state, batch, _ = bench.build(args.dtype, config=args.config,
                                        n_points=args.n_points,
                                        flat_params=args.flat_params)
    lr = jnp.asarray(1e-3, jnp.float32)
    multi = bench._scan_program(step)
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))

    # Compile outside the trace; hard-fetch completion (axon tunnel:
    # block_until_ready is untrustworthy, docs/performance.md).
    s = copy_tree(state)
    s2, loss = multi(s, batch, lr, args.k)
    bench._hard_sync(s2, loss)

    s = copy_tree(state)
    with jax.profiler.trace(args.trace_dir):
        s2, loss = multi(s, batch, lr, args.k)
        bench._hard_sync(s2, loss)

    pbs = sorted(glob.glob(os.path.join(args.trace_dir, "**/*.xplane.pb"),
                           recursive=True), key=os.path.getmtime)
    if not pbs:
        sys.exit(f"no *.xplane.pb under {args.trace_dir} — the profiler "
                 "did not write a trace (transient tunnel failure? rerun)")
    pd = jax.profiler.ProfileData.from_file(pbs[-1])
    tpu = next((pl for pl in pd.planes if "TPU" in pl.name), None)
    if tpu is None:
        sys.exit("trace has no /device:TPU plane — this tool needs the "
                 "TPU backend (planes: "
                 + ", ".join(pl.name for pl in pd.planes) + ")")
    by_line = {ln.name: list(ln.events) for ln in tpu.lines}
    if "XLA Ops" not in by_line:
        sys.exit("device plane has no 'XLA Ops' line — empty trace; rerun")

    module_ps = sum(e.duration_ns for e in by_line.get("XLA Modules", []))
    # The scanned program is one big `while`; its timeline event spans
    # every child op, so it is reported separately, NOT summed with
    # the children (that would double-count the whole step).
    fams: dict[str, dict] = {}
    wrapper_ns = 0.0
    for e in by_line["XLA Ops"]:
        fam = family(e.name)
        if fam == "while":
            wrapper_ns += e.duration_ns
            continue
        d = fams.setdefault(fam, {"ns": 0.0, "count": 0, "hlo": e.name[:200]})
        d["ns"] += e.duration_ns
        d["count"] += 1
    total_ops_ns = sum(v["ns"] for v in fams.values())

    top = sorted(fams.items(), key=lambda kv: -kv[1]["ns"])[: args.top]
    result = {
        "workload": {
            "config": args.config, "dtype": args.dtype, "k_steps": args.k,
            "n_points": args.n_points, "batch": 4,
            "flat_params": args.flat_params,
        },
        "device": jax.devices()[0].device_kind,
        "module_total_ms_per_step": module_ps / 1e6 / args.k,
        "while_wrapper_ms_per_step": wrapper_ns / 1e6 / args.k,
        "ops_total_ms_per_step": total_ops_ns / 1e6 / args.k,
        "op_families": [
            {
                "family": k,
                "ms_per_step": round(v["ns"] / 1e6 / args.k, 4),
                "pct_of_ops": round(100 * v["ns"] / total_ops_ns, 2),
                "count_per_step": v["count"] / args.k,
                "example_hlo": v["hlo"],
            }
            for k, v in top
        ],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("module_total_ms_per_step", "while_wrapper_ms_per_step",
                       "ops_total_ms_per_step")}, indent=1))
    for f_ in result["op_families"]:
        print(f'{f_["ms_per_step"]:8.4f}ms {f_["pct_of_ops"]:5.1f}% '
              f'x{f_["count_per_step"]:6.1f}  {f_["family"]}')
    print(f"full breakdown -> {args.out}; raw trace under {args.trace_dir}")


if __name__ == "__main__":
    main()
