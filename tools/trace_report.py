"""Trace analysis: load a Chrome trace-event JSON written by
``--trace_path`` (obs/tracing.py) and print where the time went.

Three views (docs/observability.md "Tracing"):

1. **Per-span-kind latency table** — count, p50, p99, total wall-time
   per span name (``queue_wait``, ``device``, ``step_dispatch``, ...).
2. **Per-bucket queue-wait vs device-time breakdown** (serve traces) —
   the shape-dependent latency split bucketed padding creates: a
   request's time divides into waiting for batchmates vs the compiled
   forward, and both vary per bucket.
3. **Critical path of the slowest request / step** — the single worst
   trace (serve: a request's admission→resolve chain; train: the
   slowest ``step`` span and its phase children), each phase with its
   duration and share, plus unattributed gap time.

Usage::

    python tools/trace_report.py run/trace.json
    python tools/trace_report.py docs/artifacts/serve_trace_example.json

Stdlib-only (reads JSON, prints text); importable — the tests and
other tools call :func:`report` and assert on the returned dict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gnot_tpu.obs.tracing import percentiles  # noqa: E402


def load_spans(path: str) -> list[dict]:
    """Chrome ``traceEvents`` -> span dicts with ms floats. Only
    ``ph: "X"`` complete events are spans; metadata events pass."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    spans = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        spans.append(
            {
                "name": e["name"],
                "start_ms": e["ts"] / 1e3,
                "dur_ms": e["dur"] / 1e3,
                "end_ms": (e["ts"] + e["dur"]) / 1e3,
                "trace_id": args.get("trace_id"),
                "span_id": args.get("span_id"),
                "parent_id": args.get("parent_id"),
                "args": args,
            }
        )
    return spans


def kind_stats(spans: list[dict]) -> dict[str, dict]:
    """name -> {count, p50_ms, p99_ms, total_ms}, ordered by total."""
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s["dur_ms"])
    out = {}
    for name, durs in sorted(
        by_name.items(), key=lambda kv: -sum(kv[1])
    ):
        out[name] = {
            "count": len(durs),
            **percentiles(durs),
            "total_ms": round(sum(durs), 4),
        }
    return out


def _queue_device_stats(spans: list[dict], arg_key: str) -> dict[str, dict]:
    """Group ``queue_wait``/``device`` spans by an args key and roll
    each group into queue-vs-device percentiles — the shared population
    definition behind both the per-bucket and per-replica views (change
    it here and both stay in agreement)."""
    groups: dict[str, dict[str, list[float]]] = {}
    for s in spans:
        key = s["args"].get(arg_key)
        if key is None or s["name"] not in ("queue_wait", "device"):
            continue
        st = groups.setdefault(str(key), {"queue_wait": [], "device": []})
        st[s["name"]].append(s["dur_ms"])
    out = {}
    for key, st in sorted(groups.items()):
        q, d = percentiles(st["queue_wait"]), percentiles(st["device"])
        out[key] = {
            "requests": len(st["queue_wait"]),
            "queue_p50_ms": q["p50_ms"],
            "queue_p99_ms": q["p99_ms"],
            "device_p50_ms": d["p50_ms"],
            "device_p99_ms": d["p99_ms"],
        }
    return out


def bucket_breakdown(spans: list[dict]) -> dict[str, dict]:
    """bucket -> queue-wait vs device-time percentiles (serve traces:
    ``queue_wait`` and ``device`` spans carry a ``bucket`` arg)."""
    return _queue_device_stats(spans, "bucket")


def replica_breakdown(spans: list[dict]) -> dict[str, dict]:
    """replica -> queue-wait vs device-time percentiles plus dispatch
    count (replicated serve traces: every span a replica server records
    carries a ``replica`` arg). This is how the load bench names the
    bottleneck PER REPLICA: a replica whose queue p99 dwarfs its device
    p99 is starved by placement or wedged, one whose device p99 grew is
    the sick engine. Empty for single-server traces (no replica args)."""
    out = {}
    dispatches: dict[str, set] = {}
    for s in spans:
        rep = s["args"].get("replica")
        if rep is not None and s["name"] == "dispatch":
            # dispatch spans repeat once per traced member; the
            # ``dispatch`` ordinal arg identifies the real dispatch.
            dispatches.setdefault(str(rep), set()).add(
                s["args"].get("dispatch", s["span_id"])
            )
    for rep, st in _queue_device_stats(spans, "replica").items():
        out[rep] = {
            "requests": st["requests"],
            "dispatches": len(dispatches.get(rep, ())),
            **{k: v for k, v in st.items() if k != "requests"},
        }
    return out


def tenant_breakdown(spans: list[dict]) -> dict[str, dict]:
    """tenant -> queue-wait vs device-time percentiles (multi-tenant
    serve traces: phase spans carry a ``tenant`` arg). The populations
    match ``serve_summary.tenants`` request-for-request over the traced
    subset, so a noisy-neighbor story told by the trace file can be
    cross-checked against the drain rollup. Empty when no request
    carried a tenant tag."""
    return _queue_device_stats(spans, "tenant")


def host_breakdown(spans: list[dict]) -> dict[str, dict]:
    """host -> queue-wait vs device-time percentiles plus placement
    count (merged FEDERATED traces: ``obs/dtrace.merge_traces`` stamps
    every remote span with a ``host`` arg, and controller ``placement``
    spans name their target host). Agrees with ``metrics_report.py``'s
    per-host view on which host is queue-bound vs device-bound. Empty
    for single-host traces."""
    placements: dict[str, int] = {}
    for s in spans:
        if s["name"] == "placement":
            h = s["args"].get("host")
            if h is not None:
                placements[str(h)] = placements.get(str(h), 0) + 1
    out = {}
    for host, st in _queue_device_stats(spans, "host").items():
        out[host] = {**st, "placements": placements.get(host, 0)}
    for host, n in placements.items():
        # A host that only ever RECEIVED placements (all its frames
        # lost / it died before exporting) still shows up honestly.
        out.setdefault(
            host,
            {
                "requests": 0, "queue_p50_ms": None, "queue_p99_ms": None,
                "device_p50_ms": None, "device_p99_ms": None,
                "placements": n,
            },
        )
    return dict(sorted(out.items()))


def critical_path(spans: list[dict]) -> dict | None:
    """The slowest request (serve) or step (train), phase by phase.

    Serve traces: the trace_id whose ``admission``..``resolve`` extent
    is longest; its spans in start order are the critical path (the
    chain is sequential by construction). Train traces: the slowest
    ``step`` span; its children plus itself. Returns ``{kind, trace_id,
    total_ms, phases: [{name, start_ms, dur_ms, share}], gap_ms}``."""
    steps = [s for s in spans if s["name"] == "step"]
    if steps and not any(s["name"] == "queue_wait" for s in spans):
        worst = max(steps, key=lambda s: s["dur_ms"])
        members = [worst] + [
            s for s in spans if s["parent_id"] == worst["span_id"]
        ]
        kind = "step"
    else:
        by_trace: dict[str, list[dict]] = {}
        for s in spans:
            if s["trace_id"] and s["name"] != "epoch":
                by_trace.setdefault(s["trace_id"], []).append(s)
        # Only complete request chains compete (a lone admission span
        # from a shed request isn't a latency story).
        candidates = {
            t: ss
            for t, ss in by_trace.items()
            if any(s["name"] == "resolve" for s in ss)
        } or by_trace
        if not candidates:
            return None
        members = max(
            candidates.values(),
            key=lambda ss: max(s["end_ms"] for s in ss)
            - min(s["start_ms"] for s in ss),
        )
        kind = "request"
    start = min(s["start_ms"] for s in members)
    end = max(s["end_ms"] for s in members)
    total = end - start
    phases = []
    attributed = 0.0
    for s in sorted(members, key=lambda s: (s["start_ms"], -s["dur_ms"])):
        if kind == "step" and s is not worst:
            attributed += s["dur_ms"]
        if kind == "request" and s["name"] not in (
            "admission", "batch_assembly", "device", "unpad"
        ):
            # The dispatch span CONTAINS assembly/device/unpad, and
            # admission is a sub-interval of queue_wait (both start at
            # submit); count only the non-overlapping top-level chain
            # (queue_wait, dispatch, resolve) toward attributed time so
            # gap_ms reports REAL unattributed gaps.
            attributed += s["dur_ms"]
        phases.append(
            {
                "name": s["name"],
                "start_ms": round(s["start_ms"] - start, 4),
                "dur_ms": round(s["dur_ms"], 4),
                "share": round(s["dur_ms"] / total, 4) if total else None,
            }
        )
    if kind == "step":
        attributed = min(attributed, worst["dur_ms"])
        total = worst["dur_ms"]
    return {
        "kind": kind,
        "trace_id": members[0]["trace_id"],
        "total_ms": round(total, 4),
        "phases": phases,
        "gap_ms": round(max(0.0, total - attributed), 4),
    }


def report(path: str) -> dict:
    spans = load_spans(path)
    return {
        "path": path,
        "spans": len(spans),
        "kinds": kind_stats(spans),
        "buckets": bucket_breakdown(spans),
        "replicas": replica_breakdown(spans),
        "tenants": tenant_breakdown(spans),
        "hosts": host_breakdown(spans),
        "critical_path": critical_path(spans),
    }


def _fmt(v) -> str:
    return "-" if v is None else f"{v:10.3f}"


def print_report(rep: dict) -> None:
    print(f"{rep['path']}: {rep['spans']} spans")
    print("\nper-span-kind latency (ms):")
    print(f"  {'kind':<16} {'count':>6} {'p50':>10} {'p99':>10} {'total':>10}")
    for name, st in rep["kinds"].items():
        print(
            f"  {name:<16} {st['count']:>6} {_fmt(st['p50_ms'])} "
            f"{_fmt(st['p99_ms'])} {_fmt(st['total_ms'])}"
        )
    if rep["buckets"]:
        print("\nqueue-wait vs device-time per bucket (ms):")
        print(
            f"  {'bucket':<12} {'reqs':>5} {'queue p50':>10} "
            f"{'queue p99':>10} {'device p50':>11} {'device p99':>11}"
        )
        for bucket, st in rep["buckets"].items():
            print(
                f"  {bucket:<12} {st['requests']:>5} "
                f"{_fmt(st['queue_p50_ms'])} {_fmt(st['queue_p99_ms'])} "
                f" {_fmt(st['device_p50_ms'])}  {_fmt(st['device_p99_ms'])}"
            )
    if rep.get("replicas"):
        print("\nqueue-wait vs device-time per replica (ms):")
        print(
            f"  {'replica':<8} {'reqs':>5} {'disp':>5} {'queue p50':>10} "
            f"{'queue p99':>10} {'device p50':>11} {'device p99':>11}"
        )
        for rid, st in rep["replicas"].items():
            print(
                f"  {rid:<8} {st['requests']:>5} {st['dispatches']:>5} "
                f"{_fmt(st['queue_p50_ms'])} {_fmt(st['queue_p99_ms'])} "
                f" {_fmt(st['device_p50_ms'])}  {_fmt(st['device_p99_ms'])}"
            )
    if rep.get("tenants"):
        print("\nqueue-wait vs device-time per tenant (ms):")
        print(
            f"  {'tenant':<12} {'reqs':>5} {'queue p50':>10} "
            f"{'queue p99':>10} {'device p50':>11} {'device p99':>11}"
        )
        for t, st in rep["tenants"].items():
            print(
                f"  {t:<12} {st['requests']:>5} "
                f"{_fmt(st['queue_p50_ms'])} {_fmt(st['queue_p99_ms'])} "
                f" {_fmt(st['device_p50_ms'])}  {_fmt(st['device_p99_ms'])}"
            )
    if rep.get("hosts"):
        print("\nqueue-wait vs device-time per host (ms, merged trace):")
        print(
            f"  {'host':<12} {'reqs':>5} {'place':>5} {'queue p50':>10} "
            f"{'queue p99':>10} {'device p50':>11} {'device p99':>11}"
        )
        for h, st in rep["hosts"].items():
            print(
                f"  {h:<12} {st['requests']:>5} {st['placements']:>5} "
                f"{_fmt(st['queue_p50_ms'])} {_fmt(st['queue_p99_ms'])} "
                f" {_fmt(st['device_p50_ms'])}  {_fmt(st['device_p99_ms'])}"
            )
    cp = rep["critical_path"]
    if cp is not None:
        print(
            f"\ncritical path — slowest {cp['kind']} "
            f"({cp['trace_id']}, {cp['total_ms']:.3f} ms total, "
            f"{cp['gap_ms']:.3f} ms unattributed):"
        )
        for ph in cp["phases"]:
            share = f"{ph['share'] * 100:5.1f}%" if ph["share"] is not None else ""
            print(
                f"  +{ph['start_ms']:9.3f} ms  {ph['name']:<16} "
                f"{ph['dur_ms']:9.3f} ms  {share}"
            )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace", help="Chrome trace-event JSON (--trace_path)")
    p.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of text",
    )
    args = p.parse_args(argv)
    if not os.path.exists(args.trace):
        print(f"no such trace file: {args.trace}", file=sys.stderr)
        return 2
    rep = report(args.trace)
    if args.json:
        json.dump(rep, sys.stdout, indent=1)
        print()
    else:
        print_report(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
