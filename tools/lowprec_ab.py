"""Low-precision serving A/B: f32 vs bf16 through the REAL replica tier,
per-dataset quality parity, and the native dispatch-path before/after.

Three measured claims, one committed artifact
(``docs/artifacts/lowprec_ab.jsonl``, schema-pinned by
``tests/test_artifacts.py::test_lowprec_ab_artifact_schema``):

1. **Quality parity** (the claim that matters on any hardware): for
   each benchmark dataset a small GNOT is trained in f32, then the SAME
   weights are served through the f32 engine and the bf16 engine
   (``serve.dtype`` policy: bf16 blocks, f32 accumulation/normalizer/
   head) and the test RelL2 is compared. The bf16 delta must sit under
   a stated, test-pinned bar — no tolerance loosening anywhere else.
2. **Throughput** through the real replica tier: open-loop Poisson
   arrivals over a shared offered-load ladder (the serve_bench
   methodology — arms differ ONLY in ``serve.dtype``), sustained req/s
   + tokens/s + p99 per arm.
3. **Dispatch hot path**: the SAME bf16 storm traced under the
   adaptive native packer vs the forced Python fallback — the
   trace_report host-phase breakdown (batch_assembly + unpad)
   before/after. At these payloads the reduction is the fused
   pad-and-cast's (batch_assembly); the unpad term is flat BY POLICY
   — per-dispatch unpad payloads (~0.5 MB at out_dim 1) sit under
   ``native.NATIVE_UNPAD_MIN_BYTES``, so both arms run the same numpy
   copy loop there, which is the adaptive policy's point.

**Honest-hardware note (read before quoting the throughput number).**
The bf16 COMPUTE win this mode is designed for lives on matrix
hardware (TPU MXU: bf16 multiplies at 2x f32 with native f32
accumulation). This image's CPU jaxlib (0.4.37) lowers bf16 dots by
upcasting — measured 1.1-3x SLOWER than f32 (the ``device_microbench``
record in the artifact; the host has AMX-BF16 silicon but no XLA path
to it). The committed CPU-proxy A/B therefore reports what this box
can honestly express: parity within the bar, the native host-path
reduction, and a req/s ratio whose device-side component is a measured
REGRESSION here. The 1.3x acceptance target is a TPU-path design
claim, recorded as ``bar_req_s_ratio_target`` with the microbench
evidence beside it — docs/performance.md "Low-precision serving"
carries the full analysis (same precedent as "Why the fused attention
kernel lost": commit the honest number, name the condition under which
the design wins).

Usage::

    JAX_PLATFORMS=cpu python tools/lowprec_ab.py \
        --out docs/artifacts/lowprec_ab.jsonl --replicas 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import serve_bench
import serve_smoke

#: Per-dataset |RelL2(bf16) - RelL2(f32)| bar the committed artifact is
#: pinned against. The policy keeps every RelL2-critical site f32, so
#: the delta is bf16 input/block quantization only — measured ~1e-3 on
#: every config; 0.01 gives honest headroom without tolerating a real
#: quality loss (an f32-head regression lands ~0.1+).
PARITY_BAR = 0.01

#: Datasets the parity pass trains+serves (name -> (synthetic config,
#: synth_size)). Sizes keep a full f32 train + two serves per dataset
#: in CPU minutes while exercising every schema (uniform grid, ragged
#: 2D clouds, 3D clouds).
PARITY_DATASETS = {
    "darcy64": ("darcy2d", 8),  # 64-point uniform grid (serve_smoke's mix)
    "elasticity": ("elasticity", 256),
    "ns2d": ("ns2d", 256),
    "heatsink3d": ("heatsink3d", 512),
}


def log_line(out, **kw):
    rec = dict(kw)
    if out:
        with open(out, "a") as f:
            f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return rec


# -- 1. quality parity ------------------------------------------------------


def rel_l2(preds, samples) -> float:
    """Mean per-sample relative L2 against the targets — the repo's
    eval metric, computed host-side on unpadded outputs."""
    vals = [
        float(np.linalg.norm(p - s.y) / max(np.linalg.norm(s.y), 1e-12))
        for p, s in zip(preds, samples)
    ]
    return float(np.mean(vals))


def parity_pass(args, out):
    """Train f32, serve the same weights at f32 and bf16, compare."""
    from gnot_tpu import config as config_lib
    from gnot_tpu.data import datasets
    from gnot_tpu.train.trainer import Trainer

    names = [n.strip() for n in args.datasets.split(",") if n.strip()]
    records = []
    for name in names:
        synth, size = PARITY_DATASETS[name]
        cfg = config_lib.make_config(**{
            "data.synthetic": synth,
            "data.synth_size": size,
            "data.n_train": args.parity_n_train,
            "data.n_test": args.parity_n_test,
            "data.batch_size": 4,
            "train.epochs": args.parity_epochs,
        })
        train_samples, test_samples = datasets.load(cfg.data)
        import dataclasses

        mc = dataclasses.replace(
            cfg.model,
            n_attn_layers=2, n_attn_hidden_dim=64, n_mlp_num_layers=2,
            n_mlp_hidden_dim=64, n_input_hidden_dim=64, n_expert=2,
            n_head=4, **datasets.infer_model_dims(train_samples),
        )
        trainer = Trainer(cfg, mc, train_samples, test_samples)
        best = trainer.fit()
        preds32 = trainer.inference_engine().predict(test_samples)
        preds16 = trainer.inference_engine("bfloat16").predict(test_samples)
        r32 = rel_l2(preds32, test_samples)
        r16 = rel_l2(preds16, test_samples)
        records.append(log_line(
            out,
            probe="parity",
            dataset=name,
            synthetic=synth,
            synth_size=size,
            epochs=args.parity_epochs,
            n_test=len(test_samples),
            best_train_metric=best,
            rel_l2_f32=round(r32, 6),
            rel_l2_bf16=round(r16, 6),
            delta=round(r16 - r32, 6),
            bar=PARITY_BAR,
        ))
    return records


# -- 2. replica-tier throughput A/B ----------------------------------------


def run_arm(router, traffic, *, offered_rps, duration_s, seed) -> dict:
    """One open-loop run (serve_bench methodology) that ALSO counts the
    node tokens of completed requests, for tokens/s."""
    rng = np.random.default_rng(seed)
    router.start()
    futures = []
    tokens = []
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    next_at = t0 + float(rng.exponential(1.0 / offered_rps))
    i = 0
    while next_at < deadline:
        now = time.perf_counter()
        if now < next_at:
            time.sleep(next_at - now)
        s = traffic[i % len(traffic)]
        futures.append(router.submit(s))
        tokens.append(s.coords.shape[0])
        i += 1
        next_at += float(rng.exponential(1.0 / offered_rps))
    results = [f.result(timeout=300) for f in futures]
    last_done = time.perf_counter()
    summary = router.drain()
    elapsed = last_done - t0
    completed = sum(r.ok for r in results)
    tokens_ok = sum(t for t, r in zip(tokens, results) if r.ok)
    shed = summary["shed"]
    return {
        "offered_rps": offered_rps,
        "duration_s": round(duration_s, 3),
        "submitted": len(futures),
        "completed": completed,
        "shed": shed,
        "shed_frac": (
            round(sum(shed.values()) / len(futures), 4) if futures else 0.0
        ),
        "achieved_rps": round(completed / elapsed, 2) if elapsed > 0 else None,
        "tokens_per_s": round(tokens_ok / elapsed, 1) if elapsed > 0 else None,
        "p50_ms": (
            round(summary["latency_p50_ms"], 2)
            if summary["latency_p50_ms"] is not None else None
        ),
        "p99_ms": (
            round(summary["latency_p99_ms"], 2)
            if summary["latency_p99_ms"] is not None else None
        ),
        "dispatches": summary["dispatches"],
        "dtype": summary["dtype"],
    }


def throughput_ab(args, model, params, traffic, out):
    from gnot_tpu.serve import InferenceEngine

    # Capacity probe + shared SLO from the f32 solo engine (one SLO,
    # both arms — "equal p99" means held to the same number).
    probe = InferenceEngine(model, params, batch_size=args.max_batch)
    probe.warmup(traffic, rows=args.max_batch)
    keys = [probe.bucket_key(s) for s in traffic]
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for s, k in zip(traffic[:8], keys[:8]):
            probe.infer([s], pad_nodes=k[0], pad_funcs=k[1],
                        rows=args.max_batch)
        times.append((time.perf_counter() - t0) / 8)
    dispatch_s = float(np.median(times))
    cap1 = args.max_batch / dispatch_s
    # 15x one dispatch: roomier than serve_bench's 12x — the ladder
    # probes the QUEUEING knee of each arm, and a bar too close to the
    # idle p99 turns knee noise into sustained-rung cliffs.
    slo = args.slo_p99_ms or round(15 * dispatch_s * 1e3, 1)
    print(
        f"lowprec_ab: f32 dispatch {dispatch_s * 1e3:.1f} ms -> offered "
        f"ladder off {cap1:.0f} req/s/replica, shared p99 SLO {slo} ms"
    )

    pools = {}
    for dtype in ("float32", "bfloat16"):
        pools[dtype] = serve_bench.make_replicas(
            model, params, args.replicas, max_batch=args.max_batch,
            traffic=traffic, dtype=dtype,
        )
        warm = pools[dtype][1]
        print(f"  warmed {dtype}: {warm['programs_warmed']} programs")

    loads = [float(x) for x in args.loads.split(",")]
    records = []
    for li, mult in enumerate(loads):
        offered = mult * cap1 * args.replicas
        for dtype in ("float32", "bfloat16"):  # interleaved arms
            router = serve_bench.fresh_router(
                pools[dtype][0], max_batch=args.max_batch,
                queue_limit=args.queue_limit,
            )
            rec = run_arm(
                router, traffic, offered_rps=offered,
                duration_s=args.duration_s, seed=args.seed + li,
            )
            rec = log_line(
                out,
                arm=f"serve_{'f32' if dtype == 'float32' else 'bf16'}",
                replicas=args.replicas, load_mult=mult, **rec,
            )
            records.append(rec)

    def sustained(arm):
        ok = [
            r for r in records
            if r["arm"] == arm
            and r["shed_frac"] <= args.max_shed_frac
            and r["p99_ms"] is not None and r["p99_ms"] <= slo
        ]
        return max(ok, key=lambda r: r["achieved_rps"], default=None)

    return records, sustained("serve_f32"), sustained("serve_bf16"), slo


# -- 3. native dispatch hot path: trace host phases before/after -----------


def _ragged_only(n, *, seed, mesh_lo, mesh_hi):
    """Pure large-cloud traffic for the host-phase arms (no 64-point
    darcy interleave — tiny dispatches would dilute the host phases
    the before/after measures)."""
    from gnot_tpu.data.batch import MeshSample

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        m = int(rng.integers(mesh_lo, mesh_hi))
        out.append(MeshSample(
            coords=rng.uniform(0, 1, size=(m, 2)).astype(np.float32),
            y=np.zeros((m, 1), np.float32),
            theta=np.ones((1,), np.float32),
            funcs=(rng.uniform(0, 1, size=(m // 4, 3)).astype(np.float32),),
        ))
    return out


def host_phase_ab(model, params, *, n, mesh_lo, mesh_hi, max_batch,
                  trace_dir, repeats=3):
    """Traced bf16 serve storms, packer impls INTERLEAVED per round
    (python, native, python, native, ...): the two arms sample the
    same thermal/cache/allocator state, and each trace_report host
    phase keeps its MIN total across rounds (the noise-floor estimator
    every bench in this repo uses — back-to-back whole arms drift at
    exactly the 10s-of-us scale these phases live at). Returns
    ``{"python": stats, "native": stats}``."""
    import trace_report

    from gnot_tpu import native
    from gnot_tpu.obs.tracing import Tracer
    from gnot_tpu.serve import InferenceEngine, InferenceServer

    traffic = _ragged_only(n, seed=9, mesh_lo=mesh_lo, mesh_hi=mesh_hi)
    engine = InferenceEngine(
        model, params, batch_size=max_batch, dtype="bfloat16"
    )
    engine.warmup(traffic, rows=max_batch)
    best: dict = {
        impl: {"requests": n} for impl in ("python", "native")
    }

    def one_storm(impl, path):
        saved = (native._lib, native._load_failed)
        if impl == "python":
            native._lib, native._load_failed = None, True
        try:
            tracer = Tracer(path=path)
            server = InferenceServer(
                engine, max_batch=max_batch, max_wait_ms=2.0,
                queue_limit=4 * n, tracer=tracer,
            )
            server.start()
            futures = [server.submit(s) for s in traffic]
            results = [f.result(timeout=120) for f in futures]
            server.drain(timeout_s=120)
            assert all(r.ok for r in results), "host-phase storm shed"
            tracer.flush()
        finally:
            native._lib, native._load_failed = saved
        spans = trace_report.load_spans(path)
        b = best[impl]
        for phase in ("batch_assembly", "unpad", "device"):
            durs = sorted(
                s["dur_ms"] for s in spans if s["name"] == phase
            )
            if not durs:
                continue
            # Each stat keeps its minimum ACROSS ROUNDS independently.
            # The committed estimator is the TRIMMED total (top 10% of
            # calls dropped): a single multi-ms scheduler preemption
            # inside one call poisons a plain total in either arm,
            # while the p50 alone misses that the python path's cost
            # lives in its heavier mid-tail — the trimmed sum is the
            # bulk cost both effects leave behind. p50 and the raw
            # total stay in the record for transparency.
            keep = durs[: max(1, len(durs) - max(1, len(durs) // 10))]
            stats = {
                "total_ms": round(sum(durs), 4),
                "trimmed_ms": round(sum(keep), 4),
                "p50_ms": round(durs[len(durs) // 2], 4),
            }
            for stat, v in stats.items():
                key = f"{phase}_{stat}"
                if b.get(key) is None or v < b[key]:
                    b[key] = v

    for rep_i in range(repeats):
        for impl in ("python", "native"):
            one_storm(
                impl, os.path.join(trace_dir, f"host_{impl}_{rep_i}.json")
            )
    return best


# -- driver -----------------------------------------------------------------


def device_microbench(model, params, traffic, *, max_batch, out):
    """The device-side dtype reality on THIS backend, committed next to
    the throughput numbers: one warm dispatch f32 vs bf16, plus a bare
    1024^2 matmul pair — the evidence line for why the CPU-proxy req/s
    ratio looks the way it does."""
    import jax
    import jax.numpy as jnp

    from gnot_tpu.serve import InferenceEngine

    ms = {}
    for dtype in ("float32", "bfloat16"):
        eng = InferenceEngine(
            model, params, batch_size=max_batch, dtype=dtype
        )
        eng.warmup(traffic[:2], rows=max_batch)
        k = eng.bucket_key(traffic[1])
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(4):
                eng.infer([traffic[1]], pad_nodes=k[0], pad_funcs=k[1],
                          rows=max_batch)
            ts.append((time.perf_counter() - t0) / 4)
        ms[dtype] = round(min(ts) * 1e3, 3)
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((1024, 1024)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((1024, 1024)), jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)
    mat = {}
    for name, (x, y) in (
        ("f32", (a, b)),
        ("bf16", (a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))),
    ):
        mm(x, y).block_until_ready()
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(4):
                mm(x, y).block_until_ready()
            ts.append((time.perf_counter() - t0) / 4)
        mat[name] = round(min(ts) * 1e3, 3)
    return log_line(
        out,
        probe="device_microbench",
        dispatch_ms_f32=ms["float32"],
        dispatch_ms_bf16=ms["bfloat16"],
        matmul1024_ms_f32=mat["f32"],
        matmul1024_ms_bf16=mat["bf16"],
        bf16_dispatch_slowdown=round(ms["bfloat16"] / ms["float32"], 3),
        note=(
            "this jaxlib's CPU backend upcasts bf16 dots (no "
            "oneDNN/AMX path); the bf16 compute win is a TPU-path "
            "property — see docs/performance.md 'Low-precision serving'"
        ),
    )


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--n_traffic", type=int, default=16)
    p.add_argument("--mesh_lo", type=int, default=600)
    p.add_argument("--mesh_hi", type=int, default=1000)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--queue_limit", type=int, default=256)
    p.add_argument("--duration_s", type=float, default=5.0)
    p.add_argument("--loads", type=str, default="0.25,0.4,0.5,0.6,0.7",
               help="offered-load rungs as fractions of replicas x "
                    "the measured solo f32 dispatch capacity (the "
                    "top rung sits at the pool's saturation knee)")
    p.add_argument("--slo_p99_ms", type=float, default=0.0)
    p.add_argument("--max_shed_frac", type=float, default=0.02)
    p.add_argument("--datasets", type=str,
                   default="darcy64,elasticity,ns2d,heatsink3d")
    p.add_argument("--parity_epochs", type=int, default=10)
    p.add_argument("--parity_n_train", type=int, default=48)
    p.add_argument("--parity_n_test", type=int, default=16)
    p.add_argument("--host_n", type=int, default=32,
                   help="requests in each host-phase traced storm")
    p.add_argument("--host_mesh_lo", type=int, default=8000)
    p.add_argument("--host_mesh_hi", type=int, default=15000)
    p.add_argument("--host_max_batch", type=int, default=8,
                   help="rows per host-phase dispatch (bigger than the "
                        "serve arms: the before/after isolates the "
                        "collate/unpad sweep, which scales with payload)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default="")
    p.add_argument("--quick", action="store_true",
                   help="tiny ladder/datasets (CI smoke, not the "
                        "committed artifact)")
    args = p.parse_args(argv)
    if args.quick:
        args.duration_s = min(args.duration_s, 1.5)
        args.loads = "0.3,0.5"
        args.datasets = "darcy64"
        args.parity_epochs = 2
        args.parity_n_train = 12
        args.parity_n_test = 6
        # Host meshes stay at full size: below ~100 KB/dispatch the
        # adaptive packer (correctly) routes both arms to numpy and
        # the before/after would measure nothing.
        args.host_n = 16
        args.replicas = min(args.replicas, 2)

    serve_bench._ensure_xla_flags(args.replicas)

    from gnot_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()

    from gnot_tpu import native

    out = args.out
    if out:
        if d := os.path.dirname(out):
            os.makedirs(d, exist_ok=True)
        open(out, "w").close()
    log_line(out, probe="native_packer", **native.status())

    # 1. quality parity per dataset.
    parity = parity_pass(args, out)

    # 2+3. one bench model for throughput + microbench; the host-phase
    # A/B gets a deliberately SMALL device model (the claim under test
    # is host-side collate/unpad cost — a wide model's activation
    # traffic at L=16k saturates the memory bus and drowns the host
    # sweep in device-side noise).
    bench_args = argparse.Namespace(
        max_batch=args.max_batch, layers=args.layers, hidden=args.hidden,
        seed=args.seed,
    )
    model, params = serve_bench._build_model(bench_args)
    host_args = argparse.Namespace(
        max_batch=args.host_max_batch, layers=1, hidden=16, seed=args.seed,
    )
    host_model, host_params = serve_bench._build_model(host_args)
    traffic = serve_smoke.mixed_traffic(
        args.n_traffic, seed=args.seed, mesh_lo=args.mesh_lo,
        mesh_hi=args.mesh_hi,
    )
    micro = device_microbench(
        model, params, traffic, max_batch=args.max_batch, out=out
    )
    records, best32, best16, slo = throughput_ab(
        args, model, params, traffic, out
    )

    import tempfile

    host = host_phase_ab(
        host_model, host_params, n=args.host_n,
        mesh_lo=args.host_mesh_lo, mesh_hi=args.host_mesh_hi,
        max_batch=args.host_max_batch, trace_dir=tempfile.gettempdir(),
    )
    for impl in ("python", "native"):
        log_line(out, arm=f"host_{impl}", **host[impl])

    def host_sum(st):
        # Trimmed bulk cost (batch_assembly + unpad) — the
        # outlier-robust committed estimator (see host_phase_ab).
        return (st.get("batch_assembly_trimmed_ms") or 0.0) + (
            st.get("unpad_trimmed_ms") or 0.0
        )

    host_before, host_after = host_sum(host["python"]), host_sum(host["native"])
    summary = log_line(
        out,
        summary="lowprec_ab",
        quick=bool(args.quick),
        parity_bar=PARITY_BAR,
        parity_max_delta=round(
            max(abs(r["delta"]) for r in parity), 6
        ),
        parity_datasets=[r["dataset"] for r in parity],
        replicas=args.replicas,
        slo_p99_ms=slo,
        sustained_rps_f32=best32["achieved_rps"] if best32 else None,
        sustained_rps_bf16=best16["achieved_rps"] if best16 else None,
        tokens_per_s_f32=best32["tokens_per_s"] if best32 else None,
        tokens_per_s_bf16=best16["tokens_per_s"] if best16 else None,
        p99_at_sustained_f32=best32["p99_ms"] if best32 else None,
        p99_at_sustained_bf16=best16["p99_ms"] if best16 else None,
        req_s_ratio=(
            round(best16["achieved_rps"] / best32["achieved_rps"], 3)
            if best32 and best16 and best32["achieved_rps"] else None
        ),
        # The design target (TPU MXU path) vs what THIS backend can
        # express — the microbench record beside it is the evidence.
        bar_req_s_ratio_target=1.3,
        bf16_dispatch_slowdown_cpu=micro["bf16_dispatch_slowdown"],
        cpu_proxy_note=(
            "bf16 dots upcast on this jaxlib CPU backend (no AMX "
            "path): the device-side bf16 term is a measured regression "
            "here, so the committed req_s_ratio reflects the CPU proxy "
            "floor, not the MXU design point"
        ),
        host_phase_trimmed_ms_python=round(host_before, 4),
        host_phase_trimmed_ms_native=round(host_after, 4),
        host_reduction_frac=(
            round(1.0 - host_after / host_before, 4) if host_before else None
        ),
        native_packer=native.status()["impl"],
    )
    print(
        f"lowprec_ab: parity max delta {summary['parity_max_delta']} "
        f"(bar {PARITY_BAR}); sustained f32 {summary['sustained_rps_f32']} "
        f"vs bf16 {summary['sustained_rps_bf16']} req/s "
        f"(ratio {summary['req_s_ratio']}); host phases (trimmed) "
        f"{summary['host_phase_trimmed_ms_python']} -> "
        f"{summary['host_phase_trimmed_ms_native']} ms "
        f"({summary['host_reduction_frac']} reduction)"
    )
    return summary


def main(argv=None) -> int:
    s = run(argv)
    ok = (
        s["parity_max_delta"] <= s["parity_bar"]
        and s["host_reduction_frac"] is not None
        and s["host_reduction_frac"] > 0
        and s["sustained_rps_bf16"] is not None
    )
    if not ok:
        print(f"FAIL: lowprec_ab bars not met: {s}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
