"""Cold-start A/B: replica scale-out 1→N, cold compiles vs deploy-time
AOT prewarm (docs/performance.md "Cold start").

Both arms run the same scenario: a router serving open-loop traffic on
ONE warmed replica scales out to N replicas mid-storm. The arms differ
only in how the new replicas become serve-ready:

* **cold** — each new replica warms the classical way
  (``EngineReplica.warm``): one real dispatch per bucket program, each
  paying a fresh trace + XLA compile (the compile cache points at an
  empty directory — a genuinely cold host).
* **prewarmed** — a deploy-time pass (``serve/aot.py
  prewarm_deployment``) compiled + snapshotted the whole program
  family for the target topology first; each new replica hydrates its
  executables from the snapshots (``prewarm_from``) — zero traces,
  zero compiles.

Measured per new replica: **time-to-first-served** (build → warm →
first probe request resolved ok, the serve-readiness latency a
scale-out or rolling reload pays), plus each arm's **shed count**
under the storm — a cold scale-out leaves one replica absorbing the
offered load for the whole compile window, so the queue overflows;
the prewarmed scale-out is capacity-complete before the queue fills.

The offered rate is calibrated against the measured single-replica
capacity (identically for both arms), so the storm genuinely overloads
one replica and a 4-replica pool genuinely absorbs it on any host.

Writes JSONL records (per-replica, per-arm, summary) —
``docs/artifacts/coldstart_ab.jsonl`` is the committed run, pinned by
``tests/test_artifacts.py::test_coldstart_ab_artifact_schema``:
prewarmed time-to-first-served >= 5x faster than cold, zero shed
during the prewarmed scale-out.

Usage::

    python tools/coldstart_ab.py --out docs/artifacts/coldstart_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _env_setup(n_replicas: int) -> None:
    """Virtual CPU devices + one intra-op thread per device BEFORE jax
    init — the serve_bench discipline (an N-replica CPU pool is only an
    honest hardware proxy when one dispatch cannot steal every core).
    No-op when jax is already imported (in-process quick smoke)."""
    if "jax" in sys.modules:
        print(
            "coldstart_ab: note — jax already imported; XLA flags "
            "unchanged (in-process smoke, not a measurement run)"
        )
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={max(8, n_replicas)}"
    if "xla_cpu_multi_thread_eigen" not in flags:
        flags += (
            " --xla_cpu_multi_thread_eigen=false"
            " intra_op_parallelism_threads=1"
        )
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_model(quick: bool):
    """The A/B model. The full run uses a config whose per-program XLA
    compile dominates tracing (the regime real deployments live in —
    on TPU the gap is 30-90 s per program); --quick shrinks it to the
    smoke model for the tier-1 sanity run."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import collate
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_params

    samples = datasets.synth_darcy2d(4, seed=0, grid_n=8)
    dim = 16 if quick else 256
    mc = ModelConfig(
        n_attn_layers=1 if quick else 3,
        n_attn_hidden_dim=dim,
        n_mlp_num_layers=1 if quick else 2,
        n_mlp_hidden_dim=dim,
        n_input_hidden_dim=dim,
        n_expert=2 if quick else 3,
        n_head=2 if quick else 4,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    return model, init_params(model, collate(samples), 0)


def _storm(router, traffic, offered_rps: float, stop: threading.Event):
    """Open-loop fixed-gap arrival thread (never throttled by
    responses). Returns (thread, futures) — start the thread, set
    ``stop``, join, then resolve."""
    futures = []

    def loop():
        gap = 1.0 / offered_rps
        i = 0
        nxt = time.perf_counter()
        while not stop.is_set():
            now = time.perf_counter()
            if now < nxt:
                time.sleep(min(gap, nxt - now))
                continue
            futures.append(router.submit(traffic[i % len(traffic)]))
            i += 1
            nxt += gap

    return threading.Thread(target=loop, daemon=True), futures


def measure_capacity(replica, traffic, *, max_batch: int) -> float:
    """Sustained req/s of ONE warmed replica measured THROUGH the real
    serving stack (router + batcher + worker), by overloading it
    open-loop and counting completions — both arms calibrate their
    offered load off this, so 'overload one replica' is true on any
    host."""
    from gnot_tpu.serve import ReplicaRouter

    router = ReplicaRouter(
        replicas=[replica],
        max_batch=max_batch,
        max_wait_ms=4.0,
        queue_limit=100_000,  # calibration never sheds; it saturates
    ).start()
    stop = threading.Event()
    thread, futures = _storm(router, traffic, 2000.0, stop)
    t0 = time.perf_counter()
    thread.start()
    time.sleep(2.5)
    stop.set()
    thread.join()
    results = [f.result(timeout=300) for f in futures]
    elapsed = time.perf_counter() - t0
    router.drain()
    return round(sum(r.ok for r in results) / elapsed, 1)


def run_arm(
    arm: str,
    *,
    model,
    params,
    traffic,
    n_replicas: int,
    max_batch: int,
    offered_rps: float,
    queue_limit: int,
    manifest=None,
) -> dict:
    """One scale-out scenario: router on replica 0, open-loop storm,
    scale out replicas 1..N-1 (cold warm vs snapshot hydration),
    measure per-replica time-to-first-served + arm shed counts."""
    import jax

    from gnot_tpu.serve import ReplicaRouter, build_replica

    devices = jax.devices()
    per = len(devices) // n_replicas

    def slice_of(i):
        return devices[i * per : (i + 1) * per]

    r0 = build_replica(model, params, 0, slice_of(0), batch_size=max_batch)
    if manifest is not None:
        r0.prewarm_from(manifest)
    else:
        r0.warm(traffic, rows=max_batch)
    router = ReplicaRouter(
        replicas=[r0],
        max_batch=max_batch,
        max_wait_ms=4.0,
        queue_limit=queue_limit,
    ).start()

    # Open-loop storm: fixed-gap arrivals, never throttled by
    # responses; runs until the scale-out completes.
    stop = threading.Event()
    storm_t, futures = _storm(router, traffic, offered_rps, stop)
    t_arm = time.perf_counter()
    storm_t.start()
    time.sleep(0.5)  # the pool runs overloaded before scale-out begins

    per_replica = []
    for i in range(1, n_replicas):
        t0 = time.perf_counter()
        r = build_replica(model, params, i, slice_of(i), batch_size=max_batch)
        if manifest is not None:
            r.prewarm_from(manifest)
        else:
            r.warm(traffic, rows=max_batch)
        router.add_replica(r)
        # Serve-readiness probe: first request on the NEW replica.
        probe = r.server.submit(traffic[0])
        res = probe.result(timeout=120)
        ttfs = time.perf_counter() - t0
        ws = r.warm_stats or {}
        per_replica.append(
            {
                "arm": arm,
                "replica": i,
                "ttfs_s": ttfs,
                "probe_ok": bool(res.ok),
                "warm_source": ws.get("source"),
                "programs": ws.get("programs"),
                "warm_seconds": ws.get("seconds"),
            }
        )
    scaleout_s = time.perf_counter() - t_arm
    stop.set()
    storm_t.join()
    results = [f.result(timeout=120) for f in futures]
    summary = router.drain()
    shed = sum(summary["shed"].values())
    arm_rec = {
        "arm": arm,
        "replicas": n_replicas,
        "offered_rps": offered_rps,
        "scaleout_s": scaleout_s,
        "submitted": len(results),
        "completed": sum(r.ok for r in results),
        "shed": dict(summary["shed"]),
        "shed_total": shed,
        "p50_ms": summary["latency_p50_ms"],
        "p99_ms": summary["latency_p99_ms"],
    }
    assert arm_rec["completed"] + shed >= arm_rec["submitted"], arm_rec
    return {"per_replica": per_replica, "arm": arm_rec}


def run(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--n_traffic", type=int, default=16)
    p.add_argument("--out", type=str, default="")
    p.add_argument(
        "--quick", action="store_true",
        help="tiny model, 2 replicas, no acceptance bars — the tier-1 "
             "smoke that the tool itself runs"
    )
    args = p.parse_args(argv)
    if args.quick:
        args.replicas = min(args.replicas, 2)
    _env_setup(args.replicas)

    from serve_smoke import mixed_traffic

    from gnot_tpu.serve import aot, build_replicas
    from gnot_tpu.utils.cache import enable_compile_cache

    model, params = build_model(args.quick)
    traffic = mixed_traffic(args.n_traffic)

    # --- calibration: one replica's capacity (shared by both arms) ----
    import jax

    cal_cache = tempfile.mkdtemp(prefix="coldstart_cal_cache_")
    enable_compile_cache(cal_cache)
    devices = jax.devices()
    per = len(devices) // args.replicas
    from gnot_tpu.serve import build_replica

    cal = build_replica(
        model, params, 0, devices[:per], batch_size=args.max_batch
    )
    cal.warm(traffic, rows=args.max_batch)
    capacity_1 = measure_capacity(cal, traffic, max_batch=args.max_batch)
    # Offered load overloads ONE replica by 50%; the queue bound sits
    # between the prewarmed scale-out's backlog peak (~0.6 x C1: the
    # overload only lasts until the first hydrated replica joins,
    # ~1 s) and the cold arm's (~3-4 x C1: one replica absorbs the
    # overload for the whole compile window) — so the cold arm sheds
    # and the prewarmed arm completes everything, with ~2x margins on
    # both sides on any host.
    offered = round(1.5 * capacity_1, 1)
    queue_limit = max(32, int(1.5 * capacity_1))

    records = []

    # --- cold arm: genuinely cold compile cache ----------------------------
    cold_cache = tempfile.mkdtemp(prefix="coldstart_cold_cache_")
    enable_compile_cache(cold_cache)
    cold = run_arm(
        "cold",
        model=model,
        params=params,
        traffic=traffic,
        n_replicas=args.replicas,
        max_batch=args.max_batch,
        offered_rps=offered,
        queue_limit=queue_limit,
    )

    # --- prewarmed arm: deploy-time AOT pass, then snapshot hydration ------
    warm_cache_dir = tempfile.mkdtemp(prefix="coldstart_warm_cache_")
    enable_compile_cache(warm_cache_dir)
    snap = tempfile.mkdtemp(prefix="coldstart_snap_")
    deploy_replicas = build_replicas(
        model, params, args.replicas, batch_size=args.max_batch
    )
    t0 = time.perf_counter()
    manifest = aot.prewarm_deployment(
        [(r.replica_id, r.engine) for r in deploy_replicas],
        traffic,
        rows=args.max_batch,
        snapshot_dir=snap,
    )
    records.append(
        {
            "arm": "deploy",
            "compile_s": manifest["compile_s"],
            "wall_s": time.perf_counter() - t0,
            "programs": len(manifest["program_keys"])
            * manifest["replicas"],
            "snapshot_bytes": manifest["snapshot_bytes"],
        }
    )
    warm = run_arm(
        "prewarmed",
        model=model,
        params=params,
        traffic=traffic,
        n_replicas=args.replicas,
        max_batch=args.max_batch,
        offered_rps=offered,
        queue_limit=queue_limit,
        manifest=manifest,
    )

    records.extend(cold["per_replica"] + [cold["arm"]])
    records.extend(warm["per_replica"] + [warm["arm"]])
    ttfs_cold = [r["ttfs_s"] for r in cold["per_replica"]]
    ttfs_warm = [r["ttfs_s"] for r in warm["per_replica"]]
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
    summary = {
        "summary": "coldstart_ab",
        "quick": bool(args.quick),
        "replicas_from": 1,
        "replicas_to": args.replicas,
        "capacity_1_rps": capacity_1,
        "offered_rps": offered,
        "ttfs_cold_s": mean(ttfs_cold),
        "ttfs_prewarmed_s": mean(ttfs_warm),
        "speedup": mean(ttfs_cold) / mean(ttfs_warm),
        "shed_cold": cold["arm"]["shed_total"],
        "shed_prewarmed": warm["arm"]["shed_total"],
        "bar_speedup": 5.0,
        "probe_ok": all(
            r["probe_ok"] for r in cold["per_replica"] + warm["per_replica"]
        ),
    }
    records.append(summary)

    failures = []
    if not summary["probe_ok"]:
        failures.append("a scale-out probe request did not serve ok")
    if summary["shed_prewarmed"] != 0:
        failures.append(
            f"prewarmed scale-out shed {summary['shed_prewarmed']} requests"
        )
    if not args.quick and summary["speedup"] < summary["bar_speedup"]:
        failures.append(
            f"speedup {summary['speedup']:.2f} below the "
            f"{summary['bar_speedup']}x bar"
        )

    if args.out:
        if d := os.path.dirname(args.out):
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    print(
        f"coldstart_ab: capacity_1={capacity_1} rps, offered={offered} "
        f"rps; TTFS cold={summary['ttfs_cold_s']:.2f}s vs "
        f"prewarmed={summary['ttfs_prewarmed_s']:.2f}s "
        f"({summary['speedup']:.1f}x); shed cold="
        f"{summary['shed_cold']} vs prewarmed={summary['shed_prewarmed']}"
    )
    for msg in failures:
        print(f"FAIL: {msg}")
    summary["failures"] = failures
    return summary


def main(argv=None) -> int:
    return 1 if run(argv)["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
