"""Donation-sanitizer overhead A/B: alias guard OFF vs COPY mode.

The acceptance bar for the runtime sanitizer (ISSUE 11,
docs/robustness.md "The donation sanitizer") is two-sided:

* **free when disabled** — with ``GNOT_ALIAS_GUARD`` unset,
  ``sanitizer.install()`` patches NOTHING. The *structural* claim is
  unit-proven (``test_off_mode_is_byte_identical``: ``jax.device_get``
  is the original function object, ``guard_donating(fn) is fn``), so
  the off arm runs literally the same machine code as the baseline —
  the A/B documents the measured equality within an honest noise
  window (|frac| <= 10% on a loaded shared box; a tight one-sided bar
  would just be betting on which way the wind blew that run);
* **bounded when on** — copy mode adds one host memcpy per
  ``device_get`` fetch (the supervisor-cadence snapshot in this
  bench), off the dispatch hot path: <=10% on the ns2d CPU micro-bench
  at snapshot_every=10.

Methodology: the telemetry/tracing A/B discipline — both arms run the
REAL hot path (jitted donating train step, rebind discipline, one
``jax.device_get(state.params)`` snapshot every ``snapshot_every``
steps mimicking the recovery supervisor), timed windows best-of-N with
a hard fetch at the end, arms INTERLEAVED so machine-load drift hits
both alike.

Usage::

    JAX_PLATFORMS=cpu python tools/sanitizer_ab.py \
        --steps 60 --repeats 3 --out docs/artifacts/sanitizer_overhead_ab.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(n_points: int, batch_size: int):
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_state, make_train_step

    samples = datasets.synth_ns2d(batch_size, n_points=n_points, seed=0)
    batch = next(iter(Loader(samples, batch_size)))
    mc = ModelConfig(
        n_attn_layers=2, n_attn_hidden_dim=128, n_mlp_num_layers=2,
        n_mlp_hidden_dim=128, n_input_hidden_dim=128, n_expert=3, n_head=4,
        **datasets.infer_model_dims(samples),
    )
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    step = make_train_step(model, optim, "rel_l2")
    return step, state, batch


def _window(step, state0, batch, steps: int, snapshot_every: int,
            copy_tree, lr) -> float:
    """One timed window: `steps` donating steps with a supervisor-style
    host snapshot every `snapshot_every` steps. The live guard mode
    (whatever sanitizer.install() left in place) applies to the
    device_get — that's the measured difference between arms."""
    from gnot_tpu.utils import sanitizer

    state = copy_tree(state0)
    step = sanitizer.guard_donating(step)
    state, loss = step(state, batch, lr)  # warm-up outside the window
    np.asarray(loss)
    snap = None
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        state, loss = step(state, batch, lr)
        if snapshot_every and i % snapshot_every == 0:
            snap = jax.device_get(state.params)
    np.asarray(loss)  # hard fetch: the window ends when the device does
    sec = (time.perf_counter() - t0) / steps
    del snap
    return sec


def time_ab(n_points: int, batch_size: int, steps: int,
            snapshot_every: int, repeats: int) -> dict[str, float]:
    """Best-of-`repeats` seconds/step for the three arms, interleaved:
    baseline (guard never installed), off (install() under an unset
    GNOT_ALIAS_GUARD — must be a no-op), copy (GNOT_ALIAS_GUARD=1)."""
    from gnot_tpu.utils import sanitizer

    step, state0, batch = build(n_points, batch_size)
    lr = jnp.asarray(1e-3, jnp.float32)
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))

    def set_mode(value: str | None):
        if value is None:
            os.environ.pop("GNOT_ALIAS_GUARD", None)
        else:
            os.environ["GNOT_ALIAS_GUARD"] = value
        sanitizer.install()

    best = {"baseline": float("inf"), "guard_off": float("inf"),
            "guard_copy": float("inf")}
    for _ in range(max(1, repeats)):
        # baseline: ensure no patch is live (same code path as a
        # process that never called install()).
        set_mode(None)
        best["baseline"] = min(
            best["baseline"],
            _window(step, state0, batch, steps, snapshot_every, copy_tree, lr),
        )
        set_mode(None)  # off arm: install() ran, patched nothing
        best["guard_off"] = min(
            best["guard_off"],
            _window(step, state0, batch, steps, snapshot_every, copy_tree, lr),
        )
        set_mode("1")
        best["guard_copy"] = min(
            best["guard_copy"],
            _window(step, state0, batch, steps, snapshot_every, copy_tree, lr),
        )
    set_mode(None)
    return best


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n_points", type=int, default=512)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--snapshot_every", type=int, default=10)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", type=str, default="")
    args = p.parse_args()

    platform = jax.devices()[0].platform
    best = time_ab(
        args.n_points, args.batch_size, args.steps, args.snapshot_every,
        args.repeats,
    )
    records = []
    for arm in ("baseline", "guard_off", "guard_copy"):
        records.append({
            "arm": arm, "ms_per_step": round(best[arm] * 1e3, 4),
            "platform": platform, "n_points": args.n_points,
            "batch_size": args.batch_size, "steps": args.steps,
            "snapshot_every": args.snapshot_every, "repeats": args.repeats,
        })
    base = records[0]["ms_per_step"]
    off = records[1]["ms_per_step"]
    copy = records[2]["ms_per_step"]
    records.append({
        "summary": "sanitizer_overhead", "config": "ns2d_micro",
        "ms_per_step_baseline": base, "ms_per_step_off": off,
        "ms_per_step_copy": copy,
        "off_vs_baseline_frac": round(off / base - 1.0, 4),
        "copy_overhead_frac": round(copy / base - 1.0, 4),
        "bar": (
            "|off_vs_baseline_frac| <= 0.10 (same machine code, noise "
            "window; byte-identity unit-proven by "
            "test_off_mode_is_byte_identical); "
            "copy_overhead_frac <= 0.10 at snapshot_every=10"
        ),
    })
    out = "\n".join(json.dumps(r) for r in records) + "\n"
    sys.stdout.write(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
