#!/usr/bin/env python
"""graftlint CLI: run the JAX-aware static-analysis suite.

Usage::

    python tools/lint.py gnot_tpu                 # lint the package
    python tools/lint.py gnot_tpu --format json   # machine-readable
    python tools/lint.py path/to/file.py --rules GL004

Exit status: 0 when clean, 1 when any finding survives suppressions,
2 on usage errors. Configuration lives in ``[tool.graftlint]`` in
pyproject.toml (docs/static_analysis.md); ``--rules`` narrows the run
to a comma-separated subset without touching the config.

Tier-1 wiring: ``tests/test_analysis.py::test_repo_tree_is_clean``
runs the same analysis in-process and asserts zero findings, so a new
violation anywhere in ``gnot_tpu/`` fails the suite — the same
mechanical gate FlashAttention-style kernel work needs around
correctness (ISSUE 4 motivation).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Import the analysis package WITHOUT executing gnot_tpu/__init__.py
# (which pulls jax/flax — a multi-second import the linter never
# needs). A namespace stub with the real __path__ lets the ordinary
# `gnot_tpu.analysis.*` imports resolve; the analysis modules are
# stdlib-only by design. Fine for this short-lived CLI process; the
# in-process path (tests) imports the real package instead.
if "gnot_tpu" not in sys.modules:
    import types

    _stub = types.ModuleType("gnot_tpu")
    _stub.__path__ = [os.path.join(_REPO_ROOT, "gnot_tpu")]
    sys.modules["gnot_tpu"] = _stub

from gnot_tpu.analysis import load_config, run_analysis  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="+", help="files or directories to analyze"
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="findings as human-readable lines or one JSON document",
    )
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule ids to run (default: config/all)",
    )
    parser.add_argument(
        "--root", default=_REPO_ROOT,
        help="repo root (pyproject.toml location; default: this repo)",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root)
    config = load_config(root)
    if args.rules:
        # An explicit --rules request overrides BOTH config lists — a
        # pyproject `disable` must not silently turn the run into a
        # zero-rule false-clean.
        config.enable = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        config.disable = []
    for p in args.paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    findings, stats = run_analysis(args.paths, root=root, config=config)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "stats": stats,
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        print(
            f"graftlint: {stats['findings']} finding(s) in "
            f"{stats['files']} file(s) "
            f"({stats['suppressed']} suppressed; rules: "
            f"{', '.join(stats['rules'])})"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
