#!/usr/bin/env python
"""graftlint CLI: run the JAX-aware static-analysis suite.

Usage::

    python tools/lint.py                          # lint config paths
    python tools/lint.py gnot_tpu --format json   # machine-readable
    python tools/lint.py path/to/file.py --rules GL004
    python tools/lint.py --changed                # pre-commit: diff-scoped
    python tools/lint.py --update-baseline        # refresh the baseline

Exit status: 0 when clean, 1 when any finding survives suppressions
(in ``--changed`` mode: any finding not covered by the committed
baseline), 2 on usage errors. Configuration lives in
``[tool.graftlint]`` in pyproject.toml (docs/static_analysis.md);
``--rules`` narrows the run to a comma-separated subset without
touching the config. Default paths come from the config's ``paths``
(gnot_tpu, tests, tools — every historical use-after-donate lived in
tests/).

``--changed`` reports findings only for the files git sees as
modified/added (working tree vs HEAD, plus untracked), so a pre-commit
hook stays quiet about the unchanged rest — the underlying analysis
still covers the full lint roots, because the donation call graph
(GL001/GL006) resolves donors cross-file and a diff-scoped parse would
be blind to them. Findings already recorded in
``tools/lint_baseline.json`` are tolerated (counted per ``(rule,
path)`` — line numbers shift under edits), anything NEW fails. The committed baseline is refreshed with
``--update-baseline`` from a FULL config-paths run, and tier-1's
``test_repo_tree_is_clean`` keeps the authoritative zero-findings bar
— the baseline can only mask what the gate already tolerates, which on
this tree is nothing.

Tier-1 wiring: ``tests/test_analysis.py::test_repo_tree_is_clean``
runs the same analysis in-process and asserts zero findings, so a new
violation anywhere in the configured paths fails the suite — the same
mechanical gate FlashAttention-style kernel work needs around
correctness (ISSUE 4 motivation).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Import the analysis package WITHOUT executing gnot_tpu/__init__.py
# (which pulls jax/flax — a multi-second import the linter never
# needs). A namespace stub with the real __path__ lets the ordinary
# `gnot_tpu.analysis.*` imports resolve; the analysis modules are
# stdlib-only by design. Fine for this short-lived CLI process; the
# in-process path (tests) imports the real package instead.
if "gnot_tpu" not in sys.modules:
    import types

    _stub = types.ModuleType("gnot_tpu")
    _stub.__path__ = [os.path.join(_REPO_ROOT, "gnot_tpu")]
    sys.modules["gnot_tpu"] = _stub

from gnot_tpu.analysis import load_config, run_analysis  # noqa: E402

BASELINE_PATH = os.path.join("tools", "lint_baseline.json")


def changed_files(root: str) -> list[str] | None:
    """Repo-relative files modified vs HEAD (staged + unstaged) plus
    untracked ones — ALL files, not just .py (a docs-only edit can
    cause a GL005 drift finding), or None when git is unavailable
    (the caller degrades to a full run — never a silent skip)."""
    out: set[str] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.update(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return sorted(out)


def load_baseline(root: str) -> dict[tuple[str, str], int]:
    """``(rule, path) -> tolerated count`` from the committed baseline
    (empty when the file is absent or unreadable — strict by default)."""
    counts: dict[tuple[str, str], int] = {}
    try:
        with open(os.path.join(root, BASELINE_PATH)) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return counts
    for rec in data.get("findings", []):
        key = (rec.get("rule", ""), rec.get("path", ""))
        counts[key] = counts.get(key, 0) + 1
    return counts


def subtract_baseline(findings, baseline: dict) -> tuple[list, int]:
    """Findings not covered by the baseline allowance, plus the number
    suppressed by it. Matched per ``(rule, path)`` with counts — line
    numbers move under unrelated edits and must not un-suppress."""
    remaining = dict(baseline)
    fresh = []
    masked = 0
    for f in findings:
        key = (f.rule, f.path)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            masked += 1
        else:
            fresh.append(f)
    return fresh, masked


def write_baseline(root: str, findings) -> str:
    path = os.path.join(root, BASELINE_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(
            {
                "version": 1,
                "note": (
                    "tolerated findings for tools/lint.py --changed; "
                    "refresh with --update-baseline. The tier-1 gate "
                    "(test_repo_tree_is_clean) stays authoritative."
                ),
                "findings": [fi.to_dict() for fi in findings],
            },
            f,
            indent=2,
        )
        f.write("\n")
    return path


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="*", default=[],
        help="files or directories to analyze (default: config `paths`)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="findings as human-readable lines or one JSON document",
    )
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule ids to run (default: config/all)",
    )
    parser.add_argument(
        "--root", default=_REPO_ROOT,
        help="repo root (pyproject.toml location; default: this repo)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only git-changed .py files; tolerate baseline findings",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help=f"full run over config paths, write {BASELINE_PATH}, exit 0",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root)
    config = load_config(root)
    if args.rules:
        # An explicit --rules request overrides BOTH config lists — a
        # pyproject `disable` must not silently turn the run into a
        # zero-rule false-clean.
        config.enable = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        config.disable = []
    if args.paths and (args.changed or args.update_baseline):
        print(
            "graftlint: --changed/--update-baseline choose their own "
            "paths; drop the positional arguments", file=sys.stderr,
        )
        return 2

    masked = 0
    if args.changed and not args.update_baseline:
        files = changed_files(root)
        scope = None
        if files is None:
            print(
                "graftlint: git unavailable; falling back to a full run",
                file=sys.stderr,
            )
        elif not files:
            print("graftlint: no changes vs HEAD")
            return 0
        else:
            # Per-file findings are gated only for changed .py files
            # under the configured roots — a scratch script outside
            # them is not gated at commit time either. Project-level
            # findings (GL005 registry/docs drift) bypass the scope:
            # a docs-only edit can cause them, and they anchor at
            # registry paths the diff may not touch.
            roots = tuple(p.rstrip("/") + "/" for p in config.paths)
            scope = {
                f for f in files
                if f.endswith(".py")
                and os.path.exists(os.path.join(root, f))
                and (f.startswith(roots) or f in config.paths)
            }
        # ALWAYS analyze the full lint roots: the donation call graph
        # (GL001/GL006) resolves donors cross-file — trainer.fit's
        # self.state donation must be visible to a changed test even
        # though trainer.py itself didn't change. The pure-AST scan is
        # ~1s over this tree; only the REPORTING is diff-scoped.
        findings, stats = run_analysis(
            list(config.paths), root=root, config=config
        )
        if scope is not None:
            findings = [
                f for f in findings
                if f.path in scope or f.project_level
            ]
        findings, masked = subtract_baseline(findings, load_baseline(root))
    else:
        paths = args.paths or list(config.paths)
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(root, p)
            if not os.path.exists(full):
                print(f"graftlint: no such path: {p}", file=sys.stderr)
                return 2
        findings, stats = run_analysis(paths, root=root, config=config)

    if args.update_baseline:
        path = write_baseline(root, findings)
        print(
            f"graftlint: baseline written to {path} "
            f"({len(findings)} finding(s))"
        )
        return 0

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    # stats["findings"] is the pre-scope full-run
                    # count; re-pin it to what this invocation actually
                    # reports so exit code, array, and count agree.
                    "stats": {
                        **stats,
                        "findings": len(findings),
                        "baseline_masked": masked,
                    },
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        extra = f", {masked} baseline-masked" if masked else ""
        print(
            f"graftlint: {len(findings)} finding(s) in "
            f"{stats['files']} file(s) "
            f"({stats['suppressed']} suppressed{extra}; rules: "
            f"{', '.join(stats['rules'])})"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
