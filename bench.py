"""Benchmark: mesh-points/sec/chip on NS2d-1k (BASELINE.json metric).

Runs the full jitted training step (forward + backward + AdamW) on the
default JAX platform (the TPU chip under the driver) at the
reference-default architecture on the NS2d ~1k-point config, counting
REAL (unpadded) mesh points per second per chip. ``vs_baseline`` is the
TPU/CPU speedup ratio; the BASELINE.md gate wants >= 8. Two baseline
divisors are available via ``--baseline``:

* ``jax`` (default): the same jitted step on the host CPU backend in
  float32 — a hardware-for-hardware ratio of this framework;
* ``torch``: the reference PyTorch implementation in CPU eager mode
  (its actual design point, ``/root/reference/main.py:27``) doing the
  same forward + backward + AdamW on the same batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp


def build_data(step_dtype: str, n_points: int, batch_size: int, config: str, attention_impl: str = "xla", ffn_impl: str = "xla", remat: bool = False):
    """One padded batch + the reference-default ModelConfig
    (main.py:16-22) for the given workload — no jax state."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader

    # Size knobs per synthetic generator; darcy2d is a square grid, so
    # n_points maps to the nearest grid edge (pass 4096 for the
    # BASELINE configs[0] 64x64 grid).
    gen_kwargs = {
        "ns2d": {"n_points": n_points},
        "darcy2d": {"grid_n": max(2, int(n_points**0.5))},
        "elasticity": {"base_points": n_points},
        "inductor2d": {"base_points": n_points},
        "heatsink3d": {"base_points": n_points},
    }[config]
    samples = datasets.SYNTHETIC[config](batch_size, seed=0, **gen_kwargs)
    mc = ModelConfig(
        dtype=step_dtype,
        attention_impl=attention_impl,
        ffn_impl=ffn_impl,
        remat=remat,
        **datasets.infer_model_dims(samples),
    )
    return next(iter(Loader(samples, batch_size))), mc


def build(step_dtype: str, attention_impl: str = "xla", n_points: int = 1024, batch_size: int = 4, ffn_impl: str = "xla", config: str = "ns2d", remat: bool = False):
    from gnot_tpu.config import OptimConfig
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_state, make_train_step

    batch, mc = build_data(
        step_dtype, n_points, batch_size, config, attention_impl, ffn_impl, remat
    )
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    step = make_train_step(model, optim, "rel_l2")
    return step, state, batch, mc


def time_steps(
    step, state, batch, lr, n_warmup: int, n_steps: int, device,
    fused: bool = False, repeats: int = 1,
) -> float:
    """Returns real-mesh-points/sec for the train step on `device`,
    best of ``repeats`` timed windows (dispatch/tunnel stalls only ever
    subtract from measured throughput, so best-of-N is the faithful
    estimator of device capability).

    ``fused=True`` compiles the n_steps iterations into ONE program
    (lax.scan over the step), so the measurement contains zero per-step
    host dispatch — the robust mode when the device sits behind a
    remote tunnel whose per-call latency varies. Default off: the
    per-step loop is what training actually does."""
    dbatch = jax.device_put(batch, device)
    lr = jax.device_put(lr, device)
    multi = None
    if fused:

        @functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
        def multi(state, b, lr, n):
            def body(s, _):
                s, loss = step(s, b, lr)
                return s, loss

            state, losses = jax.lax.scan(body, state, None, length=n)
            return state, losses[-1]

    # One compiled whole-tree copy (leaf-wise host loops would pay one
    # device round-trip per leaf, per window).
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    best = 0.0
    for i in range(max(1, repeats)):
        # Fresh copy per window: the jitted step/multi donates its
        # state argument.
        s = jax.device_put(copy_tree(state), device)
        if fused:
            if i == 0:
                # Warm with the SAME static length the timed call uses
                # — a different length would be a different compiled
                # program, and the compile would land inside the timed
                # region. Later windows reuse the compiled executable.
                s, loss = multi(s, dbatch, lr, n_steps)
                jax.block_until_ready(loss)
            t0 = time.perf_counter()
            s, loss = multi(s, dbatch, lr, n_steps)
        else:
            # Full warmup in window 0 (first call compiles); later
            # windows need only one priming step for residency.
            for _ in range(max(1, n_warmup) if i == 0 else 1):
                s, loss = step(s, dbatch, lr)
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(n_steps):
                s, loss = step(s, dbatch, lr)
        jax.block_until_ready(loss)
        best = max(best, batch.n_real_points * n_steps / (time.perf_counter() - t0))
    return best


def time_torch_steps(batch, mc, lr: float, n_warmup: int, n_steps: int) -> float:
    """Real-mesh-points/sec for the reference torch model's train step
    (CPU eager, f32 — the reference regime, main.py:27,50-52,98-103)."""
    import torch

    from gnot_tpu.interop.torch_oracle import build_reference_model, torch_rel_l2

    torch.manual_seed(0)
    model = build_reference_model(mc)
    opt = torch.optim.AdamW(model.parameters(), lr=lr)
    coords = torch.from_numpy(batch.coords)
    theta = torch.from_numpy(batch.theta)
    funcs = [torch.from_numpy(f) for f in batch.funcs] if batch.funcs is not None else None
    y = torch.from_numpy(batch.y)
    mask = torch.from_numpy(batch.node_mask)

    def one_step():
        loss = torch_rel_l2(model(coords, theta, funcs), y, mask)
        opt.zero_grad()
        loss.backward()
        opt.step()

    for _ in range(n_warmup):
        one_step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        one_step()
    dt = time.perf_counter() - t0
    return batch.n_real_points * n_steps / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument(
        "--repeats", type=int, default=3,
        help="timed repetitions; the REPORTED value is the best one. "
             "Dispatch/tunnel stalls only ever subtract from measured "
             "throughput, so best-of-N is the faithful estimator of "
             "device capability (the standard benchmarking practice)"
    )
    p.add_argument(
        "--fused_steps", action="store_true",
        help="compile the timed steps into one lax.scan program (no "
             "per-step host dispatch in the measurement). Trustworthy "
             "on LOCAL devices only: remote-tunnel backends have been "
             "observed returning from block_until_ready before scanned "
             "programs finish, yielding impossibly high numbers"
    )
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument(
        "--cpu_steps", type=int, default=10,
        help="baseline-divisor sample size (0 skips the baseline run)"
    )
    p.add_argument(
        "--baseline", type=str, default="jax", choices=["jax", "torch"],
        help="divisor for vs_baseline: this framework's step on the host "
             "CPU (jax) or the reference PyTorch eager step (torch)"
    )
    p.add_argument("--dtype", type=str, default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--attention_impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--ffn_impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--n_points", type=int, default=1024)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument(
        "--config", type=str, default="ns2d",
        choices=["ns2d", "darcy2d", "elasticity", "inductor2d", "heatsink3d"],
        help="benchmark config; the headline metric is ns2d"
    )
    p.add_argument("--remat", action="store_true", help="rematerialized backward")
    p.add_argument(
        "--mem_stats", action="store_true",
        help="also print the device's peak-memory stats as JSON on stderr "
             "(keeps the stdout one-line contract)"
    )
    args = p.parse_args()

    lr = jnp.asarray(1e-3, jnp.float32)
    accel = jax.devices()[0]
    cpu = jax.devices("cpu")[0]

    step, state, batch, _ = build(
        args.dtype, args.attention_impl, args.n_points, args.batch_size,
        args.ffn_impl, args.config, args.remat,
    )
    value = time_steps(
        step, state, batch, lr, args.warmup, args.steps, accel,
        fused=args.fused_steps, repeats=args.repeats,
    )
    if args.mem_stats:
        import sys

        stats = accel.memory_stats() or {}
        mem = {
            k: stats.get(k)
            for k in ("peak_bytes_in_use", "bytes_in_use", "largest_alloc_size")
        }
        if not any(mem.values()):
            # Devices behind remote tunnels expose no allocator stats;
            # report the compiled step's static memory analysis instead
            # (lower() only needs avals, so donated buffers are fine).
            ma = step.lower(state, batch, lr).compile().memory_analysis()
            mem = {
                "temp_size_bytes": ma.temp_size_in_bytes,
                "argument_size_bytes": ma.argument_size_in_bytes,
                "output_size_bytes": ma.output_size_in_bytes,
            }
        print(json.dumps(mem), file=sys.stderr)

    if accel.platform == "cpu" or args.cpu_steps == 0:
        vs_baseline = 1.0
    else:
        # f32 CPU baseline — the reference's numeric regime — at the
        # SAME workload, so vs_baseline is purely a hardware ratio.
        # Best-of-N on the baseline too — an asymmetric estimator would
        # bias vs_baseline upward.
        if args.baseline == "torch":
            batch_c, mc_c = build_data(
                "float32", args.n_points, args.batch_size, args.config
            )
            # warmup=1 every window: each call builds a fresh model, so
            # its first step (grad-buffer allocation) must stay out of
            # the timed region in every window, not just the first.
            cpu_value = max(
                time_torch_steps(batch_c, mc_c, 1e-3, 1, args.cpu_steps)
                for _ in range(max(1, args.repeats))
            )
        else:
            step_c, state_c, batch_c, _ = build(
                "float32", "xla", args.n_points, args.batch_size, config=args.config
            )
            cpu_value = time_steps(
                step_c, state_c, batch_c, lr, 1, args.cpu_steps, cpu,
                repeats=args.repeats,
            )
        vs_baseline = value / cpu_value

    print(
        json.dumps(
            {
                "metric": f"{args.config}_mesh_points_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "points/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
