"""Benchmark: mesh-points/sec/chip on NS2d-1k (BASELINE.json metric).

Runs the full jitted training step (forward + backward + AdamW) on the
default JAX platform (the TPU chip under the driver) at the
reference-default architecture on the NS2d ~1k-point config, counting
REAL (unpadded) mesh points per second per chip. The baseline divisor is
the same step measured on the host CPU backend in float32 — the
reference's design point (torch CPU/GPU eager, f32) — so
``vs_baseline`` is the TPU/CPU speedup ratio; the BASELINE.md gate wants
>= 8.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp


def build(step_dtype: str, attention_impl: str = "xla", n_points: int = 1024, batch_size: int = 4, ffn_impl: str = "xla", config: str = "ns2d"):
    from gnot_tpu.config import ModelConfig, OptimConfig
    from gnot_tpu.data import datasets
    from gnot_tpu.data.batch import Loader
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import init_state, make_train_step

    # Size knobs per synthetic generator; darcy2d is a square grid, so
    # n_points maps to the nearest grid edge (pass 4096 for the
    # BASELINE configs[0] 64x64 grid).
    gen_kwargs = {
        "ns2d": {"n_points": n_points},
        "darcy2d": {"grid_n": max(2, int(n_points**0.5))},
        "elasticity": {"base_points": n_points},
        "inductor2d": {"base_points": n_points},
        "heatsink3d": {"base_points": n_points},
    }[config]
    samples = datasets.SYNTHETIC[config](batch_size, seed=0, **gen_kwargs)
    mc = ModelConfig(
        dtype=step_dtype,
        attention_impl=attention_impl,
        ffn_impl=ffn_impl,
        **datasets.infer_model_dims(samples),
    )  # reference-default architecture (main.py:16-22)
    batch = next(iter(Loader(samples, batch_size)))
    model = GNOT(mc)
    optim = OptimConfig()
    state = init_state(model, optim, batch, seed=0)
    step = make_train_step(model, optim, "rel_l2")
    return step, state, batch


def time_steps(step, state, batch, lr, n_warmup: int, n_steps: int, device) -> float:
    """Returns real-mesh-points/sec for the train step on `device`."""
    state = jax.device_put(state, device)
    dbatch = jax.device_put(batch, device)
    lr = jax.device_put(lr, device)
    for _ in range(n_warmup):
        state, loss = step(state, dbatch, lr)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, dbatch, lr)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return batch.n_real_points * n_steps / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--cpu_steps", type=int, default=3)
    p.add_argument("--dtype", type=str, default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--attention_impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--ffn_impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--n_points", type=int, default=1024)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument(
        "--config", type=str, default="ns2d",
        choices=["ns2d", "darcy2d", "elasticity", "inductor2d", "heatsink3d"],
        help="benchmark config; the headline metric is ns2d"
    )
    args = p.parse_args()

    lr = jnp.asarray(1e-3, jnp.float32)
    accel = jax.devices()[0]
    cpu = jax.devices("cpu")[0]

    step, state, batch = build(
        args.dtype, args.attention_impl, args.n_points, args.batch_size,
        args.ffn_impl, args.config,
    )
    value = time_steps(step, state, batch, lr, args.warmup, args.steps, accel)

    if accel.platform == "cpu" or args.cpu_steps == 0:
        vs_baseline = 1.0
    else:
        # CPU baseline in f32 — the reference's numeric regime — at the
        # SAME workload, so vs_baseline is purely a hardware ratio.
        step_c, state_c, batch_c = build(
            "float32", "xla", args.n_points, args.batch_size, config=args.config
        )
        cpu_value = time_steps(step_c, state_c, batch_c, lr, 1, args.cpu_steps, cpu)
        vs_baseline = value / cpu_value

    print(
        json.dumps(
            {
                "metric": f"{args.config}_mesh_points_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "points/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
