"""Benchmark: mesh-points/sec/chip on NS2d-1k (BASELINE.json metric).

Runs the full jitted training step (forward + backward + AdamW) on the
default JAX platform (the TPU chip under the driver) at the
reference-default architecture on the NS2d ~1k-point config, counting
REAL (unpadded) mesh points per second per chip. ``vs_baseline`` is the
TPU/CPU speedup ratio; the BASELINE.md gate wants >= 8. Two baseline
divisors are available via ``--baseline``:

* ``jax`` (default): the same jitted step on the host CPU backend in
  float32 — a hardware-for-hardware ratio of this framework;
* ``torch``: the reference PyTorch implementation in CPU eager mode
  (its actual design point, ``/root/reference/main.py:27``) doing the
  same forward + backward + AdamW on the same batch.

Timing methodology (``--timing``):

* ``scan_marginal`` (default on accelerators) — time K-step
  ``lax.scan`` programs at TWO lengths and report the marginal
  ms/step from the difference. The constant dispatch round-trip
  cancels exactly, and each window ends in a HARD FETCH
  (``np.asarray`` of the last loss and a param leaf) rather than
  ``block_until_ready`` — which has been observed returning early on
  remote-tunnel platforms (axon), historically inflating per-step
  window numbers by >2x (docs/performance.md "Methodology"). The
  scanned step is the same ``train_step_body`` math
  (tests/test_trainer.py::test_multi_step_dispatch_matches_single_steps
  pins K scanned steps == K individual steps), and scan-of-K vs K
  dispatches measure within 12% of each other on a locally-attached
  CPU, so the marginal is the per-step device time, not a
  scan-artifact.
* ``persstep`` — the classic dispatch-per-step loop (default on CPU,
  where the host IS the device and block_until_ready is trustworthy).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...};
extra keys report ms/step, achieved TFLOP/s (from the compiled step's
XLA cost analysis), and MFU against the chip's peak for the compute
dtype.
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# Peak dense-matmul throughput by (device_kind prefix, compute dtype),
# FLOP/s. Sources: v5e 197 bf16 TFLOP/s and v4 275 bf16 TFLOP/s are
# the published per-chip peaks (Google Cloud TPU system-architecture
# tables; same figures in jax-ml.github.io/scaling-book ch.2). The f32
# rows are estimates — the MXU natively multiplies bf16 operands and
# f32 runs multi-pass at roughly 1/4 rate — and were cross-checked on
# this host by tools/honest_probe.py reading a 4096^3 matmul at 194
# bf16 TFLOP/s (~98% of the table's 197). Unknown devices report
# mfu = None (a notice goes to stderr — see peak_flops()).
PEAK_FLOPS = {
    ("TPU v5 lite", "bfloat16"): 197e12,
    ("TPU v5 lite", "float32"): 49e12,
    ("TPU v5e", "bfloat16"): 197e12,
    ("TPU v5e", "float32"): 49e12,
    ("TPU v4", "bfloat16"): 275e12,
    ("TPU v4", "float32"): 69e12,
}


def _gen_samples(config: str, n_points: int, batch_size: int):
    """Synthetic samples for a bench config — THE one size mapping
    (darcy2d is a square grid, so n_points maps to the nearest grid
    edge; pass 4096 for the BASELINE configs[0] 64x64 grid). Both the
    padded and the packed builders draw from here so A/Bs compare the
    same samples."""
    from gnot_tpu.data import datasets

    gen_kwargs = {
        "ns2d": {"n_points": n_points},
        "darcy2d": {"grid_n": max(2, int(n_points**0.5))},
        "elasticity": {"base_points": n_points},
        "inductor2d": {"base_points": n_points},
        "heatsink3d": {"base_points": n_points},
    }[config]
    return datasets.SYNTHETIC[config](batch_size, seed=0, **gen_kwargs)


def _model_config(samples, step_dtype: str, attention_impl: str, ffn_impl: str, remat: bool, model_overrides: dict | None):
    """THE one bench ModelConfig construction (padded and packed
    builders both call it, so A/Bs benchmark the same model)."""
    from gnot_tpu.config import ModelConfig
    from gnot_tpu.data import datasets

    return ModelConfig(
        dtype=step_dtype,
        attention_impl=attention_impl,
        ffn_impl=ffn_impl,
        remat=remat,
        **datasets.infer_model_dims(samples),
        **(model_overrides or {}),
    )


def build_data(step_dtype: str, n_points: int, batch_size: int, config: str, attention_impl: str = "xla", ffn_impl: str = "xla", remat: bool = False, model_overrides: dict | None = None):
    """One padded batch + the reference-default ModelConfig
    (main.py:16-22) for the given workload — no jax state.
    ``model_overrides`` replaces ModelConfig fields (e.g. a deeper
    ``n_attn_layers`` for layout A/Bs)."""
    from gnot_tpu.data.batch import Loader

    samples = _gen_samples(config, n_points, batch_size)
    mc = _model_config(
        samples, step_dtype, attention_impl, ffn_impl, remat, model_overrides
    )
    return next(iter(Loader(samples, batch_size))), mc


def build(step_dtype: str, attention_impl: str = "xla", n_points: int = 1024, batch_size: int = 4, ffn_impl: str = "xla", config: str = "ns2d", remat: bool = False, flat_params: bool = False, model_overrides: dict | None = None, packed: bool = False, pack_chunk: int = 128):
    from gnot_tpu.config import OptimConfig
    from gnot_tpu.models.gnot import GNOT
    from gnot_tpu.train.trainer import (
        flat_loss_fn,
        init_flat_state,
        init_state,
        make_train_step,
        packed_loss_fn,
    )

    if packed and flat_params:
        raise ValueError(
            "packed + flat_params not composed (the Trainer rejects the "
            "combination too); pick one"
        )
    if packed:
        # "Pack, don't pad": ONE packed dispatch (multiple segments per
        # row) from the same sample generator the padded path uses —
        # pts/s stays comparable because the metric counts REAL points
        # either way. No padded Loader is built on this path.
        from gnot_tpu.data.batch import PackedLoader

        samples = _gen_samples(config, n_points, batch_size)
        batch = PackedLoader(samples, batch_size, chunk=pack_chunk).probe_batch()
        mc = _model_config(
            samples, step_dtype, attention_impl, ffn_impl, remat, model_overrides
        )
    else:
        batch, mc = build_data(
            step_dtype, n_points, batch_size, config, attention_impl, ffn_impl,
            remat, model_overrides,
        )
    model = GNOT(mc)
    optim = OptimConfig(flat_params=flat_params)
    if flat_params:
        state, unravel = init_flat_state(model, optim, batch, seed=0)
        step = make_train_step(
            model, optim, "rel_l2",
            loss_fn=flat_loss_fn(model, unravel, "rel_l2"),
        )
    elif packed:
        state = init_state(model, optim, batch, seed=0)
        step = make_train_step(
            model, optim, "rel_l2", loss_fn=packed_loss_fn(model, "rel_l2")
        )
    else:
        state = init_state(model, optim, batch, seed=0)
        step = make_train_step(model, optim, "rel_l2")
    return step, state, batch, mc


def _hard_sync(state, loss) -> None:
    """Force completion with real device->host transfers. On remote
    tunnels, ``block_until_ready`` has been observed returning before
    the program finishes; a data fetch cannot lie. The param fetch is
    ONE element sliced on-device — fetching the whole leaf would ship
    it through the tunnel (the flat [P] layout's first leaf is the
    entire ~37 MB param buffer, which once cost ~7 s per timed window
    and buried the marginal under transfer noise)."""
    np.asarray(loss)
    np.asarray(jax.tree.leaves(state.params)[0].ravel()[0])


def _scan_program(step):
    @functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
    def multi(state, b, lr, n):
        def body(s, _):
            s, loss = step(s, b, lr)
            return s, loss

        state, losses = jax.lax.scan(body, state, None, length=n)
        return state, losses[-1]

    return multi


def time_scan_marginal(
    step, state, batch, lr, device, k1: int, k2: int, repeats: int,
    max_retries: int = 3,
) -> float:
    """Marginal seconds/step from K-step scanned programs at two
    lengths: (T(k2) - T(k1)) / (k2 - k1). Constant dispatch / tunnel
    round-trip latency cancels in the difference; each window is
    best-of-``repeats`` (stalls only ever add time). Transient tunnel
    errors retry up to ``max_retries`` times per window before the
    last one propagates."""
    if k2 <= k1:
        raise ValueError(f"need k2 > k1, got k1={k1} k2={k2}")
    dbatch = jax.device_put(batch, device)
    lr = jax.device_put(lr, device)
    multi = _scan_program(step)
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    t = {}
    for k in (k1, k2):
        best = float("inf")
        for w in range(max(1, repeats)):
            for attempt in range(max_retries):
                try:
                    s = jax.device_put(copy_tree(state), device)
                    if w == 0:
                        # Compile this K outside the timed region.
                        s2, loss = multi(s, dbatch, lr, k)
                        _hard_sync(s2, loss)
                        s = jax.device_put(copy_tree(state), device)
                    t0 = time.perf_counter()
                    s, loss = multi(s, dbatch, lr, k)
                    _hard_sync(s, loss)
                    best = min(best, time.perf_counter() - t0)
                    break
                except Exception:
                    if attempt == max_retries - 1:
                        raise
        t[k] = best
    if t[k2] <= t[k1]:
        # Timing noise swallowed the marginal (workload too small for
        # the window sizes): a non-positive estimate would make
        # points/sec, achieved TFLOP/s and MFU negative or infinite.
        raise RuntimeError(
            f"scan-marginal degenerate: T(k2={k2})={t[k2]:.4f}s <= "
            f"T(k1={k1})={t[k1]:.4f}s — increase --k2/--repeats or use "
            "--timing persstep for this workload"
        )
    return (t[k2] - t[k1]) / (k2 - k1)


def time_steps(
    step, state, batch, lr, n_warmup: int, n_steps: int, device,
    repeats: int = 1,
) -> float:
    """Per-step dispatch loop: seconds/step, best of ``repeats`` timed
    windows. Trustworthy on locally-attached devices; through a remote
    tunnel the dispatch queue hides execution and the end-of-loop sync
    under-reports — use the scan_marginal mode there."""
    dbatch = jax.device_put(batch, device)
    lr = jax.device_put(lr, device)
    # One compiled whole-tree copy (leaf-wise host loops would pay one
    # device round-trip per leaf, per window).
    copy_tree = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    best = float("inf")
    for i in range(max(1, repeats)):
        # Fresh copy per window: the jitted step donates its state.
        s = jax.device_put(copy_tree(state), device)
        for _ in range(max(1, n_warmup) if i == 0 else 1):
            s, loss = step(s, dbatch, lr)
        _hard_sync(s, loss)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            s, loss = step(s, dbatch, lr)
        _hard_sync(s, loss)
        best = min(best, (time.perf_counter() - t0) / n_steps)
    return best


def time_torch_steps(batch, mc, lr: float, n_warmup: int, n_steps: int) -> float:
    """Seconds/step for the reference torch model's train step
    (CPU eager, f32 — the reference regime, main.py:27,50-52,98-103)."""
    import torch

    from gnot_tpu.interop.torch_oracle import build_reference_model, torch_rel_l2

    torch.manual_seed(0)
    model = build_reference_model(mc)
    opt = torch.optim.AdamW(model.parameters(), lr=lr)
    coords = torch.from_numpy(batch.coords)
    theta = torch.from_numpy(batch.theta)
    funcs = [torch.from_numpy(f) for f in batch.funcs] if batch.funcs is not None else None
    y = torch.from_numpy(batch.y)
    mask = torch.from_numpy(batch.node_mask)

    def one_step():
        loss = torch_rel_l2(model(coords, theta, funcs), y, mask)
        opt.zero_grad()
        loss.backward()
        opt.step()

    for _ in range(n_warmup):
        one_step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        one_step()
    return (time.perf_counter() - t0) / n_steps


def step_flops(step, state, batch, lr) -> float | None:
    """FLOPs of one compiled training step from XLA's cost analysis."""
    try:
        ca = step.lower(state, batch, lr).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(ca["flops"])
    except Exception:
        return None


def peak_flops(device, dtype: str) -> float | None:
    kind = getattr(device, "device_kind", "")
    for (prefix, dt), peak in PEAK_FLOPS.items():
        if kind.startswith(prefix) and dt == dtype:
            return peak
    import sys

    print(
        f"bench: no peak-FLOPs entry for device_kind={kind!r} dtype={dtype!r}"
        " — mfu will be null (extend PEAK_FLOPS to enable it)",
        file=sys.stderr,
    )
    return None


def main():
    p = argparse.ArgumentParser()
    p.add_argument(
        "--timing", type=str, default="auto",
        choices=["auto", "scan_marginal", "persstep"],
        help="auto: scan_marginal on accelerators (immune to dispatch/"
             "tunnel latency AND to the early-returning block_until_ready "
             "observed on remote platforms), persstep on CPU"
    )
    p.add_argument("--k1", type=int, default=25, help="short scan window")
    p.add_argument("--k2", type=int, default=100, help="long scan window")
    p.add_argument("--steps", type=int, default=20, help="persstep window size")
    p.add_argument(
        "--repeats", type=int, default=3,
        help="timed repetitions per window; the reported value uses the "
             "best (stalls only ever subtract from measured throughput)"
    )
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument(
        "--cpu_steps", type=int, default=10,
        help="baseline-divisor sample size (0 skips the baseline run)"
    )
    p.add_argument(
        "--baseline", type=str, default="jax", choices=["jax", "torch"],
        help="divisor for vs_baseline: this framework's step on the host "
             "CPU (jax) or the reference PyTorch eager step (torch)"
    )
    p.add_argument("--dtype", type=str, default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--attention_impl", type=str, default="xla", choices=["xla"])
    p.add_argument("--ffn_impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--n_points", type=int, default=1024)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument(
        "--config", type=str, default="ns2d",
        choices=["ns2d", "darcy2d", "elasticity", "inductor2d", "heatsink3d"],
        help="benchmark config; the headline metric is ns2d"
    )
    p.add_argument("--remat", action="store_true", help="rematerialized backward")
    p.add_argument(
        "--flat_params", action="store_true",
        help="flat [P]-vector parameter/optimizer layout (fused AdamW "
             "update — docs/performance.md)"
    )
    p.add_argument(
        "--packed", action="store_true",
        help="packed ragged batching ('pack, don't pad' — multiple "
             "samples per row as chunk-aligned segments)"
    )
    p.add_argument("--pack_chunk", type=int, default=128,
                   help="packed segment alignment (tokens)")
    p.add_argument(
        "--mem_stats", action="store_true",
        help="also print the device's peak-memory stats as JSON on stderr "
             "(keeps the stdout one-line contract)"
    )
    p.add_argument(
        "--metrics_path", type=str, default="",
        help="also append the result record to this JSONL file in the "
             "trainer's MetricsSink schema (plus a run.json manifest "
             "next to it), so one report tool reads bench and training "
             "runs alike"
    )
    args = p.parse_args()

    lr = jnp.asarray(1e-3, jnp.float32)
    accel = jax.devices()[0]
    cpu = jax.devices("cpu")[0]
    timing = args.timing
    if timing == "auto":
        timing = "persstep" if accel.platform == "cpu" else "scan_marginal"

    step, state, batch, _ = build(
        args.dtype, args.attention_impl, args.n_points, args.batch_size,
        args.ffn_impl, args.config, args.remat, args.flat_params,
        packed=args.packed, pack_chunk=args.pack_chunk,
    )
    if timing == "scan_marginal":
        sec_per_step = time_scan_marginal(
            step, state, batch, lr, accel, args.k1, args.k2, args.repeats
        )
    else:
        sec_per_step = time_steps(
            step, state, batch, lr, args.warmup, args.steps, accel,
            repeats=args.repeats,
        )
    value = batch.n_real_points / sec_per_step

    flops = step_flops(step, state, batch, lr)
    achieved = flops / sec_per_step if flops else None
    peak = peak_flops(accel, args.dtype)
    mfu = achieved / peak if (achieved and peak) else None

    if args.mem_stats:
        import sys

        stats = accel.memory_stats() or {}
        mem = {
            k: stats.get(k)
            for k in ("peak_bytes_in_use", "bytes_in_use", "largest_alloc_size")
        }
        if not any(mem.values()):
            # Devices behind remote tunnels expose no allocator stats;
            # report the compiled step's static memory analysis instead
            # (lower() only needs avals, so donated buffers are fine).
            ma = step.lower(state, batch, lr).compile().memory_analysis()
            mem = {
                "temp_size_bytes": ma.temp_size_in_bytes,
                "argument_size_bytes": ma.argument_size_in_bytes,
                "output_size_bytes": ma.output_size_in_bytes,
            }
        print(json.dumps(mem), file=sys.stderr)

    if accel.platform == "cpu" or args.cpu_steps == 0:
        vs_baseline = 1.0
    else:
        # f32 CPU baseline — the reference's numeric regime — at the
        # SAME workload, so vs_baseline is purely a hardware ratio.
        # Best-of-N on the baseline too — an asymmetric estimator would
        # bias vs_baseline upward.
        if args.baseline == "torch":
            batch_c, mc_c = build_data(
                "float32", args.n_points, args.batch_size, args.config
            )
            # warmup=1 every window: each call builds a fresh model, so
            # its first step (grad-buffer allocation) must stay out of
            # the timed region in every window, not just the first.
            cpu_sec = min(
                time_torch_steps(batch_c, mc_c, 1e-3, 1, args.cpu_steps)
                for _ in range(max(1, args.repeats))
            )
        else:
            step_c, state_c, batch_c, _ = build(
                "float32", "xla", args.n_points, args.batch_size, config=args.config
            )
            cpu_sec = time_steps(
                step_c, state_c, batch_c, lr, 1, args.cpu_steps, cpu,
                repeats=args.repeats,
            )
        cpu_value = batch_c.n_real_points / cpu_sec
        vs_baseline = value / cpu_value

    result = {
        "metric": f"{args.config}_mesh_points_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "points/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
        "ms_per_step": round(sec_per_step * 1e3, 4),
        "flops_per_step": flops,
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "timing": timing,
        "dtype": args.dtype,
    }
    print(json.dumps(result))
    if args.metrics_path:
        # Same JSONL schema/writer as the trainer (utils.metrics) plus a
        # run.json manifest, so one report tool reads bench AND training
        # runs (docs/observability.md).
        import sys

        from gnot_tpu.obs import manifest as manifest_lib
        from gnot_tpu.utils.metrics import MetricsSink

        with MetricsSink(args.metrics_path) as sink:
            sink.log(kind="bench", **result)
        manifest_lib.write_manifest(
            manifest_lib.manifest_path_for(args.metrics_path),
            config=vars(args),
            argv=sys.argv[1:],
            extra={"metrics_path": args.metrics_path, "kind": "bench"},
        )


if __name__ == "__main__":
    main()
