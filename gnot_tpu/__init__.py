"""GNOT-TPU: a TPU-native neural-operator framework.

Capabilities of ``aloe101/GNOT-Replication`` (see SURVEY.md), rebuilt
TPU-first on JAX/XLA/Flax: masked ragged-mesh batching, normalized linear
attention as MXU einsums, geometry-gated soft-MoE FFNs as batched GEMMs,
sharded training over a device mesh, Orbax checkpointing.
"""

from gnot_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, OptimConfig, ServeConfig, TrainConfig, make_config
from gnot_tpu.data.batch import Loader, MeshBatch, MeshSample, collate
from gnot_tpu.models.gnot import GNOT

__version__ = "0.4.0"

__all__ = [
    "Config",
    "DataConfig",
    "MeshConfig",
    "ModelConfig",
    "OptimConfig",
    "ServeConfig",
    "TrainConfig",
    "make_config",
    "Loader",
    "MeshBatch",
    "MeshSample",
    "collate",
    "GNOT",
    "Trainer",
    "__version__",
]


def __getattr__(name):
    # Lazy: importing Trainer pulls jax/optax/orbax, which config/data
    # users may not need at import time.
    if name == "Trainer":
        from gnot_tpu.train.trainer import Trainer

        return Trainer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
