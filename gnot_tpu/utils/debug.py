"""Debug-build numeric guards (SURVEY.md §5 race-detection note: the
reference is single-threaded with nothing to race; the TPU-native
equivalent of sanitizers is ``checkify`` for NaN/inf/OOB inside jit —
plus this repo's own donation sanitizer, ``utils/sanitizer.py``).

``checked(fn)`` wraps a jittable function so NaN/inf inside it raises
with a location, instead of silently propagating through the compiled
program; pass ``errors=checkify.all_checks`` to add div-by-zero and
out-of-bounds index checks (expensive at trace time on large
programs). Debug builds only — the checks block fusion and cost real
throughput.

``enable_debug_guards()`` is the one-call debug bundle ``main.py``
runs under ``--debug_checks``: ``jax_debug_nans`` plus the donation
alias guard (``GNOT_ALIAS_GUARD``, defaulted to copy mode so
use-after-donate through aliased ``device_get`` views — the
nine-times-root-caused parity bug — cannot occur in a debug run).
"""

from __future__ import annotations

import os
from typing import Callable

import jax
from jax.experimental import checkify


def enable_debug_guards(*, nan_checks: bool = True) -> str:
    """Turn on the debug-run guard set. ``jax_debug_nans`` first (it
    must precede tracing), then the donation sanitizer —
    ``GNOT_ALIAS_GUARD`` is defaulted to ``1`` (copy mode) when unset,
    so an explicit ``GNOT_ALIAS_GUARD=poison`` (or ``=0``) still wins.
    Returns the sanitizer mode installed."""
    from gnot_tpu.utils import sanitizer

    if nan_checks:
        jax.config.update("jax_debug_nans", True)
    os.environ.setdefault("GNOT_ALIAS_GUARD", "1")
    return sanitizer.install()


def checked(fn: Callable, *, jit: bool = True, errors=None) -> Callable:
    """Returns ``fn`` instrumented with numeric checks; the wrapper
    raises ``checkify.JaxRuntimeError`` on the first violation.

    ``errors`` defaults to float checks (NaN/inf) — the practical guard
    for a training step. ``checkify.all_checks`` adds index/div checks
    but multiplies compile time on large models."""
    err_fn = checkify.checkify(
        fn, errors=checkify.float_checks if errors is None else errors
    )
    if jit:
        err_fn = jax.jit(err_fn)

    def wrapper(*args, **kwargs):
        err, out = err_fn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper
