"""Debug-build numeric guards (SURVEY.md §5 race-detection note: the
reference is single-threaded with nothing to race; the TPU-native
equivalent of sanitizers is ``checkify`` for NaN/inf/OOB inside jit).

``checked(fn)`` wraps a jittable function so every NaN/inf/div-by-zero
and out-of-bounds index inside it raises with a location, instead of
silently propagating through the compiled program. Debug builds only —
the checks block fusion and cost real throughput.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.experimental import checkify


def checked(fn: Callable, *, jit: bool = True) -> Callable:
    """Returns ``fn`` instrumented with float + index + div checks; the
    wrapper raises ``checkify.JaxRuntimeError`` on the first violation."""
    err_fn = checkify.checkify(fn, errors=checkify.all_checks)
    if jit:
        err_fn = jax.jit(err_fn)

    def wrapper(*args, **kwargs):
        err, out = err_fn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper
