"""Runtime deadlock witness: the dynamic belt to graftlint GL008's brace.

The static lock-order pass (``analysis/lockorder.py``, GL008) proves
the *source* acquires-while-holding graph cycle-free — but it resolves
calls by AST and must drop what it cannot prove (callbacks, getattr
dispatch, locks threaded through data structures). This module closes
that gap at runtime: it observes the acquisition order that *actually
happens* and reports the first inversion with both witness stacks,
exactly the two artifacts a deadlock post-mortem needs and never has.

``GNOT_LOCK_GUARD`` selects the mode (read at :func:`install` time):

* **off** (unset / ``0`` / ``off``) — nothing is patched:
  ``threading.Lock`` / ``threading.RLock`` remain the original
  factories (``test_lockguard.py`` pins ``threading.Lock is _ORIG_LOCK``
  — the identity proof, same contract as the donation sanitizer's
  off mode). Every lock in the process is byte-identical to an
  unguarded run.
* **witness** (``1`` / ``on`` / ``witness``) — lock *construction* in
  this project's files is wrapped: each lock remembers its
  construction site (``file:line`` — the same identity GL008 and
  ``docs/artifacts/lockmap.jsonl`` use), every thread tracks its held
  stack, and each first-seen acquisition edge ``A -> B`` (acquire B
  while holding A) is added to a process-wide happened-before graph.
  The first edge that closes a cycle triggers ONE ``warnings.warn``
  carrying both stacks: the stack now (B under A) and the recorded
  stack of the first reverse observation (A under B). The run
  continues — witness observes, it does not arbitrate.
* **strict** (``strict``) — as witness, but the closing edge raises
  :class:`LockOrderViolation` *before* the real acquire, so the test
  that provoked the inversion fails at the inversion, not as a hung
  CI job 870 seconds later.

Scope and cost: only constructions whose caller lives under
``gnot_tpu/`` or ``tests/`` are wrapped — stdlib and third-party locks
(queue, logging, jax) keep the original primitives. The steady-state
acquire cost is a thread-local list append plus one dict probe per
already-held lock; stacks are captured only when a NEW edge first
appears (bounded by the edge count, ~dozens — see the lockmap), never
per acquire. Tier-1 runs with witness on via ``tests/conftest.py``;
the measured overhead is recorded in docs/static_analysis.md.

Same-site, different-instance pairs (two ``EngineReplica._lock``s)
do NOT form self-edges: instance-order inversions within one
construction site would alias into an always-on false positive, and
no code here acquires sibling instances nested. Reentrant
re-acquisition of an ``RLock`` by its holder is legal and ignored; a
*non-reentrant* lock re-acquired by its holding thread is reported
immediately as a self-deadlock (that acquire never returns).
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import warnings

_MODES = ("off", "witness", "strict")

#: Live mode; "off" until install() runs.
_mode = "off"

#: The untouched factories, captured once at import (before any
#: install can swap them) — off-mode restores these very objects.
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

#: Graph bookkeeping lock — a raw original primitive, so the guard
#: never traces itself.
_meta = _ORIG_LOCK()

#: site -> {site acquired while holding it, ...}
_edges: dict[str, set[str]] = {}
#: (held_site, acquired_site) -> witness stack of the FIRST observation.
_edge_stacks: dict[tuple[str, str], str] = {}
#: Reported inversions: list of dicts (test/triage introspection).
_inversions: list[dict] = []
_reported: set[tuple[str, str]] = set()

_tls = threading.local()


class LockOrderViolation(RuntimeError):
    """Strict mode: an acquisition closed a lock-order cycle."""


def guard_mode() -> str:
    """The mode ``GNOT_LOCK_GUARD`` requests (not necessarily
    installed yet): off / witness / strict."""
    raw = os.environ.get("GNOT_LOCK_GUARD", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return "off"
    if raw == "strict":
        return "strict"
    return "witness"  # "1" / "on" / "true" / "witness"


def installed_mode() -> str:
    """The mode actually live in this process."""
    return _mode


def install() -> str:
    """Install the guard per ``GNOT_LOCK_GUARD``. Idempotent; safe to
    call from conftest, main() and tools. Off-mode restores the
    ORIGINAL factory objects — no wrapper shims left behind. Locks
    constructed while a previous mode was live keep their wrapping
    (witness/strict wrappers re-check the live mode per acquire, so
    switching to off disarms them too). Returns the live mode."""
    global _mode
    want = guard_mode()
    if want == _mode:
        return _mode
    if want == "off":
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK
    else:
        threading.Lock = _make_lock
        threading.RLock = _make_rlock
    _mode = want
    return _mode


def _site(depth: int = 2) -> str | None:
    """``file:line`` of the construction site when it lies in project
    code (path contains gnot_tpu/ or tests/), else None — stdlib and
    third-party constructions stay unwrapped."""
    frame = sys._getframe(depth)
    fn = frame.f_code.co_filename.replace(os.sep, "/")
    for anchor in ("gnot_tpu/", "tests/"):
        i = fn.rfind(anchor)
        if i >= 0:
            return f"{fn[i:]}:{frame.f_lineno}"
    return None


def _make_lock():
    site = _site()
    real = _ORIG_LOCK()
    if site is None or _mode == "off":
        return real
    return _LockGuard(real, site, reentrant=False)


def _make_rlock():
    site = _site()
    real = _ORIG_RLOCK()
    if site is None or _mode == "off":
        return real
    return _LockGuard(real, site, reentrant=True)


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    """The current stack, lockguard frames trimmed."""
    frames = traceback.extract_stack()
    keep = [
        f for f in frames
        if "utils/lockguard" not in f.filename.replace(os.sep, "/")
    ]
    return "".join(traceback.format_list(keep[-12:]))


def _reaches(src: str, dst: str) -> list[str] | None:
    """DFS path ``src -> ... -> dst`` in the happened-before graph, or
    None. Called under _meta."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


#: Optional observer for inversion reports — ``obs/dtrace.py``'s
#: flight recorder registers here (``FlightRecorder.watch_lockguard``)
#: so a runtime lock-order warning triggers a black-box dump. Called
#: AFTER the record is appended, never under any guard's lock; a
#: raising observer is swallowed (reporting must not add failure
#: modes to the thing being reported on).
on_report = None


def _report(kind: str, message: str, record: dict) -> None:
    record = {"kind": kind, "message": message, **record}
    _inversions.append(record)
    cb = on_report
    if cb is not None:
        try:
            cb(dict(record))
        except Exception:
            pass
    if _mode == "strict":
        raise LockOrderViolation(message)
    warnings.warn(f"GNOT_LOCK_GUARD: {message}", stacklevel=4)


class _LockGuard:
    """A project lock: the real primitive plus order bookkeeping."""

    __slots__ = ("_real", "site", "reentrant")

    def __init__(self, real, site: str, reentrant: bool):
        self._real = real
        self.site = site
        self.reentrant = reentrant

    def __repr__(self):
        return f"<lockguard {'RLock' if self.reentrant else 'Lock'} {self.site}>"

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _mode != "off":
            self._before()
        ok = (
            self._real.acquire(blocking, timeout)
            if timeout != -1
            else self._real.acquire(blocking)
        )
        if ok:
            _held().append(self)
        return ok

    def release(self):
        self._real.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    def _before(self) -> None:
        """Pre-acquire ordering checks: self-deadlock and cycle-closing
        edges are reported BEFORE the real acquire (strict mode must
        raise while the thread can still raise)."""
        held = _held()
        if not held:
            return
        if not self.reentrant and any(g is self for g in held):
            with _meta:
                key = (self.site, self.site)
                if key not in _reported:
                    _reported.add(key)
                    stack = _stack()
                    _report(
                        "self-deadlock",
                        f"non-reentrant lock {self.site} re-acquired by "
                        f"its holding thread (this acquire never "
                        f"returns)\n--- acquiring stack ---\n{stack}",
                        {"cycle": [self.site], "stacks": [stack]},
                    )
            return
        holder = held[-1]
        if holder is self or holder.site == self.site:
            # Reentrant re-acquire, or a sibling instance from the
            # same construction site: no orderable edge either way.
            return
        with _meta:
            edge = (holder.site, self.site)
            if self.site in _edges.get(holder.site, ()):
                return  # known edge: steady state, no stack capture
            stack = _stack()
            _edges.setdefault(holder.site, set()).add(self.site)
            _edge_stacks[edge] = stack
            back = _reaches(self.site, holder.site)
            if back is None:
                return
            cycle = [holder.site] + back
            key = (holder.site, self.site)
            if key in _reported:
                return
            _reported.add(key)
            first = _edge_stacks.get((back[0], back[1]), "<unrecorded>")
            _report(
                "inversion",
                f"lock-order inversion: acquiring {self.site} while "
                f"holding {holder.site}, but the reverse order "
                f"{' -> '.join(cycle)} was already witnessed\n"
                f"--- this acquisition ---\n{stack}"
                f"--- first reverse witness ({back[0]} -> {back[1]}) ---\n"
                f"{first}",
                {"cycle": cycle, "stacks": [stack, first]},
            )


def inversions() -> list[dict]:
    """Reported inversions so far (test/triage introspection)."""
    with _meta:
        return list(_inversions)


def edge_count() -> int:
    """Witnessed happened-before edges (test/triage introspection)."""
    with _meta:
        return sum(len(v) for v in _edges.values())


def reset() -> None:
    """Drop the happened-before graph and reports (test isolation).
    Held-stack state is per-thread and survives — callers reset
    between scenarios, not mid-acquisition."""
    with _meta:
        _edges.clear()
        _edge_stacks.clear()
        _inversions.clear()
        _reported.clear()
