"""Persistent XLA compile cache.

JAX ships a content-addressed compilation cache but leaves it OFF by
default; first compiles here are expensive (30-90s per program over a
remote-device tunnel), so the CLI enables it by default at a per-user
path. Per-user matters: a world-shared /tmp dir would fail for the
second user on a shared machine and mean executing artifacts another
user could write. The test suite (tests/conftest.py) uses the same
location, so CLI runs and tests share warm entries.
"""

from __future__ import annotations

import os
import tempfile


def default_cache_dir() -> str:
    home = os.path.expanduser("~")
    if os.path.isabs(home):
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME") or os.path.join(home, ".cache"),
            "gnot_jax_cache",
        )
    # Stripped container env without HOME: uid-scoped tmp fallback.
    return os.path.join(tempfile.gettempdir(), f"gnot_jax_cache_{os.getuid()}")


def enable_compile_cache(path: str | None = None) -> str:
    """Turn the persistent cache on (call before tracing). Returns the
    cache path in effect. Idempotent: if a cache dir is already
    configured (e.g. the test conftest's hermetic path) and no explicit
    ``path`` is given, the existing configuration wins — in-process
    ``main()`` calls must not silently redirect it."""
    import jax

    if path is None:
        existing = getattr(jax.config, "jax_compilation_cache_dir", None)
        if existing:
            return existing
    path = path or default_cache_dir()
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything that took meaningful compile time.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path
