"""Persistent XLA compile cache.

JAX ships a content-addressed compilation cache but leaves it OFF by
default; first compiles here are expensive (30-90s per program over a
remote-device tunnel), so the CLI enables it by default at a per-user
path. Per-user matters: a world-shared /tmp dir would fail for the
second user on a shared machine and mean executing artifacts another
user could write. The test suite (tests/conftest.py) uses the same
location, so CLI runs and tests share warm entries.
"""

from __future__ import annotations

import contextlib
import os
import tempfile


def default_cache_dir() -> str:
    home = os.path.expanduser("~")
    if os.path.isabs(home):
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME") or os.path.join(home, ".cache"),
            "gnot_jax_cache",
        )
    # Stripped container env without HOME: uid-scoped tmp fallback.
    return os.path.join(tempfile.gettempdir(), f"gnot_jax_cache_{os.getuid()}")


def enable_compile_cache(path: str | None = None) -> str:
    """Turn the persistent cache on (call before tracing). Returns the
    cache path in effect ("" when disabled).

    Resolution order for a default (``path=None``) call:
    ``GNOT_COMPILE_CACHE`` env (``off``/empty disables, a path
    overrides; ``GNOT_TEST_CACHE`` accepted as an alias) → an
    already-configured ``jax_compilation_cache_dir`` (e.g. a hermetic
    test path — in-process ``main()`` calls must not silently redirect
    it) → the per-user default. The env override is what makes
    ``GNOT_COMPILE_CACHE=off`` give genuinely clean-compile runs even
    through code paths that enable the cache themselves."""
    import jax

    if path is None:
        env = os.environ.get("GNOT_COMPILE_CACHE")
        if env is None:
            env = os.environ.get("GNOT_TEST_CACHE")
        if env is not None and env.strip() in ("off", ""):
            return ""
        if env:
            path = env
        else:
            existing = getattr(jax.config, "jax_compilation_cache_dir", None)
            if existing:
                return existing
            path = default_cache_dir()
    previous = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything that took meaningful compile time.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    if previous and previous != path:
        # jax binds its cache object to the dir on FIRST use and then
        # ignores config changes — without a reset, a mid-process dir
        # switch (the cold-start A/B's per-arm caches, the scratch-dir
        # tests) keeps writing to the old path while the probe reports
        # the new one.
        _reset_cache_binding()
    return path


def _reset_cache_binding() -> None:
    """Drop jax's dir-bound cache object so the next compile rebinds
    to the configured ``jax_compilation_cache_dir``. Private surface:
    degrades to 'config updated, old binding kept' if it moves."""
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # pragma: no cover — private API drift
        pass


@contextlib.contextmanager
def compile_cache_disabled():
    """Temporarily disable the persistent compile cache (reads and
    writes). The AOT snapshot path needs genuinely FRESH executables:
    on CPU jaxlib 0.4.x an executable loaded from a persistent-cache
    hit re-serializes without its jitted kernel symbols, producing a
    snapshot that fails to deserialize ("Symbols not found") — see
    serve/aot.py::aot_compile, which validates every snapshot and
    recompiles under this context when the cache-integrated compile
    produced an unserializable executable."""
    import jax

    previous = getattr(jax.config, "jax_compilation_cache_dir", None)
    if not previous:
        yield
        return
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_cache_binding()
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", previous)
        _reset_cache_binding()


def cache_dir_manifest(path: str | None = None) -> dict:
    """Size/occupancy snapshot of the persistent compile cache — the
    deploy-time artifact ``tools/aot_prewarm.py`` records alongside its
    program table. ``path=None`` reads the configured
    ``jax_compilation_cache_dir``. Returns ``{"dir", "entries",
    "bytes"}`` with Nones when the dir is unset/absent/unreadable (a
    corrupt or missing cache dir is a cold start, not a crash)."""
    if path is None:
        import jax

        path = getattr(jax.config, "jax_compilation_cache_dir", None)
    out = {"dir": path, "entries": None, "bytes": None}
    if not path or not os.path.isdir(path):
        return out
    try:
        entries = [de for de in os.scandir(path) if de.is_file()]
        out["entries"] = len(entries)
        out["bytes"] = sum(de.stat().st_size for de in entries)
    except OSError:
        pass
    return out


def warm_cache(thunks, *, min_compile_secs: float = 0.0) -> dict:
    """Run a sequence of ``(key, thunk)`` compile thunks under ONE
    probe with the persistent-cache admission threshold lowered to
    ``min_compile_secs`` — the deploy-time AOT pipeline (serve/aot.py).

    The default threshold (0.5 s, ``enable_compile_cache``) keeps tiny
    throwaway programs out of the on-disk cache; a deploy-time prewarm
    wants EVERY serving program persisted — a bucket program that
    compiles in 0.4 s still sheds a whole queue when it lands under a
    200 ms deadline. The old threshold is restored afterwards.

    Returns ``{"programs": [{"key", "seconds"}...], "seconds",
    "requests", "hits", "misses", "dir", "entries_before",
    "entries_after"}`` (the probe fields have None degradation
    semantics — see ``compile_cache_probe``)."""
    import time

    import jax

    old = getattr(
        jax.config, "jax_persistent_cache_min_compile_time_secs", None
    )
    programs = []
    try:
        if old is not None:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                min_compile_secs,
            )
        with compile_cache_probe() as stats:
            for key, thunk in thunks:
                t0 = time.monotonic()
                thunk()
                programs.append(
                    {"key": key, "seconds": time.monotonic() - t0}
                )
    finally:
        if old is not None:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", old
            )
    return {
        "programs": programs,
        "seconds": sum(p["seconds"] for p in programs),
        **stats,
    }


@contextlib.contextmanager
def compile_cache_probe():
    """Count persistent-compile-cache hits/misses across a block — the
    serve-warmup instrumentation (ISSUE 9 satellite: N-replica warm
    time is compile-bound, and whether warmup() compiled fresh or
    loaded cached executables is the difference between seconds and
    minutes at scale).

    Yields a dict filled IN PLACE (readable after the block):
    ``requests`` (compiles that consulted the cache), ``hits``,
    ``misses`` (requests - hits), plus ``dir`` and the cache-dir entry
    count ``entries_before``/``entries_after`` (new entries are the
    misses that took long enough to persist —
    ``jax_persistent_cache_min_compile_time_secs`` gates tiny
    programs out of the on-disk cache, so ``misses`` can exceed
    ``new_entries``).

    Counting rides ``jax._src.monitoring``'s cache events (the same
    counters jax's own telemetry uses); if that private surface moves,
    the probe degrades to entry-count deltas with ``hits``/``misses``
    as None rather than breaking warmup."""
    import jax  # noqa: F401 — the monitoring import below needs jax loaded

    def _count_entries(path):
        if not path or not os.path.isdir(path):
            return None
        try:
            return sum(1 for de in os.scandir(path) if de.is_file())
        except OSError:
            return None

    cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    stats = {
        "dir": cache_dir,
        "entries_before": _count_entries(cache_dir),
        "entries_after": None,
        "requests": None,
        "hits": None,
        "misses": None,
    }
    counts = {"requests": 0, "hits": 0}
    listener = None
    try:
        from jax._src import monitoring

        def listener(event: str, **kw):  # noqa: ARG001 — monitoring API
            if event == "/jax/compilation_cache/compile_requests_use_cache":
                counts["requests"] += 1
            elif event == "/jax/compilation_cache/cache_hits":
                counts["hits"] += 1

        monitoring.register_event_listener(listener)
    except Exception:  # pragma: no cover — private API drift
        listener = None
    try:
        yield stats
    finally:
        if listener is not None:
            try:
                from jax._src import monitoring

                monitoring._unregister_event_listener_by_callback(listener)
            except Exception:  # pragma: no cover
                pass
            stats["requests"] = counts["requests"]
            stats["hits"] = counts["hits"]
            stats["misses"] = counts["requests"] - counts["hits"]
        stats["entries_after"] = _count_entries(stats["dir"])
