"""Persistent XLA compile cache.

JAX ships a content-addressed compilation cache but leaves it OFF by
default; first compiles here are expensive (30-90s per program over a
remote-device tunnel), so the CLI enables it by default at a per-user
path. Per-user matters: a world-shared /tmp dir would fail for the
second user on a shared machine and mean executing artifacts another
user could write. The test suite (tests/conftest.py) uses the same
location, so CLI runs and tests share warm entries.
"""

from __future__ import annotations

import contextlib
import os
import tempfile


def default_cache_dir() -> str:
    home = os.path.expanduser("~")
    if os.path.isabs(home):
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME") or os.path.join(home, ".cache"),
            "gnot_jax_cache",
        )
    # Stripped container env without HOME: uid-scoped tmp fallback.
    return os.path.join(tempfile.gettempdir(), f"gnot_jax_cache_{os.getuid()}")


def enable_compile_cache(path: str | None = None) -> str:
    """Turn the persistent cache on (call before tracing). Returns the
    cache path in effect ("" when disabled).

    Resolution order for a default (``path=None``) call:
    ``GNOT_COMPILE_CACHE`` env (``off``/empty disables, a path
    overrides; ``GNOT_TEST_CACHE`` accepted as an alias) → an
    already-configured ``jax_compilation_cache_dir`` (e.g. a hermetic
    test path — in-process ``main()`` calls must not silently redirect
    it) → the per-user default. The env override is what makes
    ``GNOT_COMPILE_CACHE=off`` give genuinely clean-compile runs even
    through code paths that enable the cache themselves."""
    import jax

    if path is None:
        env = os.environ.get("GNOT_COMPILE_CACHE")
        if env is None:
            env = os.environ.get("GNOT_TEST_CACHE")
        if env is not None and env.strip() in ("off", ""):
            return ""
        if env:
            path = env
        else:
            existing = getattr(jax.config, "jax_compilation_cache_dir", None)
            if existing:
                return existing
            path = default_cache_dir()
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything that took meaningful compile time.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path


@contextlib.contextmanager
def compile_cache_probe():
    """Count persistent-compile-cache hits/misses across a block — the
    serve-warmup instrumentation (ISSUE 9 satellite: N-replica warm
    time is compile-bound, and whether warmup() compiled fresh or
    loaded cached executables is the difference between seconds and
    minutes at scale).

    Yields a dict filled IN PLACE (readable after the block):
    ``requests`` (compiles that consulted the cache), ``hits``,
    ``misses`` (requests - hits), plus ``dir`` and the cache-dir entry
    count ``entries_before``/``entries_after`` (new entries are the
    misses that took long enough to persist —
    ``jax_persistent_cache_min_compile_time_secs`` gates tiny
    programs out of the on-disk cache, so ``misses`` can exceed
    ``new_entries``).

    Counting rides ``jax._src.monitoring``'s cache events (the same
    counters jax's own telemetry uses); if that private surface moves,
    the probe degrades to entry-count deltas with ``hits``/``misses``
    as None rather than breaking warmup."""
    import jax  # noqa: F401 — the monitoring import below needs jax loaded

    def _count_entries(path):
        if not path or not os.path.isdir(path):
            return None
        try:
            return sum(1 for de in os.scandir(path) if de.is_file())
        except OSError:
            return None

    cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    stats = {
        "dir": cache_dir,
        "entries_before": _count_entries(cache_dir),
        "entries_after": None,
        "requests": None,
        "hits": None,
        "misses": None,
    }
    counts = {"requests": 0, "hits": 0}
    listener = None
    try:
        from jax._src import monitoring

        def listener(event: str, **kw):  # noqa: ARG001 — monitoring API
            if event == "/jax/compilation_cache/compile_requests_use_cache":
                counts["requests"] += 1
            elif event == "/jax/compilation_cache/cache_hits":
                counts["hits"] += 1

        monitoring.register_event_listener(listener)
    except Exception:  # pragma: no cover — private API drift
        listener = None
    try:
        yield stats
    finally:
        if listener is not None:
            try:
                from jax._src import monitoring

                monitoring._unregister_event_listener_by_callback(listener)
            except Exception:  # pragma: no cover
                pass
            stats["requests"] = counts["requests"]
            stats["hits"] = counts["hits"]
            stats["misses"] = counts["requests"] - counts["hits"]
        stats["entries_after"] = _count_entries(stats["dir"])
