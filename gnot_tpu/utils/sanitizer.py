"""Runtime donation sanitizer: make use-after-donate impossible (or loud).

The single most expensive bug class in this repo's history is the
**aliased host view over donated buffers**: on CPU, ``jax.device_get``
(and ``np.asarray`` on a device value) return zero-copy NumPy views of
the live device buffers, and the donating train steps
(``donate_argnums=(0,)``) hand those very buffers back to XLA on every
dispatch. A snapshot that was really a view silently "advances" with
the next step, and the bug surfaces as ~1e-3 parity drift three layers
from its cause — root-caused NINE separate times across PRs 6, 7 and
10 (docs/robustness.md "The donation sanitizer" has the case study).
graftlint GL006 (docs/static_analysis.md) catches the shape statically;
this module is the runtime belt to that brace.

``GNOT_ALIAS_GUARD`` selects the mode (read at :func:`install` time):

* **off** (unset / ``0`` / ``off``) — nothing is patched; every hot
  path is byte-identical to an unguarded process. The committed A/B
  (``docs/artifacts/sanitizer_overhead_ab.jsonl``) pins this.
* **copy** (``1`` / ``on`` / ``copy``) — ``jax.device_get`` returns
  **defensively copied** arrays: a host snapshot through the
  device_get channel — where all nine historical instances lived —
  can never alias device memory. This is the tier-1 default
  (tests/conftest.py) and what ``--debug_checks`` turns on: the cost is
  one extra host memcpy per fetch, off the dispatch hot path. Honest
  limit: ``np.asarray`` over a device value goes through numpy's
  C-level buffer path, which is not interceptable (patching
  ``ArrayImpl.__array__`` verifiably does not take effect), so that
  seeding form stays zero-copy at runtime — graftlint **GL006** covers
  it statically, and the engine's own fetches ride :func:`host_fetch`,
  which IS guarded.
* **poison** (``poison``) — the forensic mode: ``device_get`` returns
  the raw (possibly zero-copy) views but REGISTERS them against their
  device buffers; a donating dispatch wrapped by
  :func:`guard_donating` then overwrites every registered view of the
  donated buffers with a sentinel byte pattern (NaN for float views)
  and warns with the view's creation site. A stale read stops being
  1e-3 drift and becomes NaN at its own source line. (If XLA aliased
  the new state onto the donated memory, the poison lands there too —
  still loud, by design: the view's contents are undefined after
  donation either way. Diagnostic runs only.)

Wiring: trainer steps are wrapped in ``Trainer.initialize``;
``InferenceEngine`` fetches outputs through :func:`host_fetch`;
``gnot_tpu.main`` installs the guard at startup (forced on under
``--debug_checks``); tier-1 installs via ``tests/conftest.py``.
"""

from __future__ import annotations

import ctypes
import os
import traceback
import warnings
import weakref
from typing import Callable, Iterable

import numpy as np

_MODES = ("off", "copy", "poison")

#: Live mode; "off" until install() runs.
_mode = "off"
_orig_device_get: Callable | None = None

#: id(device array) -> list of (weakref to host view ndarray, origin
#: "file:line", is-jax-cache flag). Populated only in poison mode;
#: entries die with their device array (weakref.finalize) or at
#: poisoning.
_views: dict[int, list] = {}

#: Donating callables handed to guard_donating while poison was NOT
#: live (returned unwrapped). A later install of poison mode warns
#: with this count: those dispatches will never poison anything.
_unguarded_builds = 0


def guard_mode() -> str:
    """The mode ``GNOT_ALIAS_GUARD`` requests (not necessarily
    installed yet): off / copy / poison."""
    raw = os.environ.get("GNOT_ALIAS_GUARD", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return "off"
    if raw == "poison":
        return "poison"
    return "copy"  # "1" / "on" / "true" / "copy"


def installed_mode() -> str:
    """The mode actually live in this process."""
    return _mode


def install() -> str:
    """Install the guard per ``GNOT_ALIAS_GUARD``. Idempotent; safe to
    call from conftest, main() and tools. Off-mode installs NOTHING —
    the unguarded process stays byte-identical. Returns the live mode.

    Re-installation honors a CHANGED env var (tests flip modes); the
    original ``jax.device_get`` is kept once and restored around
    swaps."""
    global _mode, _orig_device_get
    import jax

    want = guard_mode()
    if want == _mode:
        return _mode
    if _orig_device_get is None:
        _orig_device_get = jax.device_get
    if want == "off":
        jax.device_get = _orig_device_get
    else:
        jax.device_get = _guarded_device_get
    if _mode == "poison" and want != "poison":
        # Leaving poison: drop the registry — wrappers built under
        # poison re-check the live mode per call (disarm is total),
        # and stale entries must not poison after a later re-arm.
        _views.clear()
    if want == "poison" and _mode != "poison" and _unguarded_builds:
        # Poison forensics attach at BUILD time: guard_donating wraps a
        # dispatch callable only when poison is already live, so a
        # Trainer/engine constructed BEFORE this install keeps its bare
        # steps and would silently register views nothing ever
        # poisons. Say so — a forensic mode the operator merely
        # believes is armed is worse than none. (A poison env set
        # before any build stays silent: nothing was built unguarded.)
        warnings.warn(
            f"GNOT_ALIAS_GUARD=poison installed after "
            f"{_unguarded_builds} donating dispatch(es) were built "
            "unguarded — rebuild the Trainer/engine (or set the env "
            "before the run) for forensics on existing objects",
            stacklevel=2,
        )
    _mode = want
    return _mode


def _origin() -> str:
    """file:line of the device_get caller (poison-mode forensics: the
    warning at poison time points at the view's creation site)."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        fn = frame.filename
        if "utils/sanitizer" in fn.replace(os.sep, "/"):
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


def _register_view(device_leaf, host_leaf, origin: str) -> None:
    if not isinstance(host_leaf, np.ndarray) or host_leaf.flags.owndata:
        return  # a real copy cannot go stale
    key = id(device_leaf)
    slot = _views.get(key)
    if slot is None:
        slot = _views[key] = []
        try:
            weakref.finalize(device_leaf, _views.pop, key, None)
        except TypeError:  # non-weakrefable leaf: keep the entry
            pass
    # jax caches the zero-copy host view on the Array (_npy_value) and
    # returns the SAME object on every fetch — so the view outliving
    # the user's reference is normal, not a leak. Remember whether
    # this view is that cache object; at poison time a cache-held view
    # with no OTHER referents is skipped (the user copied and moved
    # on — the committed fix pattern must stay silent).
    is_cache = host_leaf is getattr(device_leaf, "_npy_value", None)
    try:
        slot.append((weakref.ref(host_leaf), origin, is_cache))
    except TypeError:
        pass


def _guarded_device_get(x):
    """The patched ``jax.device_get``: deep copies in copy mode,
    register-and-pass-through in poison mode."""
    import jax

    out = _orig_device_get(x)
    if _mode == "copy":
        return jax.tree.map(
            lambda a: np.array(a) if isinstance(a, np.ndarray) else a, out
        )
    if _mode == "poison":
        origin = _origin()
        for dev, host in zip(jax.tree.leaves(x), jax.tree.leaves(out)):
            _register_view(dev, host, origin)
    return out


def host_fetch(x) -> np.ndarray:
    """Fetch a device value to host — the serve-engine output seam.

    Off: ``np.asarray`` (zero-copy when the backend allows — today's
    behavior, byte-identical). Copy: an owned copy, so an engine
    caller's result can never alias device memory another dispatch may
    reuse. Poison: zero-copy plus registration, so a later donation of
    the fetched value poisons the caller's view loudly."""
    if _mode == "copy":
        return np.array(x)
    out = np.asarray(x)
    if _mode == "poison":
        import jax

        origin = _origin()
        for dev, host in zip(
            jax.tree.leaves(x), jax.tree.leaves(out)
        ):
            _register_view(dev, host, origin)
    return out


def guard_donating(fn: Callable, donate_argnums: tuple[int, ...] = (0,)):
    """Wrap a donating dispatch callable so registered host views of
    its donated arguments are poisoned after each call (poison mode).
    In off/copy mode this returns ``fn`` ITSELF — the dispatch hot
    path carries zero wrapper frames unless forensics are on (a later
    switch TO poison warns about these unguarded builds)."""
    if _mode != "poison":
        global _unguarded_builds
        _unguarded_builds += 1
        return fn

    def guarded(*args, **kwargs):
        import jax

        if _mode != "poison":
            # Disarmed after build (install() switched modes): behave
            # exactly like the bare step — no registry walks, no
            # memsets, no warnings on the dispatch path.
            return fn(*args, **kwargs)
        donated = []
        for i in donate_argnums:
            if i < len(args):
                donated.extend(jax.tree.leaves(args[i]))
        out = fn(*args, **kwargs)
        _poison_views_of(donated, repr(getattr(fn, "__name__", fn)))
        return out

    # The recompile monitor keys on the jitted callable's _cache_size;
    # forward it so wrapping doesn't blind the monitor.
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is not None:
        guarded._cache_size = cache_size
    guarded.__name__ = getattr(fn, "__name__", "guarded_donating")
    guarded.__wrapped__ = fn
    return guarded


def _poison_views_of(donated_leaves: Iterable, donor: str) -> None:
    import sys

    origins = []
    for leaf in donated_leaves:
        for ref, origin, is_cache in _views.pop(id(leaf), ()):
            arr = ref()
            if arr is None:
                continue
            # Referents at this point: the `arr` local + getrefcount's
            # argument (= 2), plus the jax _npy_value cache when this
            # view IS the cache object. Anything beyond that is a live
            # user alias — the hazard; at or below it, the snapshot
            # was copied and dropped (the fixed pattern): stay silent.
            if sys.getrefcount(arr) <= (3 if is_cache else 2):
                continue
            if _poison_array(arr):
                origins.append(origin)
    if origins:
        warnings.warn(
            f"GNOT_ALIAS_GUARD=poison: {len(origins)} stale host view(s) "
            f"of buffers donated to {donor} poisoned with the NaN "
            f"sentinel; views created at: " + ", ".join(sorted(set(origins))),
            stacklevel=3,
        )


def _poison_array(arr: np.ndarray) -> bool:
    """Overwrite a (read-only, zero-copy) view's memory with 0xFF —
    NaN for float dtypes, -1/garbage for ints — via ctypes: numpy
    refuses the write (the view is correctly marked read-only), but
    the memory is ours and its contents are UNDEFINED post-donation
    anyway; the sentinel just makes every reader notice."""
    if not arr.flags["C_CONTIGUOUS"] or arr.nbytes == 0:
        return False
    try:
        ptr = arr.__array_interface__["data"][0]
        ctypes.memset(ptr, 0xFF, arr.nbytes)
        return True
    except Exception:  # pragma: no cover — exotic buffer layouts
        return False


def stale_view_count() -> int:
    """Registered (not yet poisoned) views — test/triage introspection."""
    return sum(
        1
        for slot in _views.values()
        for ref, _, _ in slot
        if ref() is not None
    )


def clear_registry() -> None:
    """Drop all registered views (test isolation)."""
    _views.clear()
