"""Structured metrics sinks.

The reference logs via three ``print`` lines per epoch
(``/root/reference/main.py:105,147-148``). The trainer keeps those exact
console lines for diffability; this module adds structured JSONL metrics
(loss, LR, throughput, step time, and the obs/ telemetry records —
docs/observability.md documents the full schema) on top.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, TextIO

import numpy as np


def _coerce(v: Any) -> Any:
    """JSON-safe recursive coercion: numpy scalars -> Python, arrays ->
    (nested) lists, non-finite floats -> null. json.dumps would emit
    bare NaN/Infinity tokens (invalid JSON) for non-finite floats —
    e.g. a diverged loss or the inf metric of an empty test set — and
    rejects numpy scalars/arrays outright. Telemetry records carry
    ``[n_expert]`` gate-load vectors, hence the recursion."""
    if isinstance(v, dict):
        return {k: _coerce(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_coerce(x) for x in v]
    if isinstance(v, np.floating):
        v = float(v)
    elif isinstance(v, np.integer):
        return int(v)
    elif isinstance(v, np.bool_):
        return bool(v)
    elif isinstance(v, np.ndarray) or (
        hasattr(v, "__array__") and not isinstance(v, (str, bytes, int, float, bool))
    ):
        # numpy AND jax arrays; 0-d arrays tolist() to a bare scalar.
        return _coerce(np.asarray(v).tolist())
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


class MetricsSink:
    """Append-only JSONL metrics writer.

    Context manager: ``with MetricsSink(path) as sink: ...`` closes the
    file on every exit path — an exception mid-run must not strand
    buffered records (the file is line-buffered, but the final partial
    line and the OS-level flush still need the close).
    """

    def __init__(self, path: str):
        self.path = path
        if d := os.path.dirname(path):
            os.makedirs(d, exist_ok=True)
        self._fh: TextIO = open(path, "a", buffering=1)

    def log(self, **record: Any) -> None:
        record.setdefault("ts", time.time())
        record = {k: _coerce(v) for k, v in record.items()}
        self._fh.write(json.dumps(record) + "\n")

    def flush(self) -> None:
        if not self._fh.closed:
            self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self) -> "MetricsSink":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
