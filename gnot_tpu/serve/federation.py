"""Topology-honest federation: a multi-host CONTROL plane over loopback.

Every scaling and survivability claim before this module rode a single
process: one ``ReplicaRouter``, one pool, one failure domain. At the
north-star scale GNOT serving is a multi-host system whose dominant
failure modes are HOST DEATH and NETWORK PARTITION — neither of which a
single-process test can even express. This module makes the control
plane honest about topology while the data plane stays local (the
jaxlib CPU wheel ships no cross-process collectives — see
``docs/distributed.md`` / ``docs/parallelism.md``): every host is a
real ``ReplicaRouter`` (unchanged underneath), and hosts talk ONLY
through a versioned, length-prefixed JSON wire protocol.

Three layers, bottom up:

* **Wire protocol** — 4-byte big-endian length prefix + UTF-8 JSON
  payload. ``MESSAGES`` is the literal wire-schema registry (the GL005
  registry-drift lint parses it, same as ``obs/events.py::EVENTS``);
  every frame is built by :func:`wire` which validates against it.
  ``FrameDecoder`` is a stateful tolerant parser: truncated frames
  buffer, garbage JSON is counted and skipped, oversize frames are
  drained in skip-mode — a malformed peer can NEVER wedge a host.
  Version skew is refused loudly at the ``hello`` handshake.

* **Transports** — ``TcpLink`` speaks the real loopback-TCP shape
  (socket + reader thread); ``InProcLink`` delivers the SAME encoded
  bytes synchronously on the caller's thread with an injectable clock,
  so chaos tests (partitions, dropped/delayed frames, host kills) are
  deterministic. Both feed identical ``FrameDecoder`` state machines:
  the in-proc tests exercise the real codec, not a shortcut.

* **Control plane** — ``HostAgent`` wraps one host's local pool and
  serves the protocol (place, stream, drain, stats, prewarm, scale).
  ``ClusterRouter`` is the controller: lease-based heartbeats feed a
  suspicion→dead ``FailureDetector`` (a silent host dwells in SUSPECT —
  drained around via hedged placements — before being declared dead, so
  a merely slow host is never killed); one-shot requests hedge/retry to
  survivors with at-least-once suppression (first ``result`` wins);
  rollout sessions owned by a dead host are RE-MIGRATED to a survivor
  from their persisted ``SessionStore`` snapshots (the PR 13 replay
  discipline, now cross-host: restored prefix is identical, replayed
  steps are suppressed below the cluster's high-water mark);
  ``drain()`` resolves every future on every host and emits ONE
  ``cluster_summary``. Autoscaling is cluster-scoped: merged per-host
  series, scale-ups target the least-loaded live host, and AOT
  manifests keyed by host topology hydrate joiners without a compile.

Chaos is injected at the seams the real system fails at:
``host_kill@N`` (agent dies before its Nth inbound control message),
``net_partition@N`` / ``msg_drop@N`` (Nth outbound frame partitions the
link / vanishes), ``msg_delay@MS`` (one frame held MS fake-clock
milliseconds) — registered in ``resilience/faults.py::FAULT_KINDS`` and
A/B'd by ``tools/federation_ab.py`` → ``docs/artifacts/federation_ab.jsonl``.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from gnot_tpu.data.batch import MeshSample
from gnot_tpu.obs import dtrace, events
from gnot_tpu.serve.rollout import RolloutResult
from gnot_tpu.serve.server import ServeResult

# --------------------------------------------------------------------------
# Wire protocol: framing
# --------------------------------------------------------------------------

#: Protocol generation. Bumped on any incompatible wire change; a
#: ``hello`` carrying a different version is refused with
#: ``hello_reject`` (version-skew must fail LOUDLY at connect time, not
#: silently mis-parse mid-storm).
PROTOCOL_VERSION = 1

#: Hard per-frame payload ceiling. A length prefix above this is
#: treated as hostile/corrupt: the decoder drains the declared bytes in
#: skip-mode (never buffering them) and counts ``oversize``.
MAX_FRAME_BYTES = 8 * 1024 * 1024


class ProtocolError(RuntimeError):
    """Loud failure of the wire contract (version skew, invalid
    message against ``MESSAGES``, handshake timeout)."""


def encode_frame(msg: dict) -> bytes:
    """One wire frame: 4-byte big-endian payload length + UTF-8 JSON."""
    payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame payload {len(payload)}B exceeds MAX_FRAME_BYTES"
        )
    return len(payload).to_bytes(4, "big") + payload


class FrameDecoder:
    """Stateful, tolerant frame parser — the receive half of the wire.

    ``feed(data)`` accepts ANY byte split (TCP gives no message
    boundaries) and returns the complete, well-formed messages it can
    extract. Malformed input degrades, never wedges:

    * truncated frame → buffered until more bytes arrive;
    * length prefix of 0 or payload that is not a JSON object with a
      ``kind`` → counted in ``garbage``, stream continues;
    * length prefix above ``max_frame_bytes`` → counted in
      ``oversize`` and the declared payload is DRAINED in skip-mode
      (bounded memory even for a 4 GiB claim), stream continues.

    Raw non-frame garbage is necessarily misread as a length prefix —
    the decoder consumes it as a bogus frame and resynchronises; the
    worst case is skipped bytes and bumped counters, never an
    exception or an unbounded buffer.
    """

    def __init__(self, max_frame_bytes: int = MAX_FRAME_BYTES) -> None:
        self.max_frame_bytes = max_frame_bytes
        self._buf = bytearray()
        self._skip = 0  # bytes of an oversize payload left to drain
        self.garbage = 0
        self.oversize = 0

    def feed(self, data: bytes) -> list[dict]:
        self._buf.extend(data)
        out: list[dict] = []
        while True:
            if self._skip:
                take = min(self._skip, len(self._buf))
                del self._buf[:take]
                self._skip -= take
                if self._skip:
                    break
                continue
            if len(self._buf) < 4:
                break
            n = int.from_bytes(self._buf[:4], "big")
            if n == 0:
                self.garbage += 1
                del self._buf[:4]
                continue
            if n > self.max_frame_bytes:
                self.oversize += 1
                del self._buf[:4]
                self._skip = n
                continue
            if len(self._buf) < 4 + n:
                break
            payload = bytes(self._buf[4 : 4 + n])
            del self._buf[: 4 + n]
            try:
                msg = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError):
                self.garbage += 1
                continue
            if not isinstance(msg, dict) or "kind" not in msg:
                self.garbage += 1
                continue
            out.append(msg)
        return out


# --------------------------------------------------------------------------
# Wire protocol: message schema registry (GL005-checked)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class MessageSpec:
    """Schema of one wire message kind: required field names, one-line
    doc (rendered into ``docs/serving.md``), optional field names."""

    fields: tuple[str, ...]
    doc: str
    optional: tuple[str, ...] = ()


# Controller→agent kinds.
HELLO = "hello"
HEARTBEAT = "heartbeat"
SUBMIT = "submit"
SUBMIT_ROLLOUT = "submit_rollout"
DRAIN = "drain"
STATS = "stats"
PREWARM = "prewarm"
SCALE = "scale"
TRACE_PULL = "trace_pull"
# Agent→controller kinds.
HELLO_OK = "hello_ok"
HELLO_REJECT = "hello_reject"
HEARTBEAT_ACK = "heartbeat_ack"
RESULT = "result"
PLACED = "placed"
STEP = "step"
ROLLOUT_DONE = "rollout_done"
DRAIN_OK = "drain_ok"
STATS_OK = "stats_ok"
PREWARM_OK = "prewarm_ok"
SCALE_OK = "scale_ok"
TRACE_OK = "trace_ok"
ERROR = "error"

#: The wire-schema registry. Same contract as ``obs/events.py::EVENTS``:
#: literal string keys (the GL005 registry-drift lint AST-parses this
#: dict — never imports it), every kind documented in
#: ``docs/serving.md``, every frame built through :func:`wire`.
MESSAGES: dict[str, MessageSpec] = {
    "hello": MessageSpec(
        fields=("version",),
        doc="Controller handshake; carries the controller's protocol "
        "version for skew refusal.",
        optional=("cluster",),
    ),
    "hello_ok": MessageSpec(
        fields=("version", "host", "pool"),
        doc="Agent accepts the handshake: its host id, pool size and "
        "(optionally) topology key for manifest matching.",
        optional=("topology",),
    ),
    "hello_reject": MessageSpec(
        fields=("version", "want"),
        doc="Version-skew refusal: the agent's version and the version "
        "it requires. The controller raises ProtocolError.",
        optional=("host",),
    ),
    "heartbeat": MessageSpec(
        fields=("seq",),
        doc="Controller lease probe, monotonically sequenced per host; "
        "`t` stamps the controller's send clock for the obs/dtrace.py "
        "clock-alignment exchange.",
        optional=("t",),
    ),
    "heartbeat_ack": MessageSpec(
        fields=("seq", "host", "load"),
        doc="Agent lease renewal: echoes seq, reports queue load; "
        "feeds the FailureDetector and cluster autoscaling. `t` echoes "
        "the probe's controller send stamp and `agent_t` adds the "
        "agent's own clock — one midpoint-method clock-offset sample "
        "per round (docs/observability.md 'Distributed tracing').",
        optional=("pool", "sessions", "depth", "t", "agent_t"),
    ),
    "submit": MessageSpec(
        fields=("id", "sample"),
        doc="Place one one-shot request (base64 array codec) on the "
        "agent's local router. `trace_ctx` propagates the cluster's "
        "head-sampling decision (trace id, parent span, sampled flag, "
        "tenant) — the host NEVER re-decides sampling.",
        optional=("deadline_ms", "tenant", "trace_ctx"),
    ),
    "result": MessageSpec(
        fields=("id", "ok"),
        doc="Terminal reply for a one-shot submit; duplicates from "
        "hedged placements are suppressed (first wins).",
        optional=("reason", "output", "latency_ms", "detail"),
    ),
    "submit_rollout": MessageSpec(
        fields=("id", "steps"),
        doc="Place (resume=false) or re-migrate (resume=true, from the "
        "persisted SessionStore snapshot) a rollout session. "
        "`trace_ctx` carries the session's ORIGINAL trace context on "
        "every placement — a re-migrated session's resumed steps join "
        "the trace its first step started.",
        optional=(
            "sample",
            "name",
            "resume",
            "deadline_ms",
            "rollout_deadline_ms",
            "tenant",
            "trace_ctx",
        ),
    ),
    "placed": MessageSpec(
        fields=("id", "host", "at_step"),
        doc="Rollout placement ack; at_step is the restored snapshot "
        "cursor (0 for a fresh session) — the migration replay point.",
    ),
    "step": MessageSpec(
        fields=("id", "step", "output"),
        doc="One committed rollout step streamed back; the cluster's "
        "high-water mark suppresses replayed duplicates.",
    ),
    "rollout_done": MessageSpec(
        fields=("id", "ok"),
        doc="Terminal reply for a rollout session; carries the FULL "
        "per-step outputs so step frames lost to a healed partition "
        "are repaired at resolution.",
        optional=(
            "reason",
            "steps_completed",
            "migrations",
            "drained_at_step",
            "detail",
            "outputs",
        ),
    ),
    "drain": MessageSpec(
        fields=(),
        doc="Coordinated drain: the agent drains its local pool and "
        "replies drain_ok with the pool serve_summary.",
        optional=("timeout_s",),
    ),
    "drain_ok": MessageSpec(
        fields=("host", "summary"),
        doc="Drain completion with the host's pool-level summary dict.",
    ),
    "stats": MessageSpec(
        fields=("seq",),
        doc="Poll the agent's MetricsRegistry snapshot.",
    ),
    "stats_ok": MessageSpec(
        fields=("seq", "host", "series"),
        doc="Registry snapshot reply; the controller prefixes series "
        "keys with 'host<id>/' and merges across hosts.",
    ),
    "prewarm": MessageSpec(
        fields=("manifest",),
        doc="Hydrate the joiner's pool from a topology-keyed AOT "
        "deploy manifest (no trace, no compile).",
    ),
    "prewarm_ok": MessageSpec(
        fields=("host", "replicas"),
        doc="Prewarm completion: replicas hydrated.",
    ),
    "scale": MessageSpec(
        fields=("direction",),
        doc="Cluster-scoped autoscale order ('up'/'down') targeted at "
        "the least-/most-loaded live host.",
        optional=("reason",),
    ),
    "scale_ok": MessageSpec(
        fields=("host", "ok", "pool"),
        doc="Scale order outcome with the host's new pool size.",
        optional=("detail",),
    ),
    "trace_pull": MessageSpec(
        fields=("seq",),
        doc="Collect the agent's span buffer for cross-host stitching "
        "(sent by ClusterRouter.drain before the merged trace file is "
        "written).",
    ),
    "trace_ok": MessageSpec(
        fields=("seq", "host", "trace"),
        doc="Trace-pull reply: the host tracer's Chrome export object "
        "(empty when the host runs untraced) plus its sampled/total/"
        "dropped `coverage` counters — obs/dtrace.merge_traces rebases "
        "and stitches these into one file.",
        optional=("coverage",),
    ),
    "error": MessageSpec(
        fields=("reason",),
        doc="Agent-side protocol failure for one inbound message "
        "(unknown kind, schema violation); bad_kind names the "
        "offending message's kind; the stream continues.",
        optional=("detail", "bad_kind"),
    ),
}

_CONSTANT_KINDS = {
    v
    for k, v in list(globals().items())
    if k.isupper() and isinstance(v, str) and v in MESSAGES
}
assert _CONSTANT_KINDS == set(MESSAGES), (
    "MESSAGES registry and module constants diverged: "
    f"{_CONSTANT_KINDS.symmetric_difference(set(MESSAGES))}"
)


def validate_message(msg: dict) -> None:
    """Raise :class:`ProtocolError` unless ``msg`` matches its
    registered :class:`MessageSpec` (unknown kind, or a required field
    missing). Extra fields are allowed — the registry pins the floor,
    forward-compatible senders may say more."""
    kind = msg.get("kind")
    spec = MESSAGES.get(kind)
    if spec is None:
        raise ProtocolError(f"unregistered message kind {kind!r}")
    missing = [f for f in spec.fields if f not in msg]
    if missing:
        raise ProtocolError(f"message {kind!r} missing fields {missing}")


def wire(_kind: str, **fields) -> dict:
    """Build one validated wire message. EVERY frame either side sends
    goes through here — the GL005 lint resolves these call sites
    against ``MESSAGES`` exactly like ``events.py`` emit sites."""
    msg = {"kind": _kind, **fields}
    validate_message(msg)
    return msg


# --------------------------------------------------------------------------
# Array / sample codec (byte-exact: b64 of the raw buffer)
# --------------------------------------------------------------------------


def _enc_arr(a) -> dict | None:
    if a is None:
        return None
    a = np.ascontiguousarray(a)
    return {
        "shape": list(a.shape),
        "dtype": str(a.dtype),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _dec_arr(d) -> np.ndarray | None:
    if d is None:
        return None
    raw = base64.b64decode(d["b64"])
    return (
        np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
        .reshape(d["shape"])
        .copy()
    )


def encode_sample(sample: MeshSample) -> dict:
    """JSON-safe MeshSample: every array round-trips byte-exactly."""
    return {
        "coords": _enc_arr(sample.coords),
        "y": _enc_arr(sample.y),
        "theta": _enc_arr(sample.theta),
        "funcs": [_enc_arr(f) for f in sample.funcs],
    }


def decode_sample(d: dict) -> MeshSample:
    return MeshSample(
        coords=_dec_arr(d["coords"]),
        y=_dec_arr(d["y"]),
        theta=_dec_arr(d["theta"]),
        funcs=tuple(_dec_arr(f) for f in d["funcs"]),
    )


def topology_key(hosts: int, replicas_per_host: int) -> str:
    """Canonical topology identity for AOT manifest matching: a deploy
    manifest prewarmed for ``h2r3`` only hydrates a joiner in a 2-host,
    3-replica-per-host cluster."""
    return f"h{hosts}r{replicas_per_host}"


# --------------------------------------------------------------------------
# Failure detector: ALIVE → SUSPECT → DEAD, with dwell
# --------------------------------------------------------------------------

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


class FailureDetector:
    """Lease-based suspicion→dead detector.

    A host that stops acking heartbeats moves to SUSPECT after
    ``suspect_after_s`` of silence and to DEAD only after
    ``dead_after_s`` — the dwell between the two is the design point: a
    SUSPECT host is drained AROUND (hedged placements, no new work) but
    its in-flight work is left alone, because slowness is far more
    common than death and a false kill costs a migration storm. Any
    ack revives (DEAD → ALIVE is allowed: that is a partition healing;
    the lease renews and hedged duplicates are suppressed downstream).
    """

    def __init__(
        self,
        *,
        suspect_after_s: float = 2.0,
        dead_after_s: float = 6.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not 0 < suspect_after_s < dead_after_s:
            raise ValueError(
                "need 0 < suspect_after_s < dead_after_s (the dwell), "
                f"got {suspect_after_s} / {dead_after_s}"
            )
        self.suspect_after_s = suspect_after_s
        self.dead_after_s = dead_after_s
        self._clock = clock
        self._last: dict[str, float] = {}
        self._state: dict[str, str] = {}
        self._probe_start: dict[str, float] = {}  # first UNANSWERED probe

    def register(self, host: str) -> None:
        self._last[host] = self._clock()
        self._state[host] = ALIVE
        self._probe_start.pop(host, None)

    def probe(self, host: str) -> None:
        """Record that a liveness probe was just sent. Once probing is
        in use, silence is anchored at the first UNANSWERED probe — a
        controller that idles between registration and its first
        heartbeat round (replica warm-up, a long GC pause) must not
        have its OWN idle gap billed as host silence, or the first
        sweep after the gap declares every slow-to-ack host instantly
        dead without a single real probe going unanswered."""
        if host not in self._probe_start:
            self._probe_start[host] = self._clock()

    def ack(self, host: str) -> str:
        """Lease renewal — any ack, from any state, revives the host.
        Returns the PREVIOUS state, so the caller can reconcile a
        revival (a healed partition means frames were lost both ways —
        in-flight work on the revived host must be re-driven)."""
        old = self._state.get(host, DEAD)
        self._last[host] = self._clock()
        self._state[host] = ALIVE
        self._probe_start.pop(host, None)  # the probe was answered
        return old

    def state(self, host: str) -> str:
        return self._state.get(host, DEAD)

    def silent_s(self, host: str) -> float:
        now = self._clock()
        anchor = self._last.get(host, now)
        p = self._probe_start.get(host)
        if p is not None:
            anchor = max(anchor, p)
        return now - anchor

    def sweep(self) -> list[tuple[str, str, str]]:
        """Advance every host's state off lease age; returns the edge
        list ``[(host, old_state, new_state), ...]`` (empty when
        nothing changed). DEAD is sticky under silence — only
        :meth:`ack` leaves it."""
        edges: list[tuple[str, str, str]] = []
        for host in list(self._last):
            old = self._state[host]
            silent = self.silent_s(host)
            if silent >= self.dead_after_s:
                new = DEAD
            elif silent >= self.suspect_after_s:
                new = SUSPECT if old != DEAD else DEAD
            else:
                new = old  # freshness is recorded by ack(), not sweep
            if new != old:
                self._state[host] = new
                edges.append((host, old, new))
        return edges


# --------------------------------------------------------------------------
# Transports
# --------------------------------------------------------------------------


class InProcLink:
    """Deterministic in-proc transport: the SAME encoded frames as TCP,
    delivered synchronously on the caller's thread through real
    ``FrameDecoder`` state, with chaos hooks at the wire seam.

    Outbound (controller→agent) frames are ordinal-counted per link:
    ``net_partition@N`` partitions the link BOTH ways at the Nth frame
    (healed only by :meth:`heal_partition`), ``msg_drop@N`` silently
    drops the Nth frame, ``msg_delay@MS`` holds one frame for MS
    fake-clock milliseconds (released by :meth:`flush`, which
    ``ClusterRouter.tick`` calls). Replies cross the same partition
    check — a partition is a LINK failure, not a direction failure.
    """

    def __init__(
        self,
        agent: "HostAgent",
        *,
        faults=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._agent = agent
        self._faults = faults
        self._clock = clock
        self._n_out = 0
        self.partitioned = False
        self._pending: list[tuple[float, bytes]] = []  # (due, frame)
        self._on_message: Callable[[dict], None] | None = None
        self._to_agent = FrameDecoder()
        self._to_ctrl = FrameDecoder()

    # -- controller side ----------------------------------------------------
    def connect(self, on_message: Callable[[dict], None]) -> None:
        self._on_message = on_message

    def arm(self, faults) -> None:
        """(Re)attach a fault injector mid-stream — the federation
        builder arms chaos only after the handshake so the hello frame
        can never be the chaos victim."""
        self._faults = faults

    def send(self, msg: dict) -> bool:
        """Controller→agent. Returns False when the frame was eaten by
        a fault (partition/drop) or deferred by msg_delay."""
        frame = encode_frame(msg)
        self._n_out += 1
        f = self._faults
        if f is not None and f.maybe_net_partition(self._n_out):
            self.partitioned = True
        if self.partitioned:
            return False
        if f is not None and f.maybe_msg_drop(self._n_out):
            return False
        if f is not None:
            delay_ms = f.maybe_msg_delay()
            if delay_ms > 0:
                self._pending.append(
                    (self._clock() + delay_ms / 1000.0, frame)
                )
                return False
        self._deliver(frame)
        return True

    def flush(self) -> int:
        """Release every delayed frame whose due time has passed (the
        tick-driven half of ``msg_delay``). Returns frames released."""
        now = self._clock()
        due = [f for t, f in self._pending if t <= now]
        self._pending = [(t, f) for t, f in self._pending if t > now]
        for frame in due:
            if not self.partitioned:
                self._deliver(frame)
        return len(due)

    def heal_partition(self) -> None:
        self.partitioned = False

    def close(self) -> None:
        self._pending.clear()

    # -- delivery -----------------------------------------------------------
    def _deliver(self, frame: bytes) -> None:
        for msg in self._to_agent.feed(frame):
            self._agent.handle(msg, self._reply)

    def _reply(self, msg: dict) -> None:
        """Agent→controller: same partition, same codec."""
        if self.partitioned:
            return
        frame = encode_frame(msg)
        if self._on_message is None:
            return
        for m in self._to_ctrl.feed(frame):
            self._on_message(m)

    @property
    def protocol_errors(self) -> int:
        return (
            self._to_agent.garbage
            + self._to_agent.oversize
            + self._to_ctrl.garbage
            + self._to_ctrl.oversize
        )


class TcpLink:
    """Real loopback-TCP transport: a client socket to a
    ``HostAgent.listen`` endpoint, frames written whole under a lock,
    replies decoded on a reader thread and handed to ``connect``'s
    callback. No chaos hooks — determinism lives in ``InProcLink``;
    this transport exists so the protocol is proven against real
    sockets (partial reads, interleaved frames, peer close)."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.settimeout(0.2)
        # _wlock guards the WRITE path only: send() emits each frame
        # with one sendall under it, so concurrent senders can never
        # interleave partial frames. The reader thread needs no lock —
        # the socket is full-duplex and recv() has a single consumer.
        self._wlock = threading.Lock()
        self._decoder = FrameDecoder()
        self._on_message: Callable[[dict], None] | None = None
        self._closed = False
        self._reader: threading.Thread | None = None
        self.partitioned = False  # API parity with InProcLink

    def connect(self, on_message: Callable[[dict], None]) -> None:
        self._on_message = on_message
        self._reader = threading.Thread(
            target=self._read_loop, name="fed-link-reader", daemon=True
        )
        self._reader.start()

    def send(self, msg: dict) -> bool:
        frame = encode_frame(msg)
        with self._wlock:
            try:
                self._sock.sendall(frame)
                return True
            except OSError:
                return False

    def flush(self) -> int:
        return 0

    def heal_partition(self) -> None:
        self.partitioned = False

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                data = self._sock.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if not data:
                return
            for msg in self._decoder.feed(data):
                if self._on_message is not None:
                    self._on_message(msg)

    @property
    def protocol_errors(self) -> int:
        return self._decoder.garbage + self._decoder.oversize


# --------------------------------------------------------------------------
# HostAgent: one host's protocol server around its local pool
# --------------------------------------------------------------------------


class HostAgent:
    """The per-host half of the federation: speaks the wire protocol on
    behalf of one local ``ReplicaRouter`` (unchanged underneath).

    The agent is transport-agnostic — ``handle(msg, send)`` is the
    whole server, called by ``InProcLink`` synchronously or by the TCP
    accept loop per connection. Replies go through the ``send`` the
    message arrived with, so hedged controllers and multiple
    connections each get their own stream.

    Chaos: ``faults`` arms ``host_kill@N`` — the agent dies (stops
    handling AND stops sending; in-flight local work keeps running but
    its results never leave the host) immediately BEFORE handling its
    Nth inbound control message. That models a kill -9 between frames:
    the controller sees only silence and must detect it by lease.
    """

    def __init__(
        self,
        host_id: str,
        router,
        *,
        sink=None,
        faults=None,
        session_store=None,
        metrics=None,
        scale_cb: Callable[[str], int] | None = None,
        version: int = PROTOCOL_VERSION,
        topology: str | None = None,
        tracer=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.host_id = host_id
        self.router = router
        self.sink = sink
        self.faults = faults
        self.session_store = session_store
        self.metrics = metrics
        self.scale_cb = scale_cb
        self.version = version
        self.topology = topology
        # This host's span tracer (usually the SAME object its local
        # router/servers record into): trace_pull exports it, and
        # inbound trace_ctx fields are adopted against it — the host
        # honors the cluster's sampling decision, never its own.
        self.tracer = tracer
        self._clock = clock
        self.alive = True
        self.errors = 0  # inbound messages refused with ERROR
        self._n_in = 0  #: guarded_by _lock
        self._hb_seq_seen = -1
        # At-least-once discipline: the controller re-sends in-flight
        # work after a partition heals, so duplicates are NORMAL.
        # ``_inflight`` makes a duplicate placement a no-op (the live
        # future's callbacks already stream to the link); ``_outbox``
        # retains every terminal reply so a duplicate for finished work
        # re-sends the SAME result instead of re-running it.
        self._inflight: set[str] = set()  #: guarded_by _lock
        self._outbox: dict[str, dict] = {}  #: guarded_by _lock
        self._lock = threading.Lock()
        self._server_sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = False

    # -- lifecycle ----------------------------------------------------------
    def kill(self) -> None:
        """Silent death: no goodbye frame, no flush — exactly what the
        failure detector must be able to survive."""
        self.alive = False

    def drain_local(self, timeout_s: float = 30.0) -> dict:
        return self.router.drain(timeout_s=timeout_s)

    # -- protocol server ----------------------------------------------------
    def handle(self, msg: dict, send: Callable[[dict], None]) -> None:
        """Serve one inbound message. Schema violations answer ERROR
        and the stream continues — one bad frame never wedges the
        agent. All replies are suppressed once killed."""
        if not self.alive:
            return
        with self._lock:
            self._n_in += 1
            n = self._n_in
        if self.faults is not None and self.faults.maybe_host_kill(n):
            self.kill()
            return
        reply = self._guarded(send)
        try:
            validate_message(msg)
        except ProtocolError as e:
            self.errors += 1
            reply(wire(ERROR, reason=str(e), bad_kind=str(msg.get("kind"))))
            return
        kind = msg["kind"]
        if kind == SUBMIT_ROLLOUT and not msg.get("resume") and (
            "sample" not in msg
        ):
            self.errors += 1
            reply(
                wire(
                    ERROR,
                    reason="submit_rollout without resume needs a sample",
                    bad_kind=kind,
                )
            )
            return
        try:
            if kind == HELLO:
                self._on_hello(msg, reply)
            elif kind == HEARTBEAT:
                self._on_heartbeat(msg, reply)
            elif kind == SUBMIT:
                self._on_submit(msg, reply)
            elif kind == SUBMIT_ROLLOUT:
                self._on_submit_rollout(msg, reply)
            elif kind == DRAIN:
                summary = self.drain_local(
                    timeout_s=float(msg.get("timeout_s", 30.0))
                )
                reply(wire(DRAIN_OK, host=self.host_id, summary=summary))
            elif kind == STATS:
                series = (
                    self.metrics.snapshot() if self.metrics is not None else {}
                )
                reply(
                    wire(
                        STATS_OK,
                        seq=msg["seq"],
                        host=self.host_id,
                        series=series,
                    )
                )
            elif kind == PREWARM:
                stats = self.router.prewarm_from(msg["manifest"])
                reply(
                    wire(
                        PREWARM_OK, host=self.host_id, replicas=len(stats)
                    )
                )
            elif kind == SCALE:
                self._on_scale(msg, reply)
            elif kind == TRACE_PULL:
                if self.tracer is not None:
                    export = self.tracer.export()
                    coverage = self.tracer.coverage()
                else:
                    export = {"traceEvents": [], "otherData": {}}
                    coverage = {}
                reply(
                    wire(
                        TRACE_OK,
                        seq=msg["seq"],
                        host=self.host_id,
                        trace=export,
                        coverage=coverage,
                    )
                )
            else:
                # Agent→controller kinds arriving here are a peer bug.
                self.errors += 1
                reply(
                    wire(
                        ERROR,
                        reason=f"kind {kind!r} is not a controller request",
                        bad_kind=kind,
                    )
                )
        except ProtocolError as e:
            self.errors += 1
            reply(wire(ERROR, reason=str(e), bad_kind=kind))
        except Exception as e:  # hardening: one bad frame never wedges
            self.errors += 1
            reply(
                wire(ERROR, reason="internal", bad_kind=kind, detail=repr(e))
            )

    def _guarded(self, send: Callable[[dict], None]):
        def _send(msg: dict) -> None:
            if self.alive:
                send(msg)

        return _send

    # -- handlers -----------------------------------------------------------
    def _on_hello(self, msg: dict, reply) -> None:
        if int(msg["version"]) != self.version:
            reply(
                wire(
                    HELLO_REJECT,
                    version=int(msg["version"]),
                    want=self.version,
                    host=self.host_id,
                )
            )
            return
        out = wire(
            HELLO_OK,
            version=self.version,
            host=self.host_id,
            pool=len(self.router.pool()),
        )
        if self.topology is not None:
            out["topology"] = self.topology
        reply(out)

    def _on_heartbeat(self, msg: dict, reply) -> None:
        with self._lock:
            self._hb_seq_seen = max(self._hb_seq_seen, int(msg["seq"]))
        out = wire(
            HEARTBEAT_ACK,
            seq=int(msg["seq"]),
            host=self.host_id,
            load=self._load(),
            pool=len(self.router.pool()),
        )
        # Clock-alignment exchange (obs/dtrace.ClockSync): echo the
        # controller's send stamp, add our own clock. The agent does
        # NO arithmetic — the controller owns the midpoint estimate.
        if "t" in msg:
            out["t"] = msg["t"]
            out["agent_t"] = self._clock()
        reply(out)

    def _load(self) -> float:
        """The placement signal: live queue depth across the pool."""
        total = 0
        for rep in self.router.pool():
            server = getattr(rep, "server", None)
            if server is not None:
                try:
                    total += int(server.depth())
                except Exception:
                    pass
        return float(total)

    def _on_submit(self, msg: dict, reply) -> None:
        rid = msg["id"]
        with self._lock:
            done_msg = self._outbox.get(rid)
            running = rid in self._inflight
            if done_msg is None and not running:
                self._inflight.add(rid)
        if done_msg is not None:
            reply(done_msg)  # idempotent replay of the terminal result
            return
        if running:
            return  # live future's callback will stream the result
        sample = decode_sample(msg["sample"])
        fut = self.router.submit(
            sample,
            deadline_ms=msg.get("deadline_ms"),
            tenant=msg.get("tenant"),
            trace_ctx=dtrace.TraceContext.from_wire(msg.get("trace_ctx")),
        )

        def _done(f: Future) -> None:
            try:
                res = f.result()
                out = wire(
                    RESULT,
                    id=rid,
                    ok=bool(res.ok),
                    reason=res.reason,
                    output=_enc_arr(res.output),
                    latency_ms=res.latency_ms,
                    detail=res.detail,
                )
            except Exception as e:  # a local bug, surfaced honestly
                out = wire(
                    RESULT, id=rid, ok=False, reason="exception",
                    detail=str(e),
                )
            with self._lock:
                self._outbox[rid] = out
                self._inflight.discard(rid)
            reply(out)

        fut.add_done_callback(_done)

    def _on_submit_rollout(self, msg: dict, reply) -> None:
        rid = msg["id"]
        name = msg.get("name") or rid
        at_step = 0
        with self._lock:
            done_msg = self._outbox.get(rid)
            running = rid in self._inflight
            if done_msg is None and not running:
                self._inflight.add(rid)
        if done_msg is not None:
            reply(done_msg)  # idempotent replay of the terminal result
            return
        if running:
            # Reconcile duplicate for a session still executing here:
            # ack the placement; its live callbacks keep streaming.
            reply(wire(PLACED, id=rid, host=self.host_id, at_step=0))
            return

        def _on_step(sid: str, step: int, output) -> None:
            reply(
                wire(STEP, id=rid, step=int(step), output=_enc_arr(output))
            )

        if msg.get("resume"):
            # Re-migration: restore from the persisted snapshot. The
            # restored cursor is the replay point the controller's
            # session_remigrate event reports.
            state = None
            if self.session_store is not None:
                try:
                    state = self.session_store.load(name)
                except KeyError:
                    state = None
            if state is None:
                with self._lock:
                    self._inflight.discard(rid)
                reply(
                    wire(
                        ROLLOUT_DONE,
                        id=rid,
                        ok=False,
                        reason="no_snapshot",
                        detail=f"nothing persisted for {name!r}",
                    )
                )
                return
            at_step = int(state.get("cursor", 0))
            fut = self.router.resume_rollout(
                name,
                deadline_ms=msg.get("deadline_ms"),
                rollout_deadline_ms=msg.get("rollout_deadline_ms"),
                on_step=_on_step,
                trace_ctx=dtrace.TraceContext.from_wire(
                    msg.get("trace_ctx")
                ),
            )
        else:
            fut = self.router.submit_rollout(
                decode_sample(msg["sample"]),
                int(msg["steps"]),
                deadline_ms=msg.get("deadline_ms"),
                rollout_deadline_ms=msg.get("rollout_deadline_ms"),
                on_step=_on_step,
                name=name,
                tenant=msg.get("tenant"),
                trace_ctx=dtrace.TraceContext.from_wire(
                    msg.get("trace_ctx")
                ),
            )
        reply(wire(PLACED, id=rid, host=self.host_id, at_step=at_step))

        def _done(f: Future) -> None:
            try:
                res = f.result()
                out = wire(
                    ROLLOUT_DONE,
                    id=rid,
                    ok=bool(res.ok),
                    reason=res.reason,
                    steps_completed=int(res.steps_completed),
                    migrations=int(res.migrations),
                    drained_at_step=res.drained_at_step,
                    detail=res.detail,
                    # Full per-step outputs ride the terminal frame so
                    # step frames lost to a healed partition are
                    # repaired at cluster resolution.
                    outputs=[_enc_arr(o) for o in res.outputs],
                )
            except Exception as e:
                out = wire(
                    ROLLOUT_DONE, id=rid, ok=False,
                    reason="exception", detail=str(e),
                )
            with self._lock:
                self._outbox[rid] = out
                self._inflight.discard(rid)
            reply(out)

        fut.add_done_callback(_done)

    def _on_scale(self, msg: dict, reply) -> None:
        if self.scale_cb is None:
            reply(
                wire(
                    SCALE_OK,
                    host=self.host_id,
                    ok=False,
                    pool=len(self.router.pool()),
                    detail="no scale_cb wired",
                )
            )
            return
        pool = int(self.scale_cb(str(msg["direction"])))
        reply(wire(SCALE_OK, host=self.host_id, ok=True, pool=pool))

    # -- TCP server ---------------------------------------------------------
    def listen(self, port: int = 0) -> int:
        """Serve the protocol on loopback TCP; returns the bound port
        (``port=0`` asks the OS). One reader thread per connection —
        each connection gets its own framed reply writer."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(8)
        srv.settimeout(0.2)
        self._server_sock = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"fed-{self.host_id}", daemon=True
        )
        self._accept_thread.start()
        return srv.getsockname()[1]

    def stop(self) -> None:
        self._stopping = True
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._server_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._conn_loop,
                args=(conn,),
                name=f"fed-{self.host_id}-conn",
                daemon=True,
            ).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        conn.settimeout(0.2)
        wlock = threading.Lock()

        def _send(msg: dict) -> None:
            frame = encode_frame(msg)
            with wlock:
                try:
                    conn.sendall(frame)
                except OSError:
                    pass

        decoder = FrameDecoder()
        while not self._stopping:
            try:
                data = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            for msg in decoder.feed(data):
                self.handle(msg, _send)
        self.errors += decoder.garbage + decoder.oversize
        try:
            conn.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# ClusterRouter: the federation controller
# --------------------------------------------------------------------------


@dataclass
class _Pending:
    """One in-flight one-shot: where it has been placed (hedges add
    hosts) and the caller's future (first RESULT wins)."""

    rid: str
    sample: MeshSample
    deadline_ms: float | None
    tenant: str | None
    future: Future
    hosts: set[str] = field(default_factory=set)
    last_sent: float = 0.0  # clock of the last placement frame
    trace: str | None = None  # cluster trace id ("!"-prefixed = shadow)
    root_span: str | None = None  # first placement's span id (link anchor)
    t0: float = 0.0  # submit clock (the cluster_request span's start)


@dataclass
class _ClusterSession:
    """One cluster-owned rollout session: current owner host,
    high-water streamed step (replay suppression across migrations),
    and accumulated per-step outputs."""

    rid: str
    name: str
    steps: int
    owner: str
    future: Future
    on_step: Callable | None
    deadline_ms: float | None
    rollout_deadline_ms: float | None
    tenant: str | None
    sample: MeshSample | None = None  # retained for restart-from-zero
    streamed: int = 0  # high-water committed step seen by the cluster
    at_step: int = 0  # last placement's restored cursor
    migrations: int = 0
    restarts: int = 0  # no-snapshot restarts consumed (bounded)
    outputs: dict[int, np.ndarray] = field(default_factory=dict)
    last_sent: float = 0.0  # clock of the last placement frame
    acked: bool = False  # PLACED seen for the CURRENT placement
    last_resume: bool = False  # how the current placement was sent
    trace: str | None = None  # cluster trace id ("!"-prefixed = shadow)
    root_span: str | None = None  # first placement's span id (link anchor)
    t0: float = 0.0  # submit clock (the cluster_rollout span's start)


@dataclass
class _HostState:
    host_id: str
    link: object
    pool: int = 0
    load: float = 0.0
    hb_seq: int = 0
    last_series: dict = field(default_factory=dict)
    placed: int = 0  # placements routed here (hedges included)
    rtt_ms: float | None = None  # last heartbeat round-trip


class ClusterRouter:
    """The federation controller: places work across ``HostAgent``
    hosts, keeps leases, survives partitions and host death, drains
    the whole cluster to one ``cluster_summary``.

    Single-threaded control loop by design: the owner calls
    :meth:`tick` on whatever cadence it likes (tests drive a fake
    clock); inbound messages may arrive on any thread (TCP readers) —
    state is lock-guarded, and the lock is NEVER held across a
    ``link.send`` (the in-proc transport delivers synchronously, so a
    send can re-enter :meth:`_on_message` on the same stack).
    """

    def __init__(
        self,
        *,
        sink=None,
        clock: Callable[[], float] = time.monotonic,
        suspect_after_s: float = 2.0,
        dead_after_s: float = 6.0,
        manifests: dict[str, dict] | None = None,
        series_path: str | None = None,
        failover: bool = True,
        tracer=None,
        trace_path: str | None = None,
    ) -> None:
        self.sink = sink
        self.failover = failover  # False: the A/B twin — a dead host's
        # work resolves lost instead of re-placing (tools/federation_ab.py
        # measures what failover is worth against this baseline)
        self._clock = clock
        # Cluster-scoped tracing (obs/dtrace.py): the controller's
        # tracer owns the ONE head-sampling decision per request and
        # records the placement/hedge/redeliver/remigrate span chain;
        # trace_path is where drain() writes the stitched multi-host
        # file. clocks accumulates per-host offset estimates from the
        # stamped heartbeat exchanges whether or not tracing is on —
        # host_heartbeat reports them either way.
        self._tracer = tracer
        self._trace_path = trace_path
        self.clocks = dtrace.ClockSync()
        self.merged_trace: dict | None = None  # drain()'s stitched trace
        self.detector = FailureDetector(
            suspect_after_s=suspect_after_s,
            dead_after_s=dead_after_s,
            clock=clock,
        )
        self.manifests = dict(manifests or {})
        self._series_path = series_path
        self._series_seq = 0  #: guarded_by _lock
        self._lock = threading.RLock()
        self._hosts: dict[str, _HostState] = {}  #: guarded_by _lock
        self._pending: dict[str, _Pending] = {}  #: guarded_by _lock
        self._sessions: dict[str, _ClusterSession] = {}  #: guarded_by _lock
        self._session_by_name: dict[str, str] = {}  #: guarded_by _lock
        self._next_id = 0  #: guarded_by _lock
        self._hb_seq = 0  #: guarded_by _lock
        self._stats_seq = 0  #: guarded_by _lock
        self._drained = False  #: guarded_by _lock
        self.protocol_errors = 0  # controller-side schema violations
        # The honest ledger cluster_summary reports.
        #: guarded_by _lock
        self.counts = {
            "requests": 0,
            "completed": 0,
            "shed": 0,
            "suppressed": 0,
            "sessions": 0,
            "remigrated": 0,
            "lost": 0,
            "hosts_dead": 0,
        }

    # -- membership ---------------------------------------------------------
    def add_host(self, host_id: str, link) -> None:
        """Handshake and register one host. Version skew raises
        :class:`ProtocolError` LOUDLY — a skewed host must never join
        quietly and mis-parse frames mid-storm. If an AOT manifest is
        registered for the joiner's topology key, it is hydrated before
        taking traffic (warm join, no compile)."""
        with self._lock:
            if host_id in self._hosts:
                raise ValueError(f"host {host_id!r} already federated")
        state = _HostState(host_id=host_id, link=link)
        done = threading.Event()
        verdict: dict = {}

        def _on_message(msg: dict) -> None:
            if not done.is_set() and msg.get("kind") in (
                HELLO_OK,
                HELLO_REJECT,
            ):
                verdict.update(msg)
                done.set()
                return
            self._on_message(host_id, msg)

        link.connect(_on_message)
        link.send(wire(HELLO, version=PROTOCOL_VERSION))
        if not done.wait(timeout=5.0):
            raise ProtocolError(f"host {host_id!r}: no hello reply")
        if verdict["kind"] == HELLO_REJECT:
            raise ProtocolError(
                f"host {host_id!r} refused federation: protocol version "
                f"skew (ours {PROTOCOL_VERSION}, theirs {verdict['want']})"
            )
        state.pool = int(verdict.get("pool", 0))
        with self._lock:
            if host_id in self._hosts:
                # A racing add_host handshook the same id concurrently:
                # losing the race after a successful hello must not
                # silently replace the winner's registered state.
                raise ValueError(f"host {host_id!r} already federated")
            self._hosts[host_id] = state
        self.detector.register(host_id)
        manifest = self.manifests.get(verdict.get("topology"))
        if manifest is not None:
            link.send(wire(PREWARM, manifest=manifest))

    def hosts(self) -> list[str]:
        with self._lock:
            return list(self._hosts)

    def host_state(self, host_id: str) -> str:
        return self.detector.state(host_id)

    # -- placement ----------------------------------------------------------
    def _alive_hosts(self) -> list[_HostState]:
        with self._lock:
            return [
                h
                for h in self._hosts.values()
                if self.detector.state(h.host_id) == ALIVE
            ]

    def _pick_host(
        self, exclude: set[str] = frozenset()
    ) -> _HostState | None:
        """Least-loaded ALIVE host (SUSPECT hosts are drained around)."""
        candidates = [
            h for h in self._alive_hosts() if h.host_id not in exclude
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda h: (h.load, h.placed, h.host_id))

    def merged_load(self) -> dict[str, float]:
        """Per-host queue load from the last heartbeat acks — the
        cluster autoscaler's sensor."""
        with self._lock:
            return {h.host_id: h.load for h in self._hosts.values()}

    def autoscale_target(self, direction: str = "up") -> str | None:
        """Host an autoscale order should land on. Both directions
        target the LEAST-loaded live host: a scale-up lands where
        there is headroom to absorb the new replica's warmup, a
        scale-down removes capacity where it is least missed."""
        h = self._pick_host()
        return None if h is None else h.host_id

    def scale(self, direction: str, *, reason: str = "load") -> bool:
        target = self.autoscale_target(direction)
        if target is None:
            return False
        with self._lock:
            link = self._hosts[target].link
        return bool(link.send(wire(SCALE, direction=direction, reason=reason)))

    def submit(
        self,
        sample: MeshSample,
        *,
        deadline_ms: float | None = None,
        tenant: str | None = None,
    ) -> Future:
        """Place one one-shot on the least-loaded live host. Mirrors
        ``ReplicaRouter.submit``: the future resolves to a
        ``ServeResult`` (ok=False with reason ``no_host`` when no live
        host exists — shed honestly, never hung)."""
        fut: Future = Future()
        rid = self._new_id("q")
        pend = _Pending(
            rid=rid,
            sample=sample,
            deadline_ms=deadline_ms,
            tenant=tenant,
            future=fut,
            # Head sampling decided HERE, once — every host this
            # request touches (placement, hedge, re-delivery) honors
            # this id via the propagated trace_ctx.
            trace=(
                self._tracer.start_trace()
                if self._tracer is not None
                else None
            ),
            t0=self._clock(),
        )
        with self._lock:
            self.counts["requests"] += 1
            self._pending[rid] = pend
        if not self._place_oneshot(pend):
            self._resolve_oneshot(
                rid,
                ServeResult(
                    ok=False, reason="no_host", output=None,
                    detail="no live host", latency_ms=0.0,
                ),
            )
        return fut

    def _record_placement(
        self, trace: str | None, root_span: str | None, *,
        host: str, kind: str, **extra,
    ) -> str | None:
        """One controller-side ``placement`` span (instant — the frame
        send). The FIRST placement's span id becomes the link anchor:
        every later placement of the same request (hedge, re-delivery,
        re-migration, reconcile, restart) carries ``link_to`` pointing
        at it — linked spans of ONE trace, never a duplicate chain."""
        if self._tracer is None or trace is None:
            return None
        now = self._clock()
        args = {"host": host, "kind": kind, **extra}
        if root_span is not None:
            args["link_to"] = root_span
        return self._tracer.add_span(
            "placement", now, now, trace=trace,
            parent_id=root_span, args=args,
        )

    def _wire_ctx(
        self, trace: str | None, span_id: str | None, tenant: str | None
    ) -> dict | None:
        """The ``trace_ctx`` wire field for one placement, or None
        when cluster tracing is off for this request (an unsampled
        request with no flight recorder propagates nothing — the host
        must not start its own trace for it, and with no tracer at the
        controller there is no decision to honor)."""
        if trace is None:
            return None
        return dtrace.TraceContext(
            trace_id=trace,
            span_id=span_id,
            sampled=not trace.startswith("!"),
            tenant=tenant,
        ).to_wire()

    def _place_oneshot(self, pend: _Pending, kind: str = "place") -> bool:
        host = self._pick_host(exclude=pend.hosts)
        if host is None:
            return False
        msg = wire(
            SUBMIT,
            id=pend.rid,
            sample=encode_sample(pend.sample),
        )
        if pend.deadline_ms is not None:
            msg["deadline_ms"] = pend.deadline_ms
        if pend.tenant is not None:
            msg["tenant"] = pend.tenant
        sid = self._record_placement(
            pend.trace, pend.root_span, host=host.host_id, kind=kind
        )
        ctx = self._wire_ctx(pend.trace, sid or pend.root_span, pend.tenant)
        if ctx is not None:
            msg["trace_ctx"] = ctx
        with self._lock:
            if pend.root_span is None:
                pend.root_span = sid
            pend.hosts.add(host.host_id)
            pend.last_sent = self._clock()
            host.placed += 1
        host.link.send(msg)
        return True

    def submit_rollout(
        self,
        sample: MeshSample,
        steps: int,
        *,
        deadline_ms: float | None = None,
        rollout_deadline_ms: float | None = None,
        on_step: Callable | None = None,
        name: str | None = None,
        tenant: str | None = None,
    ) -> Future:
        """Place one rollout session. Every cluster session is NAMED
        (auto ``c%05d``) so the owner host persists its rolling
        snapshots — the migration substrate: if the owner dies, the
        session resumes on a survivor from the persisted cursor, and
        steps replayed below the cluster's high-water mark are
        suppressed. The future resolves to a ``RolloutResult``."""
        fut: Future = Future()
        rid = self._new_id("s")
        sess = _ClusterSession(
            rid=rid,
            name=name or rid,
            steps=int(steps),
            owner="",
            future=fut,
            on_step=on_step,
            deadline_ms=deadline_ms,
            rollout_deadline_ms=rollout_deadline_ms,
            tenant=tenant,
            sample=sample,
            # One trace id for the session's WHOLE cluster lifetime:
            # the first placement, every re-migration after a host
            # death, even a restart-from-zero all append to this id —
            # the resumed steps join the original trace.
            trace=(self._tracer.start_trace("r")
                   if self._tracer is not None else None),
            t0=self._clock(),
        )
        host = self._pick_host()
        with self._lock:
            self.counts["sessions"] += 1
            self._sessions[rid] = sess
            self._session_by_name[sess.name] = rid
        if host is None:
            self._resolve_session(
                rid, ok=False, reason="no_host", detail="no live host"
            )
            return fut
        self._send_rollout(sess, host, sample=sample, resume=False)
        return fut

    def _send_rollout(
        self,
        sess: _ClusterSession,
        host: _HostState,
        *,
        sample: MeshSample | None,
        resume: bool,
        kind: str = "place",
    ) -> None:
        msg = wire(
            SUBMIT_ROLLOUT,
            id=sess.rid,
            steps=sess.steps,
            name=sess.name,
            resume=resume,
        )
        if sample is not None:
            msg["sample"] = encode_sample(sample)
        if sess.deadline_ms is not None:
            msg["deadline_ms"] = sess.deadline_ms
        if sess.rollout_deadline_ms is not None:
            msg["rollout_deadline_ms"] = sess.rollout_deadline_ms
        if sess.tenant is not None:
            msg["tenant"] = sess.tenant
        sid = self._record_placement(
            sess.trace, sess.root_span, host=host.host_id, kind=kind
        )
        ctx = self._wire_ctx(sess.trace, sid or sess.root_span, sess.tenant)
        if ctx is not None:
            msg["trace_ctx"] = ctx
        with self._lock:
            if sess.root_span is None:
                sess.root_span = sid
            sess.owner = host.host_id
            sess.last_sent = self._clock()
            sess.acked = False  # each placement needs a fresh PLACED
            sess.last_resume = resume
            host.placed += 1
        host.link.send(msg)

    # -- inbound ------------------------------------------------------------
    def _on_message(self, host_id: str, msg: dict) -> None:
        """Controller-side dispatch. May run on a TCP reader thread or
        re-entrantly on the controller's own stack (in-proc sends) —
        hence the RLock, and no sends while holding it."""
        try:
            validate_message(msg)
        except ProtocolError:
            with self._lock:
                self.protocol_errors += 1
            return
        kind = msg["kind"]
        if kind == HEARTBEAT_ACK:
            was = self.detector.ack(host_id)
            now = self._clock()
            if "t" in msg and "agent_t" in msg:
                # Midpoint clock alignment: the probe's send stamp was
                # echoed back, the agent stamped its own clock while
                # handling it. One sample per round trip; offset() uses
                # the min-RTT sample in the window, so a congested ack
                # widens the error bound instead of skewing the offset.
                self.clocks.observe(
                    host_id, float(msg["t"]), now, float(msg["agent_t"])
                )
            # Read outside _lock: ClusterRouter._lock must never be
            # held across another acquire (ClockSync has its own lock).
            rtt = self.clocks.rtt_ms(host_id)
            with self._lock:
                h = self._hosts.get(host_id)
                if h is not None:
                    h.load = float(msg["load"])
                    h.pool = int(msg.get("pool", h.pool))
                    if rtt is not None:
                        h.rtt_ms = rtt
            if was != ALIVE:
                # Revival (partition healed / slow host caught up):
                # frames were lost BOTH ways while the link was down —
                # re-drive this host's in-flight work. Agents are
                # idempotent (inflight set + terminal outbox), so the
                # worst case is a replayed result the first-wins /
                # high-water suppression already handles.
                self._reconcile(host_id)
        elif kind == RESULT:
            res = ServeResult(
                ok=bool(msg["ok"]),
                reason=str(msg.get("reason") or ""),
                output=_dec_arr(msg.get("output")),
                detail=str(msg.get("detail") or ""),
                latency_ms=float(msg.get("latency_ms") or 0.0),
            )
            self._resolve_oneshot(msg["id"], res)
        elif kind == PLACED:
            with self._lock:
                sess = self._sessions.get(msg["id"])
                if sess is not None:
                    sess.at_step = int(msg["at_step"])
                    sess.acked = True
        elif kind == STEP:
            self._on_step(msg)
        elif kind == ROLLOUT_DONE:
            self._on_rollout_done(host_id, msg)
        elif kind in (STATS_OK,):
            with self._lock:
                h = self._hosts.get(host_id)
                if h is not None:
                    h.last_series = dict(msg["series"])
        elif kind == TRACE_OK:
            # Stashed next to the series snapshots: drain()'s waiter
            # polls for "_trace" exactly as it polls "_drain_summary".
            with self._lock:
                h = self._hosts.get(host_id)
                if h is not None:
                    h.last_series["_trace"] = msg["trace"]
                    if "coverage" in msg:
                        h.last_series["_trace_coverage"] = msg["coverage"]
        elif kind in (DRAIN_OK, PREWARM_OK, SCALE_OK, ERROR, HELLO_OK,
                      HELLO_REJECT):
            # DRAIN_OK is consumed by drain()'s waiter; the others are
            # informational acks — recorded, never fatal.
            with self._lock:
                h = self._hosts.get(host_id)
                if h is not None and kind == DRAIN_OK:
                    h.last_series["_drain_summary"] = msg["summary"]

    def _on_step(self, msg: dict) -> None:
        cb = None
        with self._lock:
            sess = self._sessions.get(msg["id"])
            if sess is None:
                return
            sess.acked = True  # a streamed step proves delivery even
            # when the PLACED ack itself was the dropped frame
            step = int(msg["step"])
            if step <= sess.streamed:
                # Replayed duplicate from a migration (or a hedge):
                # at-least-once delivery, exactly-once consumption.
                self.counts["suppressed"] += 1
                return
            sess.streamed = step
            sess.outputs[step] = _dec_arr(msg["output"])
            cb = sess.on_step
            name = sess.name
            out = sess.outputs[step]
        if cb is not None:
            cb(name, step, out)

    def _on_rollout_done(self, host_id: str, msg: dict) -> None:
        restart_to = None
        with self._lock:
            sess = self._sessions.get(msg["id"])
            if sess is None or sess.future.done():
                if sess is not None:
                    self.counts["suppressed"] += 1
                return
            if not msg["ok"] and sess.owner != host_id:
                # A failure report from a PREVIOUS owner (e.g. the dead
                # host's local failure surfacing after we re-migrated):
                # the new placement is authoritative.
                self.counts["suppressed"] += 1
                return
            if (
                not msg["ok"]
                and msg.get("reason") == "no_snapshot"
                and sess.sample is not None
                and sess.restarts < 3
            ):
                # The owner died before its first persisted snapshot —
                # there is nothing to resume, but the cluster still
                # holds the original sample: RESTART from step zero on
                # a survivor (deterministic engine → identical steps;
                # re-streamed prefixes are suppressed by the high-water
                # mark). Bounded, so a poisoned session cannot bounce
                # forever.
                sess.restarts += 1
                restart_to = True
        if restart_to:
            host = self._pick_host()
            if host is not None:
                self._send_rollout(
                    sess, host, sample=sess.sample, resume=False,
                    kind="restart",
                )
                return
        self._resolve_session(
            msg["id"],
            ok=bool(msg["ok"]),
            reason=msg.get("reason"),
            steps_completed=int(msg.get("steps_completed") or 0),
            drained_at_step=msg.get("drained_at_step"),
            local_migrations=int(msg.get("migrations") or 0),
            detail=msg.get("detail"),
            wire_outputs=msg.get("outputs"),
        )

    # -- resolution ---------------------------------------------------------
    def _resolve_oneshot(self, rid: str, res: ServeResult) -> None:
        with self._lock:
            pend = self._pending.pop(rid, None)
            if pend is None or pend.future.done():
                self.counts["suppressed"] += 1
                return
            self.counts["completed" if res.ok else "shed"] += 1
        if self._tracer is not None and pend.trace is not None:
            self._tracer.add_span(
                "cluster_request", pend.t0, self._clock(),
                trace=pend.trace, parent_id=None,
                args={
                    "ok": res.ok, "reason": res.reason or "ok",
                    "placements": len(pend.hosts),
                    "hosts": sorted(pend.hosts),
                },
            )
        pend.future.set_result(res)

    def _resolve_session(
        self,
        rid: str,
        *,
        ok: bool,
        reason: str | None,
        steps_completed: int = 0,
        drained_at_step=None,
        local_migrations: int = 0,
        detail=None,
        wire_outputs=None,
    ) -> None:
        with self._lock:
            sess = self._sessions.pop(rid, None)
            if sess is None or sess.future.done():
                return
            self._session_by_name.pop(sess.name, None)
            if ok:
                self.counts["completed"] += 1
            elif reason in ("host_dead", "no_host", "no_snapshot"):
                self.counts["lost"] += 1
            else:
                self.counts["shed"] += 1
            # Gap repair: step frames lost to a healed partition are
            # filled from the terminal frame's full output list
            # (deterministic engine — streamed and terminal copies of
            # one step are byte-identical, so precedence is moot).
            for i, enc in enumerate(wire_outputs or []):
                step = i + 1
                if step not in sess.outputs and enc is not None:
                    sess.outputs[step] = _dec_arr(enc)
            outputs = [sess.outputs[k] for k in sorted(sess.outputs)]
        if self._tracer is not None and sess.trace is not None:
            self._tracer.add_span(
                "cluster_rollout", sess.t0, self._clock(),
                trace=sess.trace, parent_id=None,
                args={
                    "ok": ok, "reason": str(reason or ("ok" if ok else "error")),
                    "session": sess.name,
                    "steps_completed": steps_completed or sess.streamed,
                    "migrations": sess.migrations + local_migrations,
                    "restarts": sess.restarts,
                },
            )
        sess.future.set_result(
            RolloutResult(
                ok=ok,
                reason=str(reason or ("ok" if ok else "error")),
                session=sess.name,
                steps=sess.steps,
                steps_completed=steps_completed or sess.streamed,
                outputs=outputs,
                drained_at_step=drained_at_step,
                migrations=sess.migrations + local_migrations,
                detail=str(detail or ""),
            )
        )

    # -- the control loop ---------------------------------------------------
    def tick(self) -> list[tuple[str, str, str]]:
        """One control-loop beat: flush delayed frames, probe leases,
        sweep the detector, react to edges (hedge around SUSPECT,
        declare + re-migrate on DEAD), publish merged per-host series.
        Returns the detector edges (tests assert on them)."""
        with self._lock:
            hosts = list(self._hosts.values())
            self._hb_seq += 1
            seq = self._hb_seq
        for h in hosts:
            h.link.flush()
        for h in hosts:
            # Every host gets probed, DEAD ones included: a partition
            # heal revives via the next ack — DEAD is not forever.
            # probe() anchors the silence clock BEFORE the send: an
            # in-proc ack arrives inline and clears it, an unanswered
            # probe starts the suspicion dwell from here, not from
            # whenever the controller last had time to tick.
            self.detector.probe(h.host_id)
            # The send stamp rides the probe; its echo in the ack is
            # one clock-alignment sample (see _on_message).
            h.link.send(wire(HEARTBEAT, seq=seq, t=self._clock()))
        edges = self.detector.sweep()
        for host_id, old, new in edges:
            if new == SUSPECT:
                self._hedge_around(host_id)
            elif new == DEAD:
                self._on_host_dead(host_id)
        self._redrive_stale()
        for h in hosts:
            off = self.clocks.offset(h.host_id)
            self._event(
                events.HOST_HEARTBEAT,
                host=h.host_id,
                seq=seq,
                state=self.detector.state(h.host_id),
                load=h.load,
                pool=h.pool,
                edge=next(
                    (f"{o}->{n}" for hid, o, n in edges if hid == h.host_id),
                    None,
                ),
                **(
                    {
                        "clock_offset_s": round(off[0], 6),
                        "clock_err_s": round(off[1], 6),
                    }
                    if off is not None
                    else {}
                ),
            )
        self._publish_series(hosts)
        return edges

    def _redrive_stale(self) -> None:
        """At-least-once re-delivery: a submit frame dropped on an
        otherwise-HEALTHY link hangs its future forever — heartbeats
        keep flowing, so no detector edge ever re-drives it (the
        reconcile/hedge/death paths all key off lease state). Re-send
        any placement unacknowledged for a full suspicion dwell:
        agents dedupe by request id (inflight set + terminal-outbox
        replay) and the controller suppresses duplicate replies, so a
        spurious re-send costs one suppressed result, never a fork."""
        now = self._clock()
        dwell = self.detector.suspect_after_s
        with self._lock:
            stale_pend = [
                p
                for p in self._pending.values()
                if not p.future.done()
                and p.hosts
                and now - p.last_sent >= dwell
            ]
            stale_sess = [
                s
                for s in self._sessions.values()
                if not s.acked
                and not s.future.done()
                and s.last_sent > 0.0
                and now - s.last_sent >= dwell
            ]
        for p in stale_pend:
            with self._lock:
                p.last_sent = now
            for host_id in sorted(p.hosts):
                if self.detector.state(host_id) == DEAD:
                    continue  # _on_host_dead owns the death path
                with self._lock:
                    host = self._hosts.get(host_id)
                if host is None:
                    continue
                msg = wire(
                    SUBMIT, id=p.rid, sample=encode_sample(p.sample)
                )
                if p.deadline_ms is not None:
                    msg["deadline_ms"] = p.deadline_ms
                if p.tenant is not None:
                    msg["tenant"] = p.tenant
                sid = self._record_placement(
                    p.trace, p.root_span, host=host_id, kind="redeliver"
                )
                ctx = self._wire_ctx(p.trace, sid or p.root_span, p.tenant)
                if ctx is not None:
                    msg["trace_ctx"] = ctx
                host.link.send(msg)
        for s in stale_sess:
            if self.detector.state(s.owner) == DEAD:
                continue
            with self._lock:
                host = self._hosts.get(s.owner)
            if host is None:
                continue
            # Replay the CURRENT placement verbatim: a dropped resume
            # stays a resume (a failed one falls through to the
            # restart-from-zero fallback), a dropped fresh submit
            # re-ships the sample.
            self._send_rollout(
                s,
                host,
                sample=None if s.last_resume else s.sample,
                resume=s.last_resume,
                kind="redeliver",
            )

    def _reconcile(self, host_id: str) -> None:
        """Re-drive a revived host's in-flight work: re-send every
        pending one-shot placed there and re-attach every session it
        owns (``resume=True`` — the agent acks a still-running session,
        replays a terminal outbox hit, or resumes from snapshot)."""
        with self._lock:
            host = self._hosts.get(host_id)
            pend = [
                p
                for p in self._pending.values()
                if host_id in p.hosts and not p.future.done()
            ]
            sessions = [
                s
                for s in self._sessions.values()
                if s.owner == host_id and not s.future.done()
            ]
        if host is None:
            return
        for p in pend:
            msg = wire(SUBMIT, id=p.rid, sample=encode_sample(p.sample))
            if p.deadline_ms is not None:
                msg["deadline_ms"] = p.deadline_ms
            if p.tenant is not None:
                msg["tenant"] = p.tenant
            sid = self._record_placement(
                p.trace, p.root_span, host=host_id, kind="reconcile"
            )
            ctx = self._wire_ctx(p.trace, sid or p.root_span, p.tenant)
            if ctx is not None:
                msg["trace_ctx"] = ctx
            with self._lock:
                p.last_sent = self._clock()
            host.link.send(msg)
        for s in sessions:
            self._send_rollout(
                s, host, sample=None, resume=True, kind="reconcile"
            )

    def _hedge_around(self, host_id: str) -> None:
        """SUSPECT reaction: duplicate this host's in-flight one-shots
        onto a healthy sibling. If the suspect was merely slow, the
        first RESULT wins and the loser is suppressed — the request
        never notices. Sessions are NOT hedged (two live writers of one
        session would fork it); they wait for the dwell."""
        with self._lock:
            pending = [
                p
                for p in self._pending.values()
                if host_id in p.hosts and not p.future.done()
            ]
        for pend in pending:
            # The hedge is a LINKED placement of the SAME trace — the
            # merged view shows one request fanning out, never a
            # second request chain (satellite 4's continuity check).
            self._place_oneshot(pend, kind="hedge")

    def _on_host_dead(self, host_id: str) -> None:
        """DEAD reaction: the dwell expired. Re-place every one-shot
        whose only placement was the dead host; re-migrate every owned
        session to a survivor from its persisted snapshot; resolve
        honestly (reason ``host_dead``) when no survivor exists."""
        with self._lock:
            self.counts["hosts_dead"] += 1
            silent = self.detector.silent_s(host_id)
            owned_sessions = [
                s for s in self._sessions.values() if s.owner == host_id
            ]
            sole_pending = [
                p
                for p in self._pending.values()
                if p.hosts == {host_id} and not p.future.done()
            ]
        self._event(
            events.HOST_DEAD,
            host=host_id,
            silent_s=round(silent, 3),
            sessions=len(owned_sessions),
            pending=len(sole_pending),
            reason="lease_expired",
        )
        for pend in sole_pending:
            if not self.failover or not self._place_oneshot(
                pend, kind="redeliver"
            ):
                self._resolve_oneshot(
                    pend.rid,
                    ServeResult(
                        ok=False, reason="host_dead", output=None,
                        detail=f"owner {host_id} dead, no survivor",
                        latency_ms=0.0,
                    ),
                )
        for sess in owned_sessions:
            survivor = (
                self._pick_host(exclude={host_id}) if self.failover else None
            )
            if survivor is None:
                self._resolve_session(
                    sess.rid,
                    ok=False,
                    reason="host_dead",
                    detail=f"owner {host_id} dead, no survivor",
                )
                continue
            from_host = sess.owner
            with self._lock:
                sess.migrations += 1
                self.counts["remigrated"] += 1
            self._send_rollout(
                sess, survivor, sample=None, resume=True, kind="remigrate"
            )
            self._event(
                events.SESSION_REMIGRATE,
                session=sess.name,
                from_host=from_host,
                to_host=survivor.host_id,
                at_step=sess.streamed,
                replay_from=sess.at_step,
                reason="host_dead",
            )

    def _publish_series(self, hosts: list[_HostState]) -> None:
        """Merged per-host metrics row: every host's registry snapshot
        with keys prefixed ``host<id>/`` — one row a single
        ``metrics_report.py`` invocation can slice by host."""
        if self._series_path is None:
            return
        with self._lock:
            self._stats_seq += 1
            seq = self._stats_seq
        for h in hosts:
            if self.detector.state(h.host_id) == ALIVE:
                h.link.send(wire(STATS, seq=seq))
        merged: dict = {}
        with self._lock:
            self._series_seq += 1
            row_seq = self._series_seq
            for h in hosts:
                for key, st in h.last_series.items():
                    if key.startswith("_"):
                        continue
                    merged[f"{h.host_id}/{key}"] = st
        row = {"seq": row_seq, "t": self._clock(), "series": merged}
        with open(self._series_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row) + "\n")

    # -- drain --------------------------------------------------------------
    def drain(self, timeout_s: float = 30.0) -> dict:
        """Coordinated cluster drain: every live host drains its local
        pool; every still-pending cluster future resolves (drained
        one-shots as shed, unfinished sessions honestly); ONE
        ``cluster_summary`` event reports the ledger. Idempotent."""
        with self._lock:
            if self._drained:
                return self._summary()
            self._drained = True
            hosts = list(self._hosts.values())
        per_host: dict[str, dict] = {}
        deadline = self._clock() + timeout_s
        for h in hosts:
            if self.detector.state(h.host_id) == DEAD:
                continue
            h.link.flush()
            h.link.send(wire(DRAIN, timeout_s=timeout_s))
        # TCP replies are asynchronous: poll for the summaries.
        while self._clock() < deadline:
            with self._lock:
                missing = [
                    h
                    for h in hosts
                    if self.detector.state(h.host_id) != DEAD
                    and "_drain_summary" not in h.last_series
                ]
            if not missing:
                break
            time.sleep(0.02)
        # Final series row at the drained registries' terminal values:
        # without it the last published row predates the storm's
        # completion and a per-host breakdown reads zero counters.
        self._publish_series(hosts)
        with self._lock:
            for h in hosts:
                if "_drain_summary" in h.last_series:
                    per_host[h.host_id] = h.last_series["_drain_summary"]
            leftover_pending = list(self._pending.keys())
            leftover_sessions = list(self._sessions.keys())
        for rid in leftover_pending:
            self._resolve_oneshot(
                rid,
                ServeResult(
                    ok=False, reason="drained", output=None,
                    detail="cluster drained", latency_ms=0.0,
                ),
            )
        for rid in leftover_sessions:
            self._resolve_session(
                rid, ok=False, reason="drained", detail="cluster drained"
            )
        summary = self._summary(per_host)
        if self._tracer is not None:
            summary["trace_coverage"] = self._stitch_traces(hosts)
        self._event(events.CLUSTER_SUMMARY, **summary)
        return summary

    def _stitch_traces(self, hosts: list[_HostState]) -> dict:
        """Drain-time trace assembly: pull every live host's export
        over ``TRACE_PULL``, rebase remote spans into the controller's
        clock frame via the heartbeat offset estimates, write ONE
        merged trace file, and return the per-source coverage stats
        (sampled/total plus clock offset ± uncertainty) that land in
        ``cluster_summary.trace_coverage``. Called AFTER the leftover
        futures resolved, so the controller's terminal
        ``cluster_request``/``cluster_rollout`` spans are included."""
        with self._lock:
            self._stats_seq += 1
            tseq = self._stats_seq
        live = [
            h for h in hosts if self.detector.state(h.host_id) != DEAD
        ]
        for h in live:
            h.link.flush()
            h.link.send(wire(TRACE_PULL, seq=tseq))
        tr_deadline = self._clock() + 5.0
        while self._clock() < tr_deadline:
            with self._lock:
                missing = [
                    h for h in live if "_trace" not in h.last_series
                ]
            if not missing:
                break
            time.sleep(0.02)
        exports = {"controller": self._tracer.export()}
        coverage: dict[str, dict] = {
            "controller": self._tracer.coverage()
        }
        offsets: dict[str, tuple[float, float]] = {}
        clock_meta = self.clocks.snapshot()
        with self._lock:
            for h in hosts:
                tr = h.last_series.get("_trace")
                if tr is not None:
                    exports[h.host_id] = tr
                cov = h.last_series.get("_trace_coverage")
                if cov is not None:
                    coverage[h.host_id] = dict(cov)
        for host_id, meta in clock_meta.items():
            offsets[host_id] = (
                meta["clock_offset_s"], meta["clock_err_s"]
            )
            coverage.setdefault(host_id, {}).update(meta)
        merged = dtrace.merge_traces(
            exports, offsets=offsets, controller="controller"
        )
        if self._trace_path is not None:
            dtrace.write_trace(self._trace_path, merged)
        self.merged_trace = merged
        return coverage

    def _summary(self, per_host: dict | None = None) -> dict:
        with self._lock:
            proto_errors = self.protocol_errors + sum(
                getattr(h.link, "protocol_errors", 0)
                for h in self._hosts.values()
            )
            return {
                "hosts": len(self._hosts),
                "requests": self.counts["requests"],
                "completed": self.counts["completed"],
                "shed": self.counts["shed"],
                "sessions": self.counts["sessions"],
                "remigrated": self.counts["remigrated"],
                "hosts_dead": self.counts["hosts_dead"],
                "per_host": per_host or {},
                "lost": self.counts["lost"],
                "protocol_errors": proto_errors,
            }

    # -- plumbing -----------------------------------------------------------
    def _new_id(self, prefix: str) -> str:
        with self._lock:
            self._next_id += 1
            return f"{prefix}{self._next_id:05d}"

    def _event(self, event: str, **fields) -> None:
        if self.sink is not None:
            self.sink.log(event=event, **fields)


# --------------------------------------------------------------------------
# Assembly helpers
# --------------------------------------------------------------------------


class _HostSink:
    """Per-host sink wrapper: tags every record with its host id so one
    merged event stream stays attributable (the events registry allows
    extra keys by contract — see ``obs/events.py``)."""

    def __init__(self, inner, host_id: str) -> None:
        self._inner = inner
        self.host_id = host_id

    def log(self, **fields) -> None:
        if self._inner is not None:
            self._inner.log(host=self.host_id, **fields)

    def flush(self) -> None:
        if self._inner is not None and hasattr(self._inner, "flush"):
            self._inner.flush()


def build_local_federation(
    replica_groups,
    *,
    sink=None,
    clock: Callable[[], float] = time.monotonic,
    suspect_after_s: float = 2.0,
    dead_after_s: float = 6.0,
    session_store=None,
    link_faults: dict[str, object] | None = None,
    host_faults: dict[str, object] | None = None,
    manifests: dict[str, dict] | None = None,
    series_path: str | None = None,
    router_kwargs: dict | None = None,
    metrics_factory: Callable | None = None,
    tcp_base_port: int = 0,
    failover: bool = True,
    tracer_factory: Callable[[str], object] | None = None,
    cluster_tracer=None,
    trace_path: str | None = None,
    recorders: dict[str, "dtrace.FlightRecorder"] | None = None,
) -> tuple[ClusterRouter, dict[str, "HostAgent"]]:
    """Wire a whole loopback federation in one call: one
    ``ReplicaRouter`` + ``HostAgent`` per replica group, in-proc links
    (chaos-hookable per host via ``link_faults`` / ``host_faults``),
    one shared ``SessionStore`` (the migration substrate — a survivor
    must be able to READ the dead host's snapshots; on one machine that
    is one directory, in production a shared object store), and a
    ``ClusterRouter`` over the lot. Returns ``(cluster, agents)``.

    ``tcp_base_port`` > 0 runs the real loopback-TCP transport instead
    of in-proc links: ``host<i>`` listens on ``tcp_base_port + i`` and
    the controller connects a ``TcpLink`` to it (chaos hooks are
    in-proc-only — ``link_faults`` is rejected here).

    Cluster tracing (obs/dtrace.py): ``cluster_tracer`` makes the
    controller the head-sampling authority and records the placement
    chain; ``tracer_factory(host_id)`` builds each host's local tracer
    (drained over ``TRACE_PULL`` and stitched into ``trace_path``);
    ``recorders[host_id]`` wraps that host's sink in a
    :class:`~gnot_tpu.obs.dtrace.FlightRecorderSink` so anomaly events
    dump the host's black box. A ``recorders["controller"]`` entry
    wraps the CONTROLLER's sink the same way — ``host_dead`` (and any
    other trigger event the controller emits) fires there, since a
    dead host can no longer dump its own black box.
    """
    from gnot_tpu.serve.router import ReplicaRouter

    if tcp_base_port and link_faults:
        raise ValueError(
            "link_faults are in-proc chaos hooks; the TCP transport "
            "(tcp_base_port) has none — drop one or the other"
        )
    ctrl_recorder = (recorders or {}).get("controller")
    cluster = ClusterRouter(
        sink=(
            dtrace.FlightRecorderSink(sink, ctrl_recorder)
            if ctrl_recorder is not None
            else sink
        ),
        clock=clock,
        failover=failover,
        suspect_after_s=suspect_after_s,
        dead_after_s=dead_after_s,
        manifests=manifests,
        series_path=series_path,
        tracer=cluster_tracer,
        trace_path=trace_path,
    )
    agents: dict[str, HostAgent] = {}
    kwargs = dict(router_kwargs or {})
    for i, replicas in enumerate(replica_groups):
        host_id = f"host{i}"
        host_sink: object = (
            _HostSink(sink, host_id) if sink is not None else None
        )
        recorder = (recorders or {}).get(host_id)
        if recorder is not None:
            host_sink = dtrace.FlightRecorderSink(host_sink, recorder)
        metrics = metrics_factory() if metrics_factory is not None else None
        tracer = (
            tracer_factory(host_id) if tracer_factory is not None else None
        )
        host_kwargs = dict(kwargs)
        if tracer is not None:
            host_kwargs["tracer"] = tracer
        router = ReplicaRouter(
            replicas,
            sink=host_sink,
            clock=clock,
            session_store=session_store,
            persist_snapshots=session_store is not None,
            metrics=metrics,
            **host_kwargs,
        )
        agent = HostAgent(
            host_id,
            router,
            sink=host_sink,
            faults=(host_faults or {}).get(host_id),
            session_store=session_store,
            metrics=metrics,
            topology=topology_key(len(replica_groups), len(replicas)),
            tracer=tracer,
            clock=clock,
        )
        if tcp_base_port:
            port = agent.listen(tcp_base_port + i)
            link: object = TcpLink("127.0.0.1", port)
        else:
            link = InProcLink(agent, clock=clock)
        cluster.add_host(host_id, link)
        if not tcp_base_port:
            # Arm link chaos AFTER the handshake: faults target
            # steady-state traffic — an armed msg_delay/net_partition
            # eating the hello frame would wedge setup instead of
            # exercising resilience.
            link.arm((link_faults or {}).get(host_id))
        agents[host_id] = agent
    return cluster, agents
