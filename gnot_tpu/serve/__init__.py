"""Fault-tolerant inference serving (ROADMAP north star: "serves heavy
traffic from millions of users" — the request-level path the offline
``Trainer.predict`` never was).

Four pieces (docs/serving.md):

* ``engine`` — ``InferenceEngine``: validation, bucketed static-shape
  collate, jitted forward, atomic weight swap — extracted from
  ``Trainer.predict`` so train and serve share one forward path.
* ``batcher`` — per-bucket dynamic batching (flush on ``max_batch`` or
  ``max_wait_ms``); no batch ever spans two buckets, so the compiled-
  program count stays O(log L_max) under any request mix.
* ``policies`` — request deadlines (expired requests shed before
  dispatch), bounded-queue admission with fast-fail load shedding, and
  a circuit breaker tripping on non-finite outputs / device errors.
* ``server`` — ``InferenceServer``: the worker loop composing the
  above, graceful SIGTERM drain (resilience.preemption), hot
  checkpoint reload via the ``Checkpointer`` fallback chain, and
  ``queue_depth``/``shed``/``breaker_*``/``reload``/``serve_summary``
  events through the ordinary ``MetricsSink``.

Replicated serving (docs/serving.md "Replicated serving") multiplies
the single-server tier:

* ``replica`` — ``EngineReplica`` + ``build_replicas``: N engines over
  disjoint device slices (the train stack's GSPMD ``NamedSharding``
  pattern at sub-mesh scale), each carrying its bucket-affinity set
  and rolling-reload warming flag.
* ``router`` — ``ReplicaRouter``: per-request placement over the
  replicas — health-gated (breaker/wedge/warming signals drain a sick
  replica to its siblings), bucket-affinity by default (a bucket's
  one-off compile lands on exactly one replica), with rolling
  hot-reload across the pool, live scale-out (``add_replica``), and a
  pool-level ``serve_summary`` rollup.
* ``rollout`` — stateful autoregressive rollout sessions (docs/
  serving.md "Rollout serving"): one request becomes K chained
  dispatches with the carry resident on the owning replica, rolling
  host-side snapshots, streaming partial results, and router-driven
  session migration when the owner dies mid-rollout — zero lost
  sessions, every future still always resolves.
* ``aot`` — the deploy-time cold-start pipeline (docs/serving.md
  "Deploy-time prewarm"): enumerate the serving program family,
  ``jit(...).lower().compile()`` it into the persistent compile cache,
  snapshot the executables, and hydrate warm replicas from the
  manifest (``prewarm_from``) so scale-out/reload never pays an XLA
  compile.

Elastic capacity (docs/serving.md "Elastic capacity") closes the loop
from the live metrics plane to pool membership:

* ``autoscaler`` — ``AutoscaleController``: subscribes to the metrics
  registry / SLO evaluator and scales the pool against live pressure
  — prewarm-before-join on scale-out, drain-then-remove on scale-in
  (``ReplicaRouter.remove_replica``: placement stops, resident
  sessions migrate to siblings, the retired replica's latency history
  stays in the pool rollup), and self-healing replacement of
  dead/wedged/breaker-stuck replicas — under first-class stability
  guards (min/max bounds, per-direction cooldowns, hysteresis, flap
  suppression).
* ``rollout.SessionStore`` — on-disk final-carry persistence: a
  drained session resumes across server restarts
  (``resume_rollout``) from its last snapshotted step.

Multi-tenant isolation (docs/serving.md "Multi-tenant isolation"):
``policies.TenantPolicy`` composes per-tenant WFQ weights (the batcher
drains per-tenant sub-queues deficit-round-robin within priority
tiers), pool-wide admission quotas (O(1) ``shed_tenant_quota``
fast-fail), and interactive/batch priority classes; per-tenant SLO
objectives attribute autoscale pressure to the tenant burning budget,
and batch-only pressure is answered by deferral instead of replicas.
With no tenant specs configured the plane is entirely absent — the
single-tenant path is byte-for-byte unchanged.

Topology-honest federation (docs/distributed.md) lifts the pool tier
to a multi-host control plane:

* ``federation`` — ``HostAgent`` wraps each host's local
  ``ReplicaRouter`` (unchanged underneath) behind a versioned,
  length-prefixed JSON wire protocol (the ``MESSAGES`` registry);
  ``ClusterRouter`` places one-shots and rollout sessions across
  hosts, keeps lease heartbeats through a suspicion→dead
  ``FailureDetector`` (SUSPECT hedges one-shots, DEAD re-migrates
  sessions cross-host from their persisted ``SessionStore``
  snapshots), tolerates partitions (revival reconcile replays the
  terminal outbox; duplicates are suppressed by id and high-water
  step), refuses version skew loudly, and drains the whole cluster to
  one ``cluster_summary``. Two transports: real loopback TCP
  (``HostAgent.listen`` + ``TcpLink``) and a deterministic in-proc
  link with chaos hooks at the wire seam. With ``--hosts 1`` the
  plane is entirely absent — the single-host path is byte-for-byte
  unchanged (pinned by ``tools/federation_ab.py``).

Chaos-tested on CPU via the serve-side fault kinds in
``resilience.faults`` (``slow_request@N``, ``nan_output@N``,
``reload_corrupt@N``, and the federation kinds ``host_kill@N``,
``net_partition@N``, ``msg_drop@N``, ``msg_delay@MS``) —
tests/test_serve.py, tests/test_autoscale.py, tests/test_federation.py.
"""

from gnot_tpu.serve import aot  # noqa: F401
from gnot_tpu.serve.autoscaler import AutoscaleController  # noqa: F401
from gnot_tpu.serve import rollout  # noqa: F401
from gnot_tpu.serve.batcher import Batcher  # noqa: F401
from gnot_tpu.serve.engine import InferenceEngine  # noqa: F401
from gnot_tpu.serve.policies import (  # noqa: F401
    DEFAULT_TENANT,
    PRIORITY_CLASSES,
    ROUTE_POLICIES,
    AdmissionController,
    CircuitBreaker,
    Deadline,
    ReplicaHealthPolicy,
    TenantPolicy,
)
from gnot_tpu.serve.federation import (  # noqa: F401
    ClusterRouter,
    FailureDetector,
    HostAgent,
    build_local_federation,
    topology_key,
)
from gnot_tpu.serve.replica import (  # noqa: F401
    EngineReplica,
    build_replica,
    build_replicas,
)
from gnot_tpu.serve.rollout import (  # noqa: F401
    RolloutFuture,
    RolloutResult,
    RolloutSession,
    SessionStore,
    advance_sample,
    offline_rollout,
)
from gnot_tpu.serve.router import ReplicaRouter  # noqa: F401
from gnot_tpu.serve.server import (  # noqa: F401
    CheckpointReloader,
    InferenceServer,
    ServeResult,
)
