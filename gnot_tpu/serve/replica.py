"""Engine replicas: N independently-placed ``InferenceEngine``s.

One ``InferenceEngine`` drives one worker loop — one compiled-program
pipeline, one queue, one failure domain. The replica tier multiplies
that: ``build_replicas`` splits the device set into N slices and builds
one engine per slice using the SAME GSPMD ``NamedSharding`` pattern the
train stack uses (``parallel/mesh.py``): each replica owns a sub-mesh
(one device, or a ``data``-axis slice of several), its params are
replicated WITHIN the slice, dispatch rows shard over the slice's
``data`` axis, and outputs replicate back — so a replica is just the
ordinary sharded forward at a smaller mesh. Replicas never communicate:
the only cross-replica coupling is the router's placement decision
(``serve/router.py``).

``EngineReplica`` carries the per-replica state the router routes on:

* **bucket affinity** — the set of bucket keys this replica has
  compiled (seeded by ``warm()``, extended when the router assigns a
  cold bucket). Affinity routing keeps each bucket's one-off XLA
  compile on ONE replica, so steady-state recompiles per replica stay
  O(log L_max) and a cold compile stalls one replica, never the pool.
* **warming** — set by the rolling hot-reload while this replica's
  weights swap; the router drains new traffic to siblings meanwhile
  (old weights keep serving whatever the replica already holds).

Thread-safety: the affinity set and warming flag are read by every
submitting thread and written by the router/reload threads — all access
goes through the replica's lock (graftlint GL004 enforces the
annotations).
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

from gnot_tpu.config import MeshConfig
from gnot_tpu.data.batch import MeshSample, PackPlan
from gnot_tpu.serve.engine import InferenceEngine, rename_forward
from gnot_tpu.serve.server import PACKED_BUCKET


class EngineReplica:
    """One engine + its routing state. The router attaches the replica's
    ``InferenceServer`` (``attach_server``) and consults
    ``has_bucket``/``warming``/``server.*`` probes on every placement
    decision."""

    def __init__(self, replica_id: int, engine: InferenceEngine):
        self.replica_id = replica_id
        self.engine = engine
        self.server = None  # InferenceServer, attached by the router
        self._lock = threading.Lock()
        # Bucket keys this replica has compiled programs for — the
        # affinity-routing state. Read by every submitting thread,
        # written on warmup and cold-bucket assignment.
        self._buckets: set = set()  #: guarded_by _lock
        # Rolling-reload drain flag: True while THIS replica's weights
        # are swapping (at most one replica warms at a time).
        self._warming = False  #: guarded_by _lock
        # Scale-in drain flag (router.remove_replica): True from the
        # moment the removal starts — the health policy reads the
        # replica "retiring" (no new placement) while it finishes what
        # it holds and hands its sessions to siblings.
        self._retiring = False  #: guarded_by _lock
        # How this replica became serve-ready — written once by
        # warm()/prewarm_from() before the replica takes traffic, read
        # by the router's serve_summary rollup and replica_warm event:
        # {"source": "compile"|"snapshot", "programs", "seconds",
        # "hits", "misses", ...}. None until warmed.
        self._warm_stats: dict | None = None  #: guarded_by _lock

    def attach_server(self, server) -> "EngineReplica":
        self.server = server
        return self

    # -- affinity ----------------------------------------------------------

    def warm(
        self,
        samples: Sequence[MeshSample],
        *,
        rows: int | None = None,
        pack_plan: PackPlan | None = None,
    ) -> int:
        """Precompile one program per bucket in ``samples`` (plus the
        packed program when a plan is given) and seed the affinity set
        with the warmed keys — the COLD path: each program pays a real
        trace + XLA compile (or a persistent-cache load) here. Records
        ``warm_stats`` (source "compile", cache hit/miss breakdown).
        Returns the number of programs warmed."""
        import time

        from gnot_tpu.utils.cache import compile_cache_probe

        t0 = time.monotonic()
        with compile_cache_probe() as cache:
            warmed = self.engine.warmup(samples, rows=rows)
            keys = {self.engine.bucket_key(s) for s in samples}
            if pack_plan is not None:
                warmed += self.engine.warmup_packed(samples, pack_plan)
                keys.add(PACKED_BUCKET)
        stats = {
            "source": "compile",
            "programs": warmed,
            "seconds": time.monotonic() - t0,
            "hits": cache["hits"],
            "misses": cache["misses"],
        }
        with self._lock:
            self._buckets |= keys
            if (
                self._warm_stats is not None
                and self._warm_stats.get("source") == "snapshot"
            ):
                # A warmup AFTER snapshot hydration is the residual
                # pass (buckets the manifest missed run their one cold
                # compile; hydrated ones dispatch through the AOT
                # table). Keep the snapshot provenance, record the
                # residual.
                self._warm_stats["warmup_after"] = stats
            else:
                self._warm_stats = stats
        return warmed

    def prewarm_from(
        self, manifest: dict, *, snapshot_dir: str | None = None
    ) -> dict:
        """Warm-replica hydration (serve/aot.py): install this
        replica's AOT-compiled executables from the deploy manifest's
        snapshots and seed the affinity set from the program list — no
        trace, no compile, no dispatch. A program whose snapshot is
        missing/unreadable degrades to the ordinary jit path (counted
        in ``skipped``), so a stale manifest can only make a replica
        colder, never wrong. Returns the recorded ``warm_stats``."""
        from gnot_tpu.serve import aot

        block = manifest.get("per_replica", {}).get(str(self.replica_id))
        if block is None:
            # Scale-out past the manifest's topology (e.g. a 5th
            # replica on a 4-replica manifest): colder, never wrong —
            # the replica warms via ordinary compiles.
            warm_stats = {
                "source": "none",
                "programs": 0,
                "skipped": 0,
                "seconds": 0.0,
                "hits": 0,
                "misses": 0,
                "reason": "no_manifest_block",
            }
            with self._lock:
                self._warm_stats = warm_stats
            return warm_stats
        if snapshot_dir is not None:
            manifest = {**manifest, "snapshot_dir": snapshot_dir}
        stats = aot.hydrate_block(self.engine, manifest, self.replica_id)
        keys = set()
        for entry in block["programs"]:
            if entry["key"] not in stats["keys"]:
                continue
            if entry["kind"] == "packed":
                keys.add(PACKED_BUCKET)
            else:
                keys.add((entry["pad_nodes"], entry["pad_funcs"]))
        warm_stats = {
            # A replica that installed nothing did NOT hydrate — its
            # warm provenance must not claim "snapshot" (the operator
            # reading replica_warm events would conclude the pool was
            # warm when every program compiles cold).
            "source": "snapshot" if stats["installed"] else "none",
            "programs": stats["installed"],
            "skipped": stats["skipped"],
            "seconds": stats["seconds"],
            # Snapshot hydration never consults the compile cache —
            # zero misses BY CONSTRUCTION, the number the prewarm smoke
            # asserts.
            "hits": stats["installed"],
            "misses": 0,
            # Wholesale-refusal provenance (e.g. params_mismatch): the
            # router/CLI surface it instead of silently serving cold.
            **({"reason": stats["reason"]} if "reason" in stats else {}),
        }
        with self._lock:
            self._buckets |= keys
            self._warm_stats = warm_stats
        return warm_stats

    @property
    def warm_stats(self) -> dict | None:
        with self._lock:
            return dict(self._warm_stats) if self._warm_stats else None

    def has_bucket(self, key) -> bool:
        with self._lock:
            return key in self._buckets

    def note_bucket(self, key) -> None:
        """The router assigned a cold bucket here: record it BEFORE the
        first request dispatches, so every later request of this bucket
        prefers this replica and the compile happens exactly once."""
        with self._lock:
            self._buckets.add(key)

    # -- rolling-reload drain flag -----------------------------------------

    @property
    def warming(self) -> bool:
        with self._lock:
            return self._warming

    def set_warming(self, value: bool) -> None:
        with self._lock:
            self._warming = value

    @property
    def retiring(self) -> bool:
        with self._lock:
            return self._retiring

    def set_retiring(self, value: bool) -> None:
        with self._lock:
            self._retiring = value


def build_replicas(
    model,
    params,
    n_replicas: int,
    *,
    batch_size: int,
    bucket: bool = True,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
    devices: Sequence | None = None,
    forward_fn: Callable | None = None,
    dtype: str = "float32",
) -> list[EngineReplica]:
    """N engine replicas over disjoint device slices.

    The device list splits into ``n_replicas`` contiguous slices (every
    slice the same size; a remainder is left idle — unequal replicas
    would skew the router's least-loaded signal). Each replica gets the
    train stack's GSPMD treatment at its own scale: a sub-``Mesh`` over
    its slice, params ``device_put`` replicated within it
    (``NamedSharding(mesh, P())``), batches sharded over the slice's
    ``data`` axis by ``parallel.mesh.shard_batch``, outputs replicated.
    A single-device slice degenerates to ordinary placement — same code
    path, mesh of one.

    ``batch_size`` (the serving dispatch row count) must divide by the
    slice size — every dispatch row-shards over the slice.
    ``forward_fn(params, batch)`` overrides the default
    ``apply_batch`` forward (it is jitted per replica with the slice's
    out-sharding).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from gnot_tpu.parallel import mesh as mesh_lib

    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    devices = list(devices if devices is not None else jax.devices())
    if n_replicas > len(devices):
        raise ValueError(
            f"{n_replicas} replicas need at least one device each; "
            f"only {len(devices)} visible (CPU: raise "
            "--xla_force_host_platform_device_count)"
        )
    per = len(devices) // n_replicas
    if batch_size % per:
        raise ValueError(
            f"batch_size {batch_size} must divide by the {per}-device "
            f"replica slice ({len(devices)} devices / {n_replicas} "
            "replicas): dispatch rows shard over the slice"
        )
    replicas = [
        build_replica(
            model,
            params,
            i,
            devices[i * per : (i + 1) * per],
            batch_size=batch_size,
            bucket=bucket,
            pad_nodes=pad_nodes,
            pad_funcs=pad_funcs,
            forward_fn=forward_fn,
            dtype=dtype,
        )
        for i in range(n_replicas)
    ]
    return replicas


def build_replica(
    model,
    params,
    replica_id: int,
    slice_devices: Sequence,
    *,
    batch_size: int,
    bucket: bool = True,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
    forward_fn: Callable | None = None,
    dtype: str = "float32",
) -> EngineReplica:
    """ONE replica on an explicit device slice — the scale-out unit.

    ``build_replicas`` is this in a loop over contiguous slices; the
    AOT prewarm CLI and a live scale-out (``ReplicaRouter.add_replica``)
    build individual replicas for slices of the SAME target topology,
    so replica ``i`` here and replica ``i`` at deploy-time prewarm sit
    on identical device assignments — the condition for its warm
    snapshot (device-bound XLA executables) to hydrate.

    ``dtype`` is the serving compute dtype (models/precision.py): the
    default forward runs the ``dtype``-compute model clone and the
    engine publishes a cast weight copy; ``params`` here (and every
    hot reload) stay f32 at rest."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from gnot_tpu.models import precision
    from gnot_tpu.parallel import mesh as mesh_lib

    if forward_fn is None:
        from gnot_tpu.train.trainer import apply_batch

        serve_model = precision.serve_model(model, dtype)
        forward_fn = lambda p, b: apply_batch(serve_model, p, b)  # noqa: E731
    per = len(slice_devices)
    if per < 1:
        raise ValueError("a replica needs at least one device")
    if batch_size % per:
        raise ValueError(
            f"batch_size {batch_size} must divide by the {per}-device "
            "replica slice: dispatch rows shard over the slice"
        )
    rmesh = mesh_lib.make_mesh(
        MeshConfig(data=per), devices=list(slice_devices)
    )
    replicated = NamedSharding(rmesh, PartitionSpec())
    rparams = jax.device_put(params, replicated)
    # One executable per replica is the POINT of the replica tier (N
    # fixed placements, not per-request retracing) — the
    # recompile-hazard rule is right in general and wrong here.
    forward = jax.jit(forward_fn, out_shardings=replicated)  # graftlint: disable=GL003 — one jit per replica slice, N is the replica count not traffic
    engine = InferenceEngine(
        model,
        rparams,
        batch_size=batch_size,
        bucket=bucket,
        pad_nodes=pad_nodes,
        pad_funcs=pad_funcs,
        dtype=dtype,
        forward=forward,
        # Fresh-jit factory for AOT snapshot compiles (serve/aot.py):
        # same fn, same out-sharding, NEW jit object (uniquely named
        # under a tag so the CPU backend cannot dedup it against
        # already-loaded kernels).
        forward_builder=lambda tag=None: jax.jit(
            rename_forward(forward_fn, tag), out_shardings=replicated
        ),
        device_put=lambda b, m=rmesh: mesh_lib.shard_batch(m, b),
        # Hot-reloaded params arrive as host arrays; re-placing
        # them under the replica's sharding keeps the swap from
        # forcing a recompile (and keeps the replica on its slice).
        place_params=lambda p, s=replicated: jax.device_put(p, s),
    )
    return EngineReplica(replica_id, engine)
