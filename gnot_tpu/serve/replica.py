"""Engine replicas: N independently-placed ``InferenceEngine``s.

One ``InferenceEngine`` drives one worker loop — one compiled-program
pipeline, one queue, one failure domain. The replica tier multiplies
that: ``build_replicas`` splits the device set into N slices and builds
one engine per slice using the SAME GSPMD ``NamedSharding`` pattern the
train stack uses (``parallel/mesh.py``): each replica owns a sub-mesh
(one device, or a ``data``-axis slice of several), its params are
replicated WITHIN the slice, dispatch rows shard over the slice's
``data`` axis, and outputs replicate back — so a replica is just the
ordinary sharded forward at a smaller mesh. Replicas never communicate:
the only cross-replica coupling is the router's placement decision
(``serve/router.py``).

``EngineReplica`` carries the per-replica state the router routes on:

* **bucket affinity** — the set of bucket keys this replica has
  compiled (seeded by ``warm()``, extended when the router assigns a
  cold bucket). Affinity routing keeps each bucket's one-off XLA
  compile on ONE replica, so steady-state recompiles per replica stay
  O(log L_max) and a cold compile stalls one replica, never the pool.
* **warming** — set by the rolling hot-reload while this replica's
  weights swap; the router drains new traffic to siblings meanwhile
  (old weights keep serving whatever the replica already holds).

Thread-safety: the affinity set and warming flag are read by every
submitting thread and written by the router/reload threads — all access
goes through the replica's lock (graftlint GL004 enforces the
annotations).
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

from gnot_tpu.config import MeshConfig
from gnot_tpu.data.batch import MeshSample, PackPlan
from gnot_tpu.serve.engine import InferenceEngine
from gnot_tpu.serve.server import PACKED_BUCKET


class EngineReplica:
    """One engine + its routing state. The router attaches the replica's
    ``InferenceServer`` (``attach_server``) and consults
    ``has_bucket``/``warming``/``server.*`` probes on every placement
    decision."""

    def __init__(self, replica_id: int, engine: InferenceEngine):
        self.replica_id = replica_id
        self.engine = engine
        self.server = None  # InferenceServer, attached by the router
        self._lock = threading.Lock()
        # Bucket keys this replica has compiled programs for — the
        # affinity-routing state. Read by every submitting thread,
        # written on warmup and cold-bucket assignment.
        self._buckets: set = set()  #: guarded_by _lock
        # Rolling-reload drain flag: True while THIS replica's weights
        # are swapping (at most one replica warms at a time).
        self._warming = False  #: guarded_by _lock

    def attach_server(self, server) -> "EngineReplica":
        self.server = server
        return self

    # -- affinity ----------------------------------------------------------

    def warm(
        self,
        samples: Sequence[MeshSample],
        *,
        rows: int | None = None,
        pack_plan: PackPlan | None = None,
    ) -> int:
        """Precompile one program per bucket in ``samples`` (plus the
        packed program when a plan is given) and seed the affinity set
        with the warmed keys. Returns the number of programs warmed."""
        warmed = self.engine.warmup(samples, rows=rows)
        keys = {self.engine.bucket_key(s) for s in samples}
        if pack_plan is not None:
            warmed += self.engine.warmup_packed(samples, pack_plan)
            keys.add(PACKED_BUCKET)
        with self._lock:
            self._buckets |= keys
        return warmed

    def has_bucket(self, key) -> bool:
        with self._lock:
            return key in self._buckets

    def note_bucket(self, key) -> None:
        """The router assigned a cold bucket here: record it BEFORE the
        first request dispatches, so every later request of this bucket
        prefers this replica and the compile happens exactly once."""
        with self._lock:
            self._buckets.add(key)

    # -- rolling-reload drain flag -----------------------------------------

    @property
    def warming(self) -> bool:
        with self._lock:
            return self._warming

    def set_warming(self, value: bool) -> None:
        with self._lock:
            self._warming = value


def build_replicas(
    model,
    params,
    n_replicas: int,
    *,
    batch_size: int,
    bucket: bool = True,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
    devices: Sequence | None = None,
    forward_fn: Callable | None = None,
) -> list[EngineReplica]:
    """N engine replicas over disjoint device slices.

    The device list splits into ``n_replicas`` contiguous slices (every
    slice the same size; a remainder is left idle — unequal replicas
    would skew the router's least-loaded signal). Each replica gets the
    train stack's GSPMD treatment at its own scale: a sub-``Mesh`` over
    its slice, params ``device_put`` replicated within it
    (``NamedSharding(mesh, P())``), batches sharded over the slice's
    ``data`` axis by ``parallel.mesh.shard_batch``, outputs replicated.
    A single-device slice degenerates to ordinary placement — same code
    path, mesh of one.

    ``batch_size`` (the serving dispatch row count) must divide by the
    slice size — every dispatch row-shards over the slice.
    ``forward_fn(params, batch)`` overrides the default
    ``apply_batch`` forward (it is jitted per replica with the slice's
    out-sharding).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from gnot_tpu.parallel import mesh as mesh_lib

    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    devices = list(devices if devices is not None else jax.devices())
    if n_replicas > len(devices):
        raise ValueError(
            f"{n_replicas} replicas need at least one device each; "
            f"only {len(devices)} visible (CPU: raise "
            "--xla_force_host_platform_device_count)"
        )
    per = len(devices) // n_replicas
    if batch_size % per:
        raise ValueError(
            f"batch_size {batch_size} must divide by the {per}-device "
            f"replica slice ({len(devices)} devices / {n_replicas} "
            "replicas): dispatch rows shard over the slice"
        )
    if forward_fn is None:
        from gnot_tpu.train.trainer import apply_batch

        forward_fn = lambda p, b: apply_batch(model, p, b)  # noqa: E731

    replicas = []
    for i in range(n_replicas):
        mesh_devices = devices[i * per : (i + 1) * per]
        rmesh = mesh_lib.make_mesh(MeshConfig(data=per), devices=mesh_devices)
        replicated = NamedSharding(rmesh, PartitionSpec())
        rparams = jax.device_put(params, replicated)
        # One executable per replica is the POINT of this loop (N fixed
        # placements, not per-request retracing) — the recompile-hazard
        # rule is right in general and wrong here.
        forward = jax.jit(forward_fn, out_shardings=replicated)  # graftlint: disable=GL003 — one jit per replica slice, N is the replica count not traffic
        engine = InferenceEngine(
            model,
            rparams,
            batch_size=batch_size,
            bucket=bucket,
            pad_nodes=pad_nodes,
            pad_funcs=pad_funcs,
            forward=forward,
            device_put=lambda b, m=rmesh: mesh_lib.shard_batch(m, b),
            # Hot-reloaded params arrive as host arrays; re-placing
            # them under the replica's sharding keeps the swap from
            # forcing a recompile (and keeps the replica on its slice).
            place_params=lambda p, s=replicated: jax.device_put(p, s),
        )
        replicas.append(EngineReplica(i, engine))
    return replicas
