"""InferenceEngine: THE forward path for inference, offline and serving.

Extracted from ``Trainer.predict``'s internals (validation, bucketed
static-shape collate, jitted forward, unpad slicing) so train-time
prediction and request serving share ONE code path — a divergence here
would mean "the model you validated is not the model you serve".

Two entry points:

* ``predict(samples)`` — the offline, all-at-once path with the exact
  semantics ``Trainer.predict`` always had (multi-batch loader with
  prefetch, mesh group padding, multi-process slice assembly).
* ``infer(samples, pad_nodes=, pad_funcs=, rows=)`` — ONE dispatch at
  one fully static shape, the serving hot path. The server's batcher
  guarantees every dispatch lands on a bucket boundary and the sample
  count is padded to a fixed row count, so the engine compiles at most
  one program per bucket: the O(log L) compiled-program bound of
  ``data/batch.py`` holds under any request mix (``compiled_shapes``
  counts the distinct signatures actually seen — the serving SLO the
  chaos suite asserts).

Params are swapped atomically under a lock (``swap_params``) — the hot
checkpoint reload path; a dispatch reads the reference once, so
in-flight requests always see one consistent weight set.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

import jax
import numpy as np

from gnot_tpu import native
from gnot_tpu.data.batch import (
    Loader,
    MeshSample,
    PackPlan,
    bucket_length,
    collate,
    pack_collate,
    pack_prefix,
    validate_samples,
)
from gnot_tpu.models import precision
from gnot_tpu.serve.catalog import bucket_program_key, packed_program_key
from gnot_tpu.utils import sanitizer


def rename_forward(fn: Callable, tag: str | None) -> Callable:
    """Wrap ``fn`` under a distinct ``__name__`` (hence a distinct HLO
    module name) when ``tag`` is set. The XLA CPU backend dedups
    compiles of an identically-named module against kernels already
    loaded in the process, which makes their executables
    unserializable — a unique name forces genuinely fresh codegen
    (serve/aot.py snapshot compiles). Identity when ``tag`` is None."""
    if tag is None:
        return fn

    def _renamed(p, b):
        return fn(p, b)

    _renamed.__name__ = _renamed.__qualname__ = f"gnot_snapshot_{tag}"
    return _renamed


class InferenceEngine:
    """Validated, bucketed, statically-shaped batched forward.

    ``forward(params, batch) -> [B, L, out]`` is the jitted forward; the
    default wraps ``apply_batch`` (the same forward invocation training
    uses). ``device_put`` places a host batch for the step (the
    trainer's mesh sharding hook; identity when absent). ``n_proc`` /
    ``p_idx`` / ``group_pad`` carry the multi-process predict()
    discipline (see Trainer.predict's docstring) — serving runs are
    single-process and leave them at defaults.
    """

    def __init__(
        self,
        model,
        params,
        *,
        batch_size: int,
        bucket: bool = True,
        pad_nodes: int = 0,
        pad_funcs: int = 0,
        forward: Callable | None = None,
        forward_builder: Callable | None = None,
        device_put: Callable | None = None,
        group_pad: bool = False,
        n_proc: int = 1,
        p_idx: int = 0,
        place_params: Callable | None = None,
        dtype: str = "float32",
    ):
        # Serving compute dtype (models/precision.py): "float32" is the
        # historical engine, byte-identical. "bfloat16" serves the SAME
        # f32-at-rest weights through the low-precision policy — the
        # default forward runs the bf16-compute model clone, batches
        # collate half-width via the fused pad-and-cast packer, and
        # swap_params publishes a bf16 weight COPY (the caller's tree
        # is never touched, so hot reload and train/serve sharing see
        # f32 exactly as before). Program identity is dtype-keyed:
        # dispatch signatures carry leaf dtypes, so an f32 and a bf16
        # program at the same shape never collide in the AOT table or
        # the compiled-shapes count.
        self.policy = precision.policy_for(dtype)
        self.dtype = dtype
        if dtype != "float32" and forward is None and forward_builder is None:
            model = precision.serve_model(model, dtype)
        self.model = model
        self.batch_size = batch_size
        self.bucket = bucket
        self.pad_nodes = pad_nodes
        self.pad_funcs = pad_funcs
        self._device_put = device_put or (lambda b: b)
        # Optional placement hook applied to every swap_params publish
        # (serve/replica.py): a replica engine re-places hot-reloaded
        # host params under its own mesh-slice sharding, so a reload
        # neither migrates the replica off its devices nor forces a
        # recompile. Identity when absent.
        self._place_params = place_params or (lambda p: p)
        if forward is None and forward_builder is None:
            from gnot_tpu.train.trainer import apply_batch

            def forward_builder(tag=None):
                fn = rename_forward(
                    lambda p, b: apply_batch(model, p, b), tag
                )
                return jax.jit(fn)

        if forward is None:
            forward = forward_builder()
        self._forward = forward
        # Factory for a FRESH jitted forward with identical options
        # (serve/aot.py): once a program has been LOADED in-process
        # (persistent-cache hit, snapshot hydration), the CPU backend
        # dedups later compiles of the same-named HLO module against
        # the loaded kernels and their executables re-serialize
        # without kernel code ("Symbols not found") — snapshot
        # compiles therefore need a brand-new jit object AND, via
        # ``tag``, a unique module name. None when the caller passed
        # only a prebuilt `forward` (AOT snapshots then degrade to
        # whatever that object compiles).
        self._forward_builder = forward_builder
        self.group_pad = group_pad
        self.n_proc = n_proc
        self.p_idx = p_idx
        self._lock = threading.Lock()  # published params + shape log
        # The published weight reference: swapped by reload callers,
        # read by the dispatch threads (graftlint GL004 enforces the
        # guarded_by annotation). Under a reduced-precision policy this
        # is the CAST COPY (cast-on-publish); the caller's f32 tree is
        # never mutated.
        self._params = self._place_params(
            precision.cast_params(params, dtype)
        ) if dtype != "float32" else params  #: guarded_by _lock
        # Distinct (B, L, Lf) dispatch signatures — a host-side proxy
        # for the number of XLA programs this engine forced. The chaos
        # suite bounds it by the bucket count; mutated by whichever
        # thread dispatches, read by the server's summary thread.
        self._shapes: set[tuple] = set()  #: guarded_by _lock
        # AOT-hydrated executables (serve/aot.py warm-replica
        # snapshots): dispatch-signature key -> loaded executable.
        # Dispatches whose signature is installed here run the
        # executable DIRECTLY — no trace, no compile, no cache lookup —
        # so a prewarmed replica's first request never waits on XLA.
        # Written by the prewarm path (router/CLI thread), read by the
        # worker's dispatches.
        self._aot: dict[tuple, Callable] = {}  #: guarded_by _lock
        # Dispatch provenance counters for the prewarm assertions
        # (serve_smoke --prewarm): how many dispatches ran through an
        # installed snapshot vs fell back to the jitted forward.
        self._aot_calls = 0  #: guarded_by _lock
        self._jit_calls = 0  #: guarded_by _lock
        # Program catalog (serve/catalog.py): when attached, the first
        # dispatch of each program also captures the executable's XLA
        # cost/memory analysis (one extra AOT-style compile, at warmup
        # in practice) under the AOT table's program key. Hydration
        # (serve/aot.py) pre-records every snapshot program's entry, so
        # a prewarmed engine never compiles for a cost probe.
        self._catalog = None

    # -- params ------------------------------------------------------------

    def swap_params(self, params) -> None:
        """Atomically publish a new weight set (hot reload). In-flight
        dispatches keep the reference they already read; the next
        dispatch sees the new one. No request is ever dropped or served
        a half-swapped tree. Cast-on-publish: a reduced-precision
        engine publishes a ``dtype`` COPY here (the one cast per
        reload), so reload sources keep handing over the same f32
        trees they always did."""
        params = self._place_params(precision.cast_params(params, self.dtype))
        with self._lock:
            self._params = params

    @property
    def params(self):
        with self._lock:
            return self._params

    # -- validation / bucketing --------------------------------------------

    def validate(self, samples: Sequence[MeshSample]) -> None:
        """Reject oversize (vs fixed pads) and non-finite inputs with
        the offending sample index (data.batch.validate_samples)."""
        validate_samples(
            samples, pad_nodes=self.pad_nodes, pad_funcs=self.pad_funcs
        )

    def bucket_key(self, sample: MeshSample) -> tuple[int, int]:
        """The static pad-shape this sample's dispatch must use:
        ``(pad_nodes, pad_funcs)``. Fixed trainer pads win (distributed
        training captured dataset-wide maxima); otherwise the bucketed
        (or exact, bucket=False) lengths. The batcher keys its queues
        on this, so no batch ever mixes two buckets."""
        n = sample.coords.shape[0]
        f = max((fn.shape[0] for fn in sample.funcs), default=0)
        if self.pad_nodes:
            pn = self.pad_nodes
        else:
            pn = bucket_length(n) if self.bucket else n
        if self.pad_funcs:
            pf = self.pad_funcs
        elif f:
            pf = bucket_length(f) if self.bucket else f
        else:
            pf = 0
        return pn, pf

    @property
    def compiled_shapes(self) -> int:
        """Distinct dispatch shapes seen so far (compiled-program
        bound proxy; one XLA program per entry)."""
        with self._lock:
            return len(self._shapes)

    # -- ahead-of-time programs (serve/aot.py) -----------------------------

    def place_batch(self, batch):
        """Place a host batch exactly as a live dispatch would (the
        trainer/replica mesh-sharding hook; identity otherwise) — the
        AOT pipeline lowers against THIS so the compiled signature is
        the one real dispatches hit."""
        return self._device_put(batch)

    def lower_program(self, batch):
        """``jit(...).lower()`` of the serving forward at ``batch``'s
        (already placed) signature — no execution, no compile. The AOT
        pipeline calls ``.compile()`` on the result at deploy time so
        the persistent cache (and the warm-replica snapshot) holds the
        executable before any replica serves."""
        return self._forward.lower(self.params, batch)

    def lower_fresh(self, batch, *, tag: str | None = None):
        """Like ``lower_program`` but on a brand-new jit object (see
        ``forward_builder``), optionally under a unique HLO module name
        (``tag``) — the compile this produces is genuinely fresh (and
        serializable) even when this program was already compiled or
        cache-loaded in this process."""
        fwd = (
            self._forward_builder(tag=tag)
            if self._forward_builder is not None
            else self._forward
        )
        return fwd.lower(self.params, batch)

    @staticmethod
    def signature_of(batch) -> tuple:
        """The dispatch-signature key of a (host or placed) batch —
        what the AOT executable table and ``compiled_shapes`` key on.
        Shape AND dtype per leaf: program identity is dtype-keyed, so
        an f32 and a bf16 program at the same shapes are two programs,
        never one table slot."""
        return tuple(
            (np.shape(l), str(np.asarray(l).dtype if not hasattr(l, "dtype") else l.dtype))
            for l in jax.tree.leaves(batch)
        )

    def install_program(self, signature: tuple, loaded: Callable) -> None:
        """Hydrate one AOT executable: dispatches whose batch matches
        ``signature`` run ``loaded(params, batch)`` directly instead of
        the jitted forward — zero trace/compile on the hot path."""
        with self._lock:
            self._aot[signature] = loaded

    @property
    def aot_programs(self) -> int:
        with self._lock:
            return len(self._aot)

    @property
    def dispatch_counts(self) -> dict:
        """``{"aot": n, "jit": m}`` — how many dispatches ran through an
        installed snapshot vs the jitted forward (the serve_smoke
        --prewarm assertion: a fully prewarmed storm has ``jit == 0``)."""
        with self._lock:
            return {"aot": self._aot_calls, "jit": self._jit_calls}

    def _run_forward(self, params, placed, timings: dict | None = None):
        """One forward execution: the installed AOT executable when this
        signature was hydrated, the jitted forward otherwise. A
        ``timings`` dict riding along gets ``timings["path"]`` — the
        dispatch provenance ("aot"/"jit") the server's jit-fallback
        counter and compile-span attribution read."""
        sig = self.signature_of(placed)
        with self._lock:
            loaded = self._aot.get(sig)
            if loaded is not None:
                self._aot_calls += 1
            else:
                self._jit_calls += 1
        if timings is not None:
            timings["path"] = "aot" if loaded is not None else "jit"
        return (loaded or self._forward)(params, placed)

    # -- program catalog (serve/catalog.py) --------------------------------

    def attach_catalog(self, catalog) -> None:
        """Wire (or detach, with None) the shared program catalog:
        dispatches then capture first-seen program costs and stamp
        their program key into ``timings`` for server attribution."""
        self._catalog = catalog

    @property
    def catalog(self):
        return self._catalog

    def _capture_costs(self, program: str, placed) -> None:
        """Record one program's XLA cost/memory analysis into the
        attached catalog, once per program key. The probe compiles via
        ``lower().compile()`` (the AOT pipeline's own path — the jit
        call's executable is not reachable from here), so it runs at
        most once per program; an entry pre-recorded by hydration or a
        manifest makes this a no-op. A failed probe records the
        explicit ``unavailable`` marker — never raises into a dispatch."""
        cat = self._catalog
        if cat is None or cat.has(program):
            return
        from gnot_tpu.obs.costs import extract_costs, unavailable_costs

        try:
            costs = extract_costs(
                self._forward.lower(self.params, placed).compile()
            )
        except Exception as e:  # a cost probe must never fail serving
            costs = unavailable_costs(
                f"capture failed: {type(e).__name__}"
            )
        cat.record(program, costs, source="compile")

    # -- the serving hot path ----------------------------------------------

    def infer(
        self,
        samples: Sequence[MeshSample],
        *,
        pad_nodes: int,
        pad_funcs: int,
        rows: int | None = None,
        timings: dict | None = None,
        clock: Callable[[], float] | None = None,
    ) -> list[np.ndarray]:
        """ONE dispatch at the fully static shape ``(rows, pad_nodes,
        pad_funcs)``: short batches are padded to ``rows`` with repeats
        of the last sample (dropped on return), so a bucket compiles
        exactly one program no matter how full its flushes run.
        Returns per-sample UNPADDED outputs ``[n_i, out]``. Callers
        (the server) validate and bucket upstream.

        ``timings`` (tracing hook, obs/tracing.py): when a dict is
        passed it is filled with ``phase -> (start, end)`` stamps for
        ``batch_assembly`` (collate + pad), ``device`` (forward
        dispatch + the blocking fetch — host wall-time until the
        outputs landed), and ``unpad`` (per-sample slicing), read on
        ``clock`` (the caller's monotonic clock; defaults to
        ``time.monotonic``). ``timings=None`` (the default) stamps
        nothing — the serving hot path is unchanged when tracing is
        off."""
        reqs = list(samples)
        if not reqs:
            return []
        rows = rows or self.batch_size
        if len(reqs) > rows:
            raise ValueError(
                f"infer() got {len(reqs)} samples for a {rows}-row dispatch"
            )
        tick = clock if clock is not None else time.monotonic
        if timings is not None:
            t0 = tick()
        batch = collate(
            reqs + [reqs[-1]] * (rows - len(reqs)),
            bucket=False,
            pad_nodes=pad_nodes,
            pad_funcs=pad_funcs,
            dtype=self.dtype,
        )
        fresh = self._note_shape(batch)
        program = None
        if timings is not None or self._catalog is not None:
            program = bucket_program_key(
                pad_nodes, pad_funcs, rows, self.dtype
            )
        params = self.params  # one consistent weight set per dispatch
        if timings is not None:
            t1 = tick()
            timings["batch_assembly"] = (t0, t1)
            timings["program"] = program
            timings["fresh_signature"] = fresh
        placed = self._device_put(batch)
        # host_fetch: np.asarray in off mode (byte-identical), a
        # defensive copy / registered view under GNOT_ALIAS_GUARD
        # (utils/sanitizer.py) — the engine-side sanitizer seam.
        out = sanitizer.host_fetch(
            self._run_forward(params, placed, timings)
        )
        if timings is not None:
            t2 = tick()
            timings["device"] = (t1, t2)
        # Batched native unpad: every response's [n_i, out] block is an
        # OWNED copy cut in one call (Python-loop slicing otherwise —
        # value-identical), so no response pins the dispatch buffer.
        outs = native.unpad_rows(
            out, [(i, 0, s.coords.shape[0]) for i, s in enumerate(reqs)]
        )
        if timings is not None:
            timings["unpad"] = (t2, tick())
        if self._catalog is not None:
            self._capture_costs(program, placed)
        return outs

    def infer_packed(
        self,
        samples: Sequence[MeshSample],
        plan: PackPlan,
        *,
        placements: Sequence[tuple[int, int]] | None = None,
        timings: dict | None = None,
        clock: Callable[[], float] | None = None,
    ) -> list[np.ndarray]:
        """ONE dispatch of MANY small requests packed into the plan's
        fixed shape — chunk-aligned contiguous segments sharing rows
        instead of one padded row per request ("pack, don't pad" on the
        serving hot path). The segment metadata keeps attention exactly
        per-sample (ops.attention.packed_normalized_linear_attention),
        so each request's output matches its solo padded dispatch to fp
        summation order; per-segment unpad returns exactly request i's
        ``[n_i, out]`` rows. One plan == one compiled program however
        full the dispatch runs. ``timings``/``clock``: the same tracing
        contract as ``infer``.
        """
        reqs = list(samples)
        if not reqs:
            return []
        if placements is None:
            placements = pack_prefix([s.coords.shape[0] for s in reqs], plan)
        if len(placements) != len(reqs):
            raise ValueError(
                f"infer_packed() got {len(reqs)} samples but only "
                f"{len(placements)} fit the plan {plan}; the batcher's "
                "take_fn must cut dispatches to the packable prefix"
            )
        tick = clock if clock is not None else time.monotonic
        if timings is not None:
            t0 = tick()
        batch = pack_collate(
            reqs,
            placements,
            n_rows=plan.n_rows,
            row_len=plan.row_len,
            chunk=plan.chunk,
            n_slots=plan.n_slots,
            pad_funcs=plan.pad_funcs,
            dtype=self.dtype,
        )
        fresh = self._note_shape(batch)
        program = None
        if timings is not None or self._catalog is not None:
            program = packed_program_key(plan, self.dtype)
        params = self.params  # one consistent weight set per dispatch
        if timings is not None:
            t1 = tick()
            timings["batch_assembly"] = (t0, t1)
            timings["program"] = program
            timings["fresh_signature"] = fresh
        placed = self._device_put(batch)
        # host_fetch: np.asarray in off mode (byte-identical), a
        # defensive copy / registered view under GNOT_ALIAS_GUARD
        # (utils/sanitizer.py) — the engine-side sanitizer seam.
        out = sanitizer.host_fetch(
            self._run_forward(params, placed, timings)
        )
        if timings is not None:
            t2 = tick()
            timings["device"] = (t1, t2)
        # Per-segment unpad, batched through the native scatter: each
        # request gets exactly its own [n_i, out] rows as an owned copy.
        outs = native.unpad_rows(
            out,
            [
                (r, off, s.coords.shape[0])
                for s, (r, off) in zip(reqs, placements)
            ],
        )
        if timings is not None:
            timings["unpad"] = (t2, tick())
        if self._catalog is not None:
            self._capture_costs(program, placed)
        return outs

    def warmup_packed(
        self, samples: Sequence[MeshSample], plan: PackPlan
    ) -> int:
        """Precompile the ONE packed program (a single representative
        dispatch, outputs discarded) — same startup discipline as
        ``warmup``. Returns 1 when a packable sample existed."""
        fits = [s for s in samples if plan.packable(s)]
        if not fits:
            return 0
        self.infer_packed(fits[:1], plan)
        return 1

    def _note_shape(self, batch) -> bool:
        """Log one dispatch signature. True iff it was NEW — on the jit
        path that dispatch is the one paying the program's XLA compile,
        which is what the server's compile-span attribution keys on."""
        key = self.signature_of(batch)
        with self._lock:
            fresh = key not in self._shapes
            self._shapes.add(key)
        return fresh

    def warmup(
        self, samples: Sequence[MeshSample], *, rows: int | None = None
    ) -> int:
        """Precompile one program per bucket present in ``samples``
        (one real dispatch each, outputs discarded). Serving startup
        calls this with representative traffic so the first live
        request of a bucket pays milliseconds, not an XLA compile —
        without it, a compile landing under tight deadlines sheds every
        request queued behind it. Returns the number of buckets
        warmed."""
        seen: set[tuple[int, int]] = set()
        for s in samples:
            key = self.bucket_key(s)
            if key in seen:
                continue
            seen.add(key)
            self.infer([s], pad_nodes=key[0], pad_funcs=key[1], rows=rows)
        return len(seen)

    # -- the offline path (Trainer.predict semantics) ----------------------

    def predict(self, samples: Sequence[MeshSample]) -> list[np.ndarray]:
        """Per-sample unpadded model outputs ``[n_i, out_dim]`` for an
        arbitrary sample list — the offline inference path
        ``Trainer.predict`` delegates to (see its docstring for the
        mesh / multi-process contract)."""
        samples = list(samples)
        self.validate(samples)
        n_real = len(samples)
        bs = self.batch_size
        # One dispatch covers `group` sample rows: the global batch
        # concatenates every host's bs-row slice in process order, so
        # global row r of dispatch i is samples[i*group + r].
        group = bs * self.n_proc if self.group_pad else bs
        if self.group_pad and n_real % group:
            samples = samples + [samples[-1]] * (group - n_real % group)
        if self.n_proc > 1:
            loader_samples = []
            for i in range(0, len(samples), group):
                loader_samples.extend(
                    samples[i + self.p_idx * bs : i + (self.p_idx + 1) * bs]
                )
        else:
            loader_samples = samples
        loader = Loader(
            loader_samples,
            bs,
            bucket=self.bucket,
            pad_nodes=self.pad_nodes,
            pad_funcs=self.pad_funcs,
            dtype=self.dtype,
        )
        params = self.params
        outs: list[np.ndarray] = []
        for bi, batch in enumerate(loader):
            # Multi-process: device_put assembles the global batch from
            # the per-host slices; the forward runs sharded and returns
            # the replicated [group, L, out] prediction.
            self._note_shape(batch)
            # host_fetch: the engine-side sanitizer seam (see infer).
            out = sanitizer.host_fetch(
                self._run_forward(params, self._device_put(batch))
            )
            outs.extend(
                native.unpad_rows(
                    out,
                    [
                        (j, 0, samples[bi * group + j].coords.shape[0])
                        for j in range(out.shape[0])
                    ],
                )
            )
        return outs[:n_real]
