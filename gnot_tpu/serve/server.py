"""Fault-tolerant inference serving loop.

``InferenceServer`` composes the pieces: requests enter through
bounded-queue admission (``policies.AdmissionController``), wait in the
per-bucket ``Batcher``, and are dispatched by one worker thread through
the ``InferenceEngine`` at static bucket shapes. Robustness policies
are applied in a fixed order at each dispatch:

1. **deadline shed** — expired requests leave BEFORE dispatch;
2. **circuit breaker** — open → instant "reject with reason" responses
   (never a hang behind a sick backend); trips on repeated non-finite
   outputs or device errors, recovers through a half-open trial;
3. **forward** — one static-shape dispatch per bucket;
4. **output finiteness** — non-finite outputs fail their requests and
   feed the breaker (a sick chip must not serve NaNs as answers).

Hot checkpoint reload (``reload()``) restores on the CALLER's thread —
the worker keeps serving the old weights throughout — then publishes
atomically via ``engine.swap_params``; the restore rides the
``Checkpointer`` fallback chain with a deadline-clamped retry budget,
so a corrupted ``latest`` degrades to an older checkpoint instead of
killing the serving process. Graceful drain (``drain()``, or SIGTERM
via ``resilience.preemption.PreemptionHandler``) stops admission,
completes every in-flight request, and emits a ``serve_summary`` event.

Every decision is observable: ``queue_depth`` / ``shed`` /
``breaker_open`` / ``breaker_close`` / ``reload`` / ``serve_summary``
events flow through the ordinary ``MetricsSink`` (schema in
docs/serving.md), so serving runs leave the same JSONL/manifest trail
training runs do.

With a ``pack_plan`` (``data/batch.py::PackPlan``, ``--serve_packed``)
the server additionally runs PACKED dispatch: every plan-fitting
request shares one ``PACKED_BUCKET`` whose dispatches are cut by
first-fit FIFO prefix packing (``pack_prefix``) — many small requests
ride ONE fixed-shape compiled program as chunk-aligned segments
(``engine.infer_packed``) instead of one padded row each, per-segment
unpad hands each request exactly its own nodes, and packing decisions
flow through the same spans/events as padded dispatches (the
``queue_depth`` event carries ``packed``/``real_tokens``/
``capacity_tokens``; ``serve_summary`` gains ``pad_waste_by_bucket``).
Oversize requests fall back to the ordinary padded per-bucket path.

Stateful rollout sessions (``serve/rollout.py``, ``submit_rollout``):
one request becomes K CHAINED dispatches — each committed step advances
the session's replica-resident carry and the next step re-enters the
ordinary admission/batcher/dispatch pipeline (so concurrent sessions at
different step indices batch and pack together, and every robustness
policy above applies per step). Completed steps emit ``rollout_step``
events and stream to the client; the carry is snapshotted host-side
every ``session_snapshot_every`` steps (``session_snapshot`` events —
the supervisor's rolling last-good pattern). A step that fails on a
backend signal (breaker, NaN, dispatch error, replica death) hands the
session back to the router's migration callback instead of losing it;
deadline/queue sheds terminate the session with the honest reason; a
drain mid-rollout persists a final snapshot and resolves the future
with the completed prefix plus a ``drained_at_step`` marker — a
session future, like a request future, ALWAYS resolves.

With a ``tracer`` (``obs/tracing.py``, ``--trace_path``) every request
additionally gets a ``trace_id`` at submit and a host-side span chain
``admission -> queue_wait -> batch_assembly -> dispatch -> device ->
unpad -> resolve``; batch-level phases are recorded per member request
with a ``member_trace_ids`` arg linking co-dispatched requests, shed/
breaker/reload events carry the ``trace_id`` so the event stream and
the trace correlate, and ``serve_summary`` gains the span-derived
per-bucket queue-wait vs device-time breakdown. Tracing off
(``tracer=None``, the default) leaves every path above untouched.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np

from gnot_tpu.data.batch import MeshSample, PackPlan, pack_prefix
from gnot_tpu.obs import events
from gnot_tpu.obs.metrics import LogHistogram, Reservoir
from gnot_tpu.obs.tracing import percentiles
from gnot_tpu.serve.batcher import Batcher
from gnot_tpu.serve.engine import InferenceEngine
from gnot_tpu.serve.policies import (
    DEFAULT_TENANT,
    AdmissionController,
    CircuitBreaker,
    Deadline,
)
from gnot_tpu.serve.rollout import RolloutFuture, RolloutSession

#: The bucket key every plan-fitting request shares under packed
#: dispatch mode (``pack_plan=``). Distinct from any ``(pn, pf)``
#: bucket tuple; the batcher's take_fn sizes its dispatches by
#: first-fit prefix packing instead of max_batch.
PACKED_BUCKET = ("packed",)

#: Terminal reasons a request can resolve with. "ok" carries an output;
#: everything else is a degraded reject-with-reason response.
REASONS = (
    "ok",
    "shed_deadline",
    "shed_queue_full",
    "shed_tenant_quota",
    "rejected_breaker_open",
    "rejected_invalid",
    "rejected_draining",
    "error_nan_output",
    "error_dispatch",
    # rollout-session step failures (serve/rollout.py)
    "error_replica_dead",
    "error_stale_session",
)

#: Step-failure reasons that indicate a SICK OWNER rather than a sick
#: request: the session is handed to the router's migration callback
#: (re-placed on a sibling from its snapshot) instead of terminating.
#: Deadline/queue/validation sheds stay terminal — a deadline storm
#: sheds sessions honestly, it does not bounce them around the pool.
MIGRATABLE_REASONS = frozenset(
    (
        "rejected_breaker_open",
        "error_nan_output",
        "error_dispatch",
        "error_replica_dead",
        "error_stale_session",
    )
)


@dataclasses.dataclass
class ServeResult:
    """What a request's Future resolves to — ALWAYS, on every path; a
    request is never left hanging."""

    ok: bool
    reason: str  # one of REASONS
    output: np.ndarray | None = None  # [n_i, out_dim] when ok
    detail: str = ""
    latency_ms: float = 0.0


@dataclasses.dataclass
class _Request:
    sample: MeshSample
    future: Future
    ordinal: int  # 1-indexed admission count (fault-injection key)
    submitted: float
    deadline: Deadline | None
    trace: str | None = None  # tracer trace_id; None = off / unsampled
    # Rollout-session step plumbing (serve/rollout.py): the owning
    # session (None = ordinary one-shot request) and the server's
    # 1-indexed rollout-step admission ordinal (the replica_kill/
    # stale_session/rollout_nan fault key).
    session: RolloutSession | None = None
    rollout_ordinal: int = 0
    # Tenant identity (docs/serving.md "Multi-tenant isolation"): the
    # submitter's tenant name, or None for untagged traffic — session
    # steps inherit their session's tenant. None everywhere when no
    # tenant config is given (the byte-for-byte default path).
    tenant: str | None = None


class _ReplicaKilled(Exception):
    """Internal control flow for the ``replica_kill`` fault: raised at
    the dispatch about to run, caught by the worker loop, which fails
    every in-system request (``error_replica_dead``) and exits — the
    router's ``dead`` health signal, with no Future left hanging."""


class InferenceServer:
    """One worker thread draining a bounded request queue through the
    engine. ``submit()`` is thread-safe and non-blocking (admission
    fast-fails); results arrive via ``concurrent.futures.Future``.

    ``reload_fn() -> (params, info) | None`` is the hot-reload source
    (``CheckpointReloader`` wraps a ``Checkpointer``); ``faults`` is a
    ``resilience.faults.FaultInjector`` with serve-side kinds armed.
    ``preempt`` is a ``PreemptionHandler`` whose triggered flag the
    worker polls — SIGTERM therefore drains gracefully instead of
    killing in-flight requests.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        max_batch: int = 4,
        max_wait_ms: float = 10.0,
        queue_limit: int = 64,
        default_deadline_ms: float = 0.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 1.0,
        sink=None,
        reload_fn: Callable | None = None,
        faults=None,
        preempt=None,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        pack_plan: PackPlan | None = None,
        replica: int | None = None,
        session_snapshot_every: int = 1,
        metrics=None,
        session_store=None,
        persist_snapshots: bool = False,
        catalog=None,
        tenants=None,
    ):
        self.engine = engine
        self.sink = sink
        # Replica identity (serve/router.py): when set, every event this
        # server emits and every span it records carries a ``replica``
        # field/arg, so an N-replica pool's one shared sink/tracer still
        # attributes each record to its engine (trace_report's
        # per-replica breakdown and the router's per-replica
        # serve_summary rollup key on it). None (the default) leaves
        # single-server output byte-identical to the pre-replica tier.
        self.replica = replica
        self.reload_fn = reload_fn
        self.faults = faults
        self.preempt = preempt
        self._clock = clock
        # obs.tracing.Tracer (or None = tracing off, zero added work).
        # The tracer's own clock is independent; span timestamps here
        # use OUR clock so queue-wait arithmetic is exact under the
        # fake clocks the tests inject.
        self._tracer = tracer
        self.default_deadline_ms = default_deadline_ms
        self.max_batch = max_batch
        self.admission = AdmissionController(queue_limit)
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            clock=clock,
        )
        # Packed dispatch mode ("pack, don't pad" on the serving hot
        # path): plan-fitting requests all share ONE bucket whose
        # dispatches are cut by first-fit FIFO prefix packing (many
        # small requests ride one fixed-shape program as chunk-aligned
        # segments) instead of one padded row per request. Oversize
        # requests fall back to the ordinary per-bucket padded path, so
        # packing never rejects traffic the padded server accepted.
        self.pack_plan = pack_plan
        # Multi-tenant isolation plane (policies.TenantPolicy, None =
        # off — every path byte-for-byte the single-tenant tier): the
        # policy gates per-tenant quotas at submit (fast-fail
        # "shed_tenant_quota" BEFORE the global admission gate) and
        # drives the batcher's per-tenant WFQ sub-queues. One policy
        # object is shared pool-wide under the router, so a tenant's
        # quota bounds its in-system count across replicas.
        self.tenants = tenants

        def key_fn(r):
            if pack_plan is not None and pack_plan.packable(r.sample):
                return PACKED_BUCKET
            return engine.bucket_key(r.sample)

        def take_fn(key, reqs):
            if key is not PACKED_BUCKET:
                return None
            return len(
                pack_prefix(
                    [r.sample.coords.shape[0] for r in reqs], pack_plan
                )
            )

        self.batcher = Batcher(
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            key_fn=key_fn,
            take_fn=take_fn if pack_plan is not None else None,
            tenants=tenants,
            # Untagged traffic under an active policy rides the default
            # tenant's sub-queue (weight 1, interactive, no quota).
            tenant_fn=lambda r: (
                r.tenant if r.tenant is not None else DEFAULT_TENANT
            ),
        )
        self._inbound: queue.Queue = queue.Queue()
        self._lock = threading.Lock()  # counters + admission ordinal
        self._worker: threading.Thread | None = None
        self._draining = threading.Event()
        self._drained = threading.Event()
        # Counters for serve_summary — shared between the client
        # threads (submit/reload/drain) and the worker (graftlint GL004
        # enforces the guarded_by annotations).
        self._submitted = 0  #: guarded_by _lock
        self._admitted = 0  #: guarded_by _lock
        self._completed = 0  #: guarded_by _lock
        self._shed: dict[str, int] = {}  #: guarded_by _lock
        self._dispatches = 0  #: guarded_by _lock
        self._reloads = 0  #: guarded_by _lock
        # BOUNDED latency retention (obs/metrics.py, ISSUE 14): the
        # windowed log-bucketed histogram is the percentile source
        # (O(1) memory, lossless pool merge, estimates within
        # metrics.REL_ERROR of exact nearest-rank) and the reservoir is
        # the bounded raw-sample escape hatch (`latencies_ms()`). Both
        # are internally locked — no `_lock` needed at the record/read
        # sites, so the publisher thread can poll mid-dispatch. When a
        # live `metrics` registry is attached, the histogram IS the
        # registry's per-replica series, so serve_summary and every
        # metrics_snapshot read the same buckets by construction.
        self._metrics = metrics
        lbl = {"replica": replica} if replica is not None else {}
        self._metric_labels = lbl
        if metrics is not None:
            self._lat_hist = metrics.histogram(
                "serve_request_latency_ms", **lbl
            )
            self._step_hist = metrics.histogram(
                "rollout_step_latency_ms", **lbl
            )
            self._c_requests = metrics.counter("serve_requests_total", **lbl)
            self._c_completed = metrics.counter(
                "serve_completed_total", **lbl
            )
            self._c_dispatches = metrics.counter(
                "serve_dispatches_total", **lbl
            )
            self._c_steps = metrics.counter("rollout_steps_total", **lbl)
            metrics.gauge(
                "serve_queue_depth",
                fn=lambda: self.admission.depth, **lbl,
            )
            metrics.gauge(
                "serve_breaker_open",
                fn=lambda: 1.0 if self.breaker.state == "open" else 0.0,
                **lbl,
            )
            metrics.gauge(
                "serve_resident_sessions",
                fn=self.resident_sessions, **lbl,
            )
        else:
            self._lat_hist = LogHistogram()
            self._step_hist = LogHistogram()
            self._c_requests = None
            self._c_completed = None
            self._c_dispatches = None
            self._c_steps = None
        self._lat_res = Reservoir()
        self._step_res = Reservoir()
        # Program catalog (serve/catalog.py): every executed dispatch is
        # attributed to its compiled program (requests/tokens/device
        # seconds) — the cost x traffic join behind the capacity model.
        # Attaching here also wires the ENGINE's compile-time cost
        # capture when nothing else did, so one ``catalog=`` knob arms
        # the whole plane per server (the router passes it per replica).
        self._catalog = catalog
        if (
            catalog is not None
            and getattr(engine, "catalog", None) is None
            and hasattr(engine, "attach_catalog")
        ):
            engine.attach_catalog(catalog)
        # Jit-fallback visibility (ISSUE 16): dispatches that ran the
        # jitted forward instead of an installed AOT executable were
        # invisible — now a per-replica counter (+ registry series) and,
        # for a FRESH signature, a dedicated `compile` trace span.
        self._jit_fallbacks = 0  #: guarded_by _lock
        self._c_jit_fallback = (
            metrics.counter("serve_jit_fallback_total", **lbl)
            if metrics is not None
            else None
        )
        # Pad-waste unification (ISSUE 16): with a live registry the
        # per-bucket token counters ARE the accounting — _summary reads
        # them back, so serve_summary.pad_waste_by_bucket and the
        # registry series cannot diverge (one ledger, two views). The
        # cache mirrors _bucket_hists (get-or-create off the hot path).
        self._pack_counters: dict = {}
        # Hot-path series caches: registry get-or-create is a string
        # build + lock per call — fine at shed/alert cadence, not per
        # completed request. Benign races (two threads missing the
        # cache together) resolve to the SAME registry object.
        self._bucket_hists: dict[str, LogHistogram] = {}
        self._shed_counters: dict = {}
        # Per-tenant accounting (docs/serving.md "Multi-tenant
        # isolation"): counts for the serve_summary `tenants` rollup
        # plus — with a live registry — the tenant_* series the
        # per-tenant SLO objectives burn against. Populated ONLY for
        # tagged requests, so the untagged default path adds no keys,
        # no series, no summary block. Histograms/counters are
        # internally locked; the plain dicts ride _lock.
        self._tenant_stats: dict[str, dict] = {}  #: guarded_by _lock
        self._tenant_hists: dict[str, LogHistogram] = {}
        self._tenant_counters: dict = {}
        # Span-derived per-bucket timing for serve_summary: bucket key
        # -> {"queue_ms": one wait per TRACED request (shed included),
        # "device_ms": the dispatch's device time once per traced
        # member}. The population and the (nearest-rank) percentiles
        # mirror tools/trace_report.py's bucket_breakdown exactly, so
        # the two views agree on any trace. Populated only when tracing
        # is on; mutated by the worker, snapshotted by _summary on the
        # drain thread.
        self._bucket_stats: dict = {}  #: guarded_by _lock
        # Per-bucket packing efficiency for serve_summary: bucket label
        # -> {"dispatches", "real_tokens", "capacity_tokens"} over ALL
        # dispatches (packed and padded alike — node tokens only, the
        # FLOP-dominant axis), i.e. the measured pad waste the packing
        # A/B (tools/pack_ab.py) compares. Mutated by the worker,
        # snapshotted by _summary on the drain thread.
        self._pack_stats: dict = {}  #: guarded_by _lock
        # Worker liveness stamp for replica health (serve/router.py):
        # refreshed once per worker-loop iteration, so a worker wedged
        # INSIDE a dispatch (straggling device, runaway compile) shows
        # a growing ``progress_age`` while requests sit in the system —
        # the router's wedge signal. Written by the worker, read by
        # router threads.
        self._last_progress = clock()  #: guarded_by _lock
        # Rollout-session state (serve/rollout.py): the resident-session
        # table (read by router load accounting — a replica holding many
        # sessions must not look idle), per-server session counters for
        # the serve_summary sessions rollup, the rollout-step admission
        # ordinal (the replica_kill/stale_session/rollout_nan fault
        # key), and the per-step latency population.
        if session_snapshot_every < 1:
            raise ValueError(
                "session_snapshot_every must be >= 1, got "
                f"{session_snapshot_every}"
            )
        self.session_snapshot_every = session_snapshot_every
        self._sessions: dict[str, RolloutSession] = {}  #: guarded_by _lock
        self._sessions_started = 0  #: guarded_by _lock
        self._sessions_completed = 0  #: guarded_by _lock
        self._sessions_drained = 0  #: guarded_by _lock
        self._sessions_shed = 0  #: guarded_by _lock
        self._sessions_failed = 0  #: guarded_by _lock
        self._rollout_steps = 0  #: guarded_by _lock
        # Set by _die (the replica_kill fault) the moment the worker
        # starts failing everything: the router must read this replica
        # as dead IMMEDIATELY — migration callbacks run on the dying
        # thread itself, before it has actually exited.
        self._dead = False  #: guarded_by _lock
        # On-disk session persistence (serve/rollout.py::SessionStore):
        # a drained session's final snapshot is written here so a
        # restarted server/router can resume it (resume_rollout).
        self._session_store = session_store
        # Rolling persistence (serve/federation.py): when on, every DUE
        # snapshot of a NAMED session is also written to the store, not
        # just the final drain-time one — the cross-host migration
        # substrate: a host killed without warning leaves its sessions'
        # last-good cursors on disk for a survivor to resume from. Off
        # by default: the single-host path keeps its drain-only write
        # pattern (and its byte-identical event stream).
        self._persist_snapshots = persist_snapshots
        # Scale-in eviction hook (router.remove_replica): when set, a
        # committed step hands its unfinished session to the callback
        # (re-placed on a sibling at a step boundary) instead of
        # chaining the next step here.
        self._evict_cb = None  #: guarded_by _lock

    # -- client side -------------------------------------------------------

    def start(self) -> "InferenceServer":
        if self._worker is not None:
            raise RuntimeError("server already started")
        self._worker = threading.Thread(
            target=self._run, name="gnot-serve-worker", daemon=True
        )
        self._worker.start()
        return self

    def submit(
        self,
        sample: MeshSample,
        *,
        deadline_ms: float | None = None,
        tenant: str | None = None,
        trace_ctx=None,
    ) -> Future:
        """Admit one request. Fast-fails (resolved Future, degraded
        reason) on: draining, full queue (load shedding at the door),
        exhausted tenant quota (``shed_tenant_quota`` — checked BEFORE
        the global gate, so a flooding tenant fails at ITS door without
        consuming shared admission), or invalid input (non-finite /
        oversize — validated HERE so a poison sample is rejected with
        its index named instead of NaN-ing a whole batch of innocent
        neighbors). ``tenant`` names the submitter (None = untagged;
        with no TenantPolicy configured the tag is carried for
        per-tenant accounting only). ``trace_ctx`` (an
        ``obs/dtrace.TraceContext``) is a sampling decision already
        made upstream — the cluster controller's — which this server
        ADOPTS instead of consulting its own counter, so one federated
        request is sampled identically on every host it touches."""
        fut: Future = Future()
        now = self._clock()
        # trace_id assignment happens AT SUBMIT (head sampling decides
        # once — here for local requests, at the ClusterRouter for
        # propagated ones); every later span/event for this request
        # reuses it, so even a shed request's events correlate.
        trace = (
            (
                self._tracer.adopt(trace_ctx)
                if trace_ctx is not None
                else self._tracer.start_trace()
            )
            if self._tracer is not None
            else None
        )
        if tenant is None and trace_ctx is not None:
            tenant = trace_ctx.tenant
        with self._lock:
            self._submitted += 1
        if self._c_requests is not None:
            self._c_requests.inc()
        self._note_tenant_request(tenant)
        if self._draining.is_set():
            self._trace_span(trace, "admission", now, reason="rejected_draining")
            return self._resolve_now(
                fut, "rejected_draining", now, tenant=tenant
            )
        try:
            self.engine.validate([sample])
        except ValueError as err:
            self._event(
                events.SHED, reason="rejected_invalid", detail=str(err),
                **({"trace_id": trace} if trace else {}),
            )
            self._trace_span(trace, "admission", now, reason="rejected_invalid")
            return self._resolve_now(
                fut, "rejected_invalid", now, detail=str(err), tenant=tenant
            )
        if self.tenants is not None:
            # Per-tenant quota gate FIRST (docs/serving.md "Multi-tenant
            # isolation"): a tenant over its bounded in-system count
            # fast-fails at its OWN door — O(1), tenant-tagged, and
            # without consuming shared admission, so a flooding tenant
            # cannot exhaust the pool-wide queue_limit siblings use.
            tname = tenant if tenant is not None else DEFAULT_TENANT
            if not self.tenants.try_admit(tname):
                self._count_shed("shed_tenant_quota")
                self._note_tenant_shed(tname, "shed_tenant_quota")
                self._event(
                    events.TENANT_QUOTA_SHED,
                    tenant=tname,
                    quota=self.tenants.quota(tname),
                    in_system=self.tenants.in_system(tname),
                    **({"trace_id": trace} if trace else {}),
                )
                self._trace_span(
                    trace, "admission", now, reason="shed_tenant_quota"
                )
                fut.set_result(
                    ServeResult(ok=False, reason="shed_tenant_quota")
                )
                return fut
        if not self.admission.try_admit():
            if self.tenants is not None:
                self.tenants.release(
                    tenant if tenant is not None else DEFAULT_TENANT
                )
            self._count_shed("shed_queue_full")
            self._note_tenant_shed(tenant, "shed_queue_full")
            self._event(
                events.SHED,
                reason="shed_queue_full",
                depth=self.admission.depth,
                limit=self.admission.limit,
                **({"tenant": tenant} if tenant is not None else {}),
                **({"trace_id": trace} if trace else {}),
            )
            self._trace_span(trace, "admission", now, reason="shed_queue_full")
            fut.set_result(
                ServeResult(ok=False, reason="shed_queue_full")
            )
            return fut
        # An explicit per-request 0 means "no deadline", same as the
        # config convention (ServeConfig.deadline_ms).
        ms = deadline_ms if deadline_ms is not None else self.default_deadline_ms
        ms = ms or None
        # Enqueue under the SAME lock drain() sets the flag under: a
        # put serialized before the flag flips is visible to the
        # worker's final sweep, and a submit serialized after it is
        # rejected here — no request can ever strand in the queue with
        # nothing left to consume it.
        raced_shutdown = False
        with self._lock:
            if self._draining.is_set():
                raced_shutdown = True
            else:
                self._admitted += 1
                req = _Request(
                    sample=sample,
                    future=fut,
                    ordinal=self._admitted,
                    submitted=now,
                    deadline=(
                        Deadline(now + ms / 1e3) if ms is not None else None
                    ),
                    trace=trace,
                    tenant=tenant,
                )
                self._inbound.put(req)
        if raced_shutdown:
            self.admission.release()
            self._release_tenant(tenant)
            self._trace_span(trace, "admission", now, reason="rejected_draining")
            return self._resolve_now(
                fut, "rejected_draining", now, tenant=tenant
            )
        # Admission closed; queue_wait opens here (recorded at dispatch,
        # when its end is known — spans cross the client/worker threads).
        self._trace_span(trace, "admission", now, reason="admitted")
        return fut

    def submit_rollout(
        self,
        sample: MeshSample | None = None,
        steps: int | None = None,
        *,
        deadline_ms: float | None = None,
        rollout_deadline_ms: float | None = None,
        on_step: Callable | None = None,
        session: RolloutSession | None = None,
        name: str | None = None,
        tenant: str | None = None,
    ) -> RolloutFuture:
        """Admit one autoregressive rollout: ``steps`` chained
        dispatches whose carry stays resident on THIS server between
        steps (serve/rollout.py). Each step re-enters the ordinary
        admission/batcher/dispatch pipeline — concurrent sessions at
        different step indices batch together, and every one-shot
        policy (deadline shed, breaker, finiteness) applies per step.
        ``deadline_ms`` is the PER-STEP budget (default: the server's
        ``default_deadline_ms``); ``rollout_deadline_ms`` bounds the
        whole trajectory. ``on_step(sid, step, output)`` streams
        committed steps (the returned ``RolloutFuture.iter_steps()`` is
        the pull-style twin). ``session`` re-places an existing session
        (router placement / migration) and ignores the other arguments.
        ``name`` gives the session a client-chosen id — the handle a
        later ``resume_rollout`` resumes it under after a restart.

        The future ALWAYS resolves with a ``RolloutResult``: completed,
        partial-with-``drained_at_step``, or shed-with-reason."""
        if session is None:
            if sample is None or steps is None:
                raise ValueError(
                    "submit_rollout needs (sample, steps) or a session"
                )
            if name is not None and self.has_session(name):
                # Two live sessions under one sid would shadow each
                # other in the residence table (and fight over the
                # same persisted snapshot).
                raise ValueError(
                    f"a session named {name!r} is already resident"
                )
            with self._lock:
                self._sessions_started += 1
                n = self._sessions_started
            self._note_session("started")
            prefix = "s" if self.replica is None else f"s{self.replica}."
            ms = (
                deadline_ms
                if deadline_ms is not None
                else self.default_deadline_ms
            )
            session = RolloutSession(
                name or f"{prefix}{n:04d}",
                sample,
                steps,
                snapshot_every=self.session_snapshot_every,
                step_deadline_ms=ms or None,
                rollout_deadline=(
                    self._clock() + rollout_deadline_ms / 1e3
                    if rollout_deadline_ms
                    else None
                ),
                on_step=on_step,
                tenant=tenant,
            )
            session.named = name is not None
        else:
            # A router placement or a migrated arrival: the session
            # carries its own budgets/cursor; it just takes residence
            # here (counted — the per-replica sessions rollup reports
            # sessions ACCEPTED, migrated arrivals included).
            with self._lock:
                self._sessions_started += 1
            self._note_session("started")
        with self._lock:
            self._sessions[session.sid] = session
        self._submit_step(session)
        return session.future

    def resume_rollout(
        self,
        name: str,
        *,
        deadline_ms: float | None = None,
        rollout_deadline_ms: float | None = None,
        on_step: Callable | None = None,
    ) -> RolloutFuture:
        """Resume a persisted session from the session store: load the
        final carry snapshot a previous server's drain wrote, rebuild
        the session at its last snapshotted step, and run the remaining
        steps here. Raises ``KeyError`` when no snapshot exists. A
        session already complete at its snapshot resolves immediately.
        The restored prefix is NOT re-streamed (the client already got
        it); only new steps deliver."""
        if self._session_store is None:
            raise RuntimeError("no session store configured")
        if self.has_session(name):
            # A retry racing a live resume would run the trajectory
            # twice under one sid (same guard as submit_rollout).
            raise ValueError(
                f"a session named {name!r} is already resident"
            )
        state = self._session_store.load(name)
        if state is None:
            raise KeyError(f"no persisted session {name!r}")
        ms = (
            deadline_ms
            if deadline_ms is not None
            else self.default_deadline_ms
        )
        session = RolloutSession.from_state(
            state,
            snapshot_every=self.session_snapshot_every,
            step_deadline_ms=ms or None,
            rollout_deadline=(
                self._clock() + rollout_deadline_ms / 1e3
                if rollout_deadline_ms
                else None
            ),
            on_step=on_step,
        )
        if session.finished:
            session.resolve(True, "ok")
            return session.future
        return self.submit_rollout(session=session)

    # -- rollout-session internals (serve/rollout.py) ----------------------

    def _submit_step(self, session: RolloutSession) -> None:
        """Enqueue the session's next step as an internal request (the
        worker batches and dispatches it like any other). Terminal
        conditions (drain, exhausted rollout budget, invalid carry,
        full queue) resolve the session NOW instead — a session never
        strands between steps."""
        now = self._clock()
        if self._draining.is_set():
            self._end_session(session, reason="drained", kind="drained")
            return
        rd = session.rollout_deadline
        if rd is not None and now >= rd:
            self._end_session(
                session,
                reason="shed_deadline",
                kind="shed",
                detail="whole-rollout deadline exhausted",
            )
            return
        try:
            self.engine.validate([session.sample])
        except ValueError as err:
            self._end_session(
                session, reason="rejected_invalid", kind="shed",
                detail=str(err),
            )
            return
        if self.tenants is not None:
            # Per-step tenant quota gate (a session's K chained steps
            # each hold one in-system slot, so a tenant's quota bounds
            # its request AND rollout pressure with one number). The
            # shed is terminal, not migratable — quota exhaustion is
            # the tenant's own doing, and bouncing the session to a
            # sibling sharing the same pool-wide policy would re-fail.
            tname = (
                session.tenant
                if session.tenant is not None
                else DEFAULT_TENANT
            )
            if not self.tenants.try_admit(tname):
                self._count_shed("shed_tenant_quota")
                self._note_tenant_shed(tname, "shed_tenant_quota")
                self._event(
                    events.TENANT_QUOTA_SHED,
                    tenant=tname,
                    quota=self.tenants.quota(tname),
                    in_system=self.tenants.in_system(tname),
                    session=session.sid,
                )
                self._end_session(
                    session,
                    reason="shed_tenant_quota",
                    kind="shed",
                    detail=f"tenant quota exhausted at step "
                    f"{session.cursor + 1}",
                )
                return
        if not self.admission.try_admit():
            self._release_tenant(session.tenant)
            self._end_session(
                session,
                reason="shed_queue_full",
                kind="shed",
                detail=f"admission full at step {session.cursor + 1}",
            )
            return
        ms = session.step_deadline_ms
        at = now + ms / 1e3 if ms is not None else None
        if rd is not None:
            at = rd if at is None else min(at, rd)
        raced_shutdown = False
        with self._lock:
            if self._draining.is_set():
                raced_shutdown = True
            else:
                self._submitted += 1
                self._admitted += 1
                self._rollout_steps += 1
                req = _Request(
                    sample=session.sample,
                    future=Future(),
                    ordinal=self._admitted,
                    submitted=now,
                    deadline=Deadline(at) if at is not None else None,
                    session=session,
                    rollout_ordinal=self._rollout_steps,
                    tenant=session.tenant,
                    # A federated session's steps all adopt the
                    # cluster's ONE sampling decision (session.trace_ctx
                    # — survives migration/resume, so resumed steps are
                    # spans of the ORIGINAL trace). Locally-placed
                    # sessions keep their historical behavior: steps
                    # run untraced.
                    trace=(
                        self._tracer.adopt(session.trace_ctx)
                        if self._tracer is not None
                        and getattr(session, "trace_ctx", None) is not None
                        else None
                    ),
                )
                self._inbound.put(req)
        if raced_shutdown:
            self.admission.release()
            self._release_tenant(session.tenant)
            self._end_session(session, reason="drained", kind="drained")
            return
        if self._c_requests is not None:
            self._c_requests.inc()
        if self._c_steps is not None:
            self._c_steps.inc()
        self._note_tenant_request(session.tenant)

    def _session_step_done(self, req: _Request, result: ServeResult) -> None:
        """One session step left the system: commit + chain the next
        step, or resolve/migrate the session per the failure reason.
        Runs on whichever thread finished the step (worker or drain)."""
        session = req.session
        if result.ok:
            step = session.record_step(result.output)
            self._step_hist.record(result.latency_ms)
            self._step_res.add(result.latency_ms)
            self._event(
                events.ROLLOUT_STEP,
                session=session.sid,
                step=step,
                steps=session.steps,
                latency_ms=result.latency_ms,
            )
            session.publish_step(step, result.output)
            if session.snapshot_due():
                self._event(
                    events.SESSION_SNAPSHOT,
                    session=session.sid,
                    step=session.take_snapshot(),
                )
                if (
                    self._persist_snapshots
                    and session.named
                    and self._session_store is not None
                ):
                    # A failed write must not fail the step — the
                    # in-memory session is still authoritative; only
                    # the crash-resume point goes stale.
                    try:
                        self._session_store.save(session)
                    except OSError:
                        pass
            if session.finished:
                if session.resolve(True, "ok"):
                    with self._lock:
                        self._sessions_completed += 1
                    self._note_session("completed")
                self._drop_session(session)
                # A completed NAMED session's persisted snapshot is
                # stale — a later resume must not replay a finished
                # trajectory. (Unnamed sessions never persist: their
                # auto ids restart per process, so touching the store
                # under one could clobber another run's snapshot.)
                if self._session_store is not None and session.named:
                    self._session_store.delete(session.sid)
            else:
                with self._lock:
                    evict = self._evict_cb
                if evict is not None:
                    # Scale-in eviction (router.remove_replica): hand
                    # the session to a sibling at THIS step boundary.
                    # Snapshot first so the handover replays nothing
                    # (cursor == snapshot cursor on arrival).
                    self._event(
                        events.SESSION_SNAPSHOT,
                        session=session.sid,
                        step=session.take_snapshot(),
                    )
                    self._drop_session(session)
                    if evict(session, self.replica):
                        return
                    # No sibling could take it: keep it resident and
                    # let the removal's drain resolve it honestly.
                    with self._lock:
                        self._sessions[session.sid] = session
                self._submit_step(session)
            return
        reason = result.reason
        if reason == "rejected_draining":
            self._end_session(session, reason="drained", kind="drained")
        elif reason in MIGRATABLE_REASONS:
            # A sick OWNER, not a sick request: hand the session (with
            # its snapshot) back to the router for re-placement; on a
            # standalone server the failure is terminal but still
            # resolves — never a hang.
            self._drop_session(session)
            if session.migrate_cb is not None:
                session.migrate_cb(session, reason, result.detail, self.replica)
            else:
                if session.resolve(False, reason, detail=result.detail):
                    with self._lock:
                        self._sessions_failed += 1
                    self._note_session("failed", lost=True)
                self._event(
                    events.SHED, reason=reason, session=session.sid,
                    step=session.cursor,
                )
        else:
            self._end_session(
                session, reason=reason, kind="shed", detail=result.detail
            )

    def _end_session(
        self, session: RolloutSession, *, reason: str, kind: str,
        detail: str = "",
    ) -> None:
        """Terminal (non-ok) session resolution on this server: persist
        a FINAL snapshot (the SIGTERM-drain contract — an open
        session's last-good state survives the exit), resolve the
        future (idempotent; ``drained`` carries the
        ``drained_at_step`` marker), emit the shed event carrying the
        session id, drop the residence entry."""
        step = session.take_snapshot()
        drained = kind == "drained"
        # Persist the final snapshot BEFORE resolving (the restart
        # contract: once the client sees `drained`, the store holds the
        # state resume_rollout continues from). A failed write must not
        # block the drain — the in-memory resolution is still honest.
        persisted = False
        if (
            drained
            and session.named
            and self._session_store is not None
        ):
            try:
                self._session_store.save(session)
                persisted = True
            except OSError:
                pass
        resolved = session.resolve(
            False,
            reason,
            drained_at_step=step if drained else None,
            detail=detail,
        )
        self._drop_session(session)
        if not resolved:
            return
        with self._lock:
            if drained:
                self._sessions_drained += 1
            else:
                self._sessions_shed += 1
        self._note_session("drained" if drained else "shed")
        self._event(
            events.SESSION_SNAPSHOT,
            session=session.sid,
            step=step,
            **({"persisted": True} if persisted else {}),
        )
        self._event(
            events.SHED, reason=reason, session=session.sid, step=step
        )

    def begin_eviction(self, evict_cb: Callable) -> None:
        """Arm scale-in eviction (router.remove_replica): from the next
        committed step on, every unfinished resident session is handed
        to ``evict_cb(session, replica_id) -> bool`` at a step boundary
        (snapshot already taken at the current cursor — zero replay).
        A False return keeps the session here (no sibling available);
        the removal's drain then resolves it."""
        with self._lock:
            self._evict_cb = evict_cb

    def _drop_session(self, session: RolloutSession) -> None:
        with self._lock:
            self._sessions.pop(session.sid, None)

    def _open_sessions(self) -> list[RolloutSession]:
        with self._lock:
            return list(self._sessions.values())

    def _die(self, pending: list[_Request]) -> None:
        """The ``replica_kill`` fault fired: this replica is gone. Every
        request still in the system resolves NOW with
        ``error_replica_dead`` (a Future must never hang on a dead
        replica — resident sessions migrate through their failure
        path), then the worker thread exits: ``worker_alive()`` flips
        False, the router's ``dead`` health signal."""
        with self._lock:
            self._dead = True
        dead = ServeResult(
            ok=False,
            reason="error_replica_dead",
            detail="replica killed (injected replica_kill)",
        )
        n = 0
        for r in pending:
            self._finish(r, dead)
            self._note_tenant_shed(r.tenant, "error_replica_dead")
            n += 1
        try:
            while True:
                item = self._inbound.get_nowait()
                if item is not None:
                    self._finish(item, dead)
                    self._note_tenant_shed(item.tenant, "error_replica_dead")
                    n += 1
        except queue.Empty:
            pass
        # pop_ready(flush_all) REMOVES the swept requests, so a later
        # drain() sweep cannot double-finish them.
        for _, rs in self.batcher.pop_ready(self._clock(), flush_all=True):
            for r in rs:
                self._finish(r, dead)
                self._note_tenant_shed(r.tenant, "error_replica_dead")
                n += 1
        if n:
            self._count_shed("error_replica_dead", n=n)

    def reload(self, *, deadline_ms: float = 0.0) -> bool:
        """Hot-swap weights from the reload source (synchronous, on the
        CALLER's thread — the worker keeps serving old weights
        meanwhile). Atomic publish via ``engine.swap_params``; a failed
        or exhausted restore leaves the old weights serving and returns
        False. Emits a ``reload`` event either way."""
        if self.reload_fn is None:
            raise RuntimeError("no reload source configured")
        with self._lock:
            self._reloads += 1
            ordinal = self._reloads
        t0 = self._clock()
        if self.faults is not None and hasattr(self.reload_fn, "directory"):
            self.faults.maybe_reload_corrupt(ordinal, self.reload_fn.directory)
        info: dict = {}
        params = None
        try:
            out = self.reload_fn(deadline_ms=deadline_ms or None)
            if out is not None:
                params, info = out
        except Exception as err:  # noqa: BLE001 — serving must outlive reloads
            info = {"error": f"{type(err).__name__}: {err}"}
        ok = params is not None
        if ok:
            self.engine.swap_params(params)
        # Reloads trace on their own "r" stream: an aux lifecycle must
        # not consume a request keep slot (obs/tracing.start_trace).
        trace = (
            self._tracer.start_trace(stream="r")
            if self._tracer is not None
            else None
        )
        self._trace_span(trace, "reload", t0, ok=ok, reload=ordinal)
        self._event(
            events.RELOAD,
            ok=ok,
            reload=ordinal,
            duration_ms=(self._clock() - t0) * 1e3,
            **info,
            **({"trace_id": trace} if trace else {}),
        )
        return ok

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Graceful shutdown: stop admitting, flush every queued
        request through dispatch (deadline shedding still applies),
        join the worker, emit ``serve_summary``. Returns the summary
        dict. Idempotent."""
        with self._lock:  # serialized against submit()'s enqueue
            self._draining.set()
        if self._worker is not None:
            self._inbound.put(None)  # wake the worker
            self._worker.join(timeout=timeout_s)
            if self._worker.is_alive():
                # A dispatch is stuck past the drain budget (wedged
                # device, runaway compile). The worker still owns the
                # batcher/queue — sweeping them from here would race it
                # (double-finish, concurrent Batcher mutation); report
                # and return what we have instead.
                self._event(events.DRAIN_TIMEOUT, timeout_s=timeout_s)
                # Open sessions must still resolve (the wedged worker
                # may never chain them): partial-with-marker, via the
                # idempotent resolve — if the worker un-wedges later
                # its own finalization is a no-op.
                for session in self._open_sessions():
                    self._end_session(
                        session, reason="drained", kind="drained",
                        detail="drain timed out behind a wedged dispatch",
                    )
                return self._summary(emit=not self._drained.is_set())
        # The worker has exited (or never ran): resolve anything still
        # queued or batched — a request must NEVER be left hanging.
        try:
            while True:
                item = self._inbound.get_nowait()
                if item is not None:
                    self._finish(
                        item, ServeResult(ok=False, reason="rejected_draining")
                    )
                    self._count_shed("rejected_draining")
                    self._note_tenant_shed(item.tenant, "rejected_draining")
                    # Terminal span so the trace chain ends at its shed
                    # point with the reason (the propagation contract,
                    # docs/observability.md). No bucket arg: the rollup
                    # doesn't note drain-swept requests either, so the
                    # trace_report/serve_summary populations agree.
                    self._trace_span(
                        item.trace, "queue_wait", item.submitted,
                        reason="rejected_draining",
                    )
        except queue.Empty:
            pass
        for r in list(self.batcher.requests()):
            self._finish(
                r, ServeResult(ok=False, reason="rejected_draining")
            )
            self._count_shed("rejected_draining")
            self._note_tenant_shed(r.tenant, "rejected_draining")
            self._trace_span(
                r.trace, "queue_wait", r.submitted,
                reason="rejected_draining",
            )
        # Sessions still resident (their in-flight step was swept above,
        # or they raced registration against the drain flag): resolve
        # partial-with-marker, final snapshot persisted. Idempotent —
        # sessions the worker already finalized are no-ops.
        for session in self._open_sessions():
            self._end_session(session, reason="drained", kind="drained")
        if not self._drained.is_set():
            self._drained.set()
            return self._summary(emit=True)
        return self._summary(emit=False)

    # -- worker side -------------------------------------------------------

    def _run(self) -> None:
        while True:
            now = self._clock()
            if self.preempt is not None and self.preempt.triggered:
                self._draining.set()
            draining = self._draining.is_set()
            if draining:
                timeout = 0.0
            else:
                # Cap the idle block at 100 ms so the preemption flag
                # (SIGTERM) is polled even when no flush is due.
                timeout = self.batcher.next_flush_in(now)
                timeout = 0.1 if timeout is None else min(timeout, 0.1)
            try:
                item = self._inbound.get(timeout=timeout)
                if item is not None:
                    self.batcher.add(item, self._clock())
            except queue.Empty:
                pass
            # Absorb the rest of the burst WITHOUT blocking, then fall
            # through to the flush check every iteration — a sustained
            # storm must not starve dispatch behind an always-non-empty
            # inbound queue.
            try:
                while True:
                    item = self._inbound.get_nowait()
                    if item is not None:
                        self.batcher.add(item, self._clock())
            except queue.Empty:
                pass
            now = self._clock()
            # Liveness stamps: once per poll (an idle worker refreshes
            # every <= 100 ms) and once per DISPATCH — a backlogged
            # worker steadily draining many ready batches is making
            # progress, not wedged; only a worker stuck INSIDE one
            # dispatch (straggler, runaway compile) stops stamping —
            # exactly the wedge shape the router's health check wants.
            with self._lock:
                self._last_progress = now
            batches = self.batcher.pop_ready(
                now, flush_all=self._draining.is_set()
            )
            for bi, (key, reqs) in enumerate(batches):
                with self._lock:
                    self._last_progress = self._clock()
                try:
                    self._dispatch(key, reqs)
                except _ReplicaKilled:
                    # The kill fires BEFORE any _finish in _dispatch,
                    # so the current batch (and every later popped one)
                    # is still wholly unresolved — sweep them all.
                    self._die([r for _, rs in batches[bi:] for r in rs])
                    return
            if (
                self._draining.is_set()
                and len(self.batcher) == 0
                and self._inbound.empty()
            ):
                return

    def _dispatch(self, key, reqs: list[_Request]) -> None:
        packed = key is PACKED_BUCKET
        if packed:
            plan = self.pack_plan
            pn = pf = None
            bucket = f"packed:{plan.n_rows}x{plan.row_len}"
        else:
            plan = None
            pn, pf = key
            bucket = f"{pn}x{pf}"
        # Rollout-session faults, keyed by the server's 1-indexed
        # rollout-step admission ordinal (docs/robustness.md).
        # replica_kill first — a dying replica fails EVERYTHING, so it
        # must fire before any per-request resolution; then per-step
        # stale-carry failures, which drop their victims from the batch
        # (the session restores from its snapshot via migration).
        if self.faults is not None:
            for r in reqs:
                if r.session is not None and self.faults.maybe_replica_kill(
                    r.rollout_ordinal
                ):
                    raise _ReplicaKilled()
            fresh = []
            for r in reqs:
                if r.session is not None and self.faults.maybe_stale_session(
                    r.rollout_ordinal
                ):
                    self._count_shed("error_stale_session")
                    self._event(
                        events.SHED,
                        reason="error_stale_session",
                        ordinal=r.ordinal,
                        session=r.session.sid,
                    )
                    self._finish(
                        r,
                        ServeResult(
                            ok=False,
                            reason="error_stale_session",
                            detail="resident carry lost (injected "
                            "stale_session)",
                        ),
                    )
                else:
                    fresh.append(r)
            reqs = fresh
            if not reqs:
                return
        # Injected straggler: stall until the victim's deadline passes
        # (deterministic head-of-line blocking — docs/serving.md).
        if self.faults is not None:
            for r in reqs:
                if self.faults.maybe_slow_request(r.ordinal):
                    stall = (
                        r.deadline.remaining_s(self._clock()) + 1e-3
                        if r.deadline is not None
                        else 0.01
                    )
                    time.sleep(stall)
        now = self._clock()
        live: list[_Request] = []
        for r in reqs:
            if r.deadline is not None and r.deadline.expired(now):
                self._finish(r, ServeResult(ok=False, reason="shed_deadline"))
                self._count_shed("shed_deadline")
                self._note_tenant_shed(r.tenant, "shed_deadline")
                if r.trace is not None:
                    self._trace_span(
                        r.trace, "queue_wait", r.submitted, now,
                        bucket=bucket, reason="shed_deadline",
                    )
                    self._note_bucket(
                        bucket, queue_ms=[(now - r.submitted) * 1e3]
                    )
                self._event(
                    events.SHED, reason="shed_deadline", ordinal=r.ordinal,
                    waited_ms=(now - r.submitted) * 1e3,
                    **({"tenant": r.tenant} if r.tenant is not None else {}),
                    **({"trace_id": r.trace} if r.trace else {}),
                )
            else:
                live.append(r)
        if not live:
            return
        if not self.breaker.allow():
            for r in live:
                self._finish(
                    r,
                    ServeResult(
                        ok=False,
                        reason="rejected_breaker_open",
                        detail="circuit breaker open (backend unhealthy)",
                    ),
                )
                if r.trace is not None:
                    self._trace_span(
                        r.trace, "queue_wait", r.submitted, now,
                        bucket=bucket, reason="rejected_breaker_open",
                    )
                    self._note_bucket(
                        bucket, queue_ms=[(now - r.submitted) * 1e3]
                    )
            self._count_shed("rejected_breaker_open", n=len(live))
            for r in live:
                self._note_tenant_shed(r.tenant, "rejected_breaker_open")
            rejected_ids = [r.trace for r in live if r.trace is not None]
            self._event(
                events.SHED, reason="rejected_breaker_open", n=len(live),
                **({"trace_ids": rejected_ids} if rejected_ids else {}),
            )
            return
        if packed:
            # First-fit prefix packing of the LIVE set (recomputed —
            # deadline sheds may have changed it since the batcher's
            # take, and first-fit is not monotone under removals, so a
            # shed can occasionally leave a live set that no longer
            # fits one dispatch). The loop cuts it into however many
            # plan-shaped dispatches it needs, in arrival order.
            rest = live
            while rest:
                placements = pack_prefix(
                    [r.sample.coords.shape[0] for r in rest], plan
                )
                n = max(1, len(placements))
                self._dispatch_one(
                    rest[:n], placements[:n], bucket, now, pn, pf
                )
                rest = rest[n:]
        else:
            self._dispatch_one(live, None, bucket, now, pn, pf)

    def _dispatch_one(
        self, live, placements, bucket, now, pn, pf
    ) -> None:
        """ONE engine dispatch (one compiled-program execution) for an
        already deadline/breaker-screened request group: lifecycle
        spans, queue_depth event, pad-waste bookkeeping, forward,
        output-finiteness scan, resolve. ``placements`` selects the
        packed path (pack_plan-shaped dispatch); None is the ordinary
        padded per-bucket dispatch."""
        plan = self.pack_plan if placements is not None else None
        with self._lock:
            self._dispatches += 1
            dispatch = self._dispatches
        if self._c_dispatches is not None:
            self._c_dispatches.inc()
        # Traced members of this batch: queue_wait closes at dispatch
        # pop; the batch-level phases below are recorded per member
        # (same trace_id) with member_trace_ids linking the riders.
        member_ids = [r.trace for r in live if r.trace is not None]
        for r in live:
            # remaining_ms: deadline budget left when dispatch finally
            # pulled the request — how close this bucket runs to
            # shedding (0 would have been a shed).
            self._trace_span(
                r.trace, "queue_wait", r.submitted, now,
                bucket=bucket, waited_ms=(now - r.submitted) * 1e3,
                **(
                    {"remaining_ms": r.deadline.remaining_ms(now)}
                    if r.deadline is not None
                    else {}
                ),
                **({"tenant": r.tenant} if r.tenant is not None else {}),
            )
        # Pad waste of this dispatch's static shape: real node tokens
        # vs the compiled program's token capacity (padded path: rows x
        # bucket length; packed path: the plan's fixed row grid).
        real_tokens = sum(r.sample.coords.shape[0] for r in live)
        capacity_tokens = (
            plan.capacity_tokens if plan is not None else self.max_batch * pn
        )
        self._event(
            events.QUEUE_DEPTH,
            depth=self.admission.depth,
            batched=len(self.batcher),
            dispatch=dispatch,
            bucket_nodes=plan.row_len if plan is not None else pn,
            bucket_funcs=plan.pad_funcs if plan is not None else pf,
            n=len(live),
            packed=plan is not None,
            real_tokens=real_tokens,
            capacity_tokens=capacity_tokens,
            **({"trace_ids": member_ids} if member_ids else {}),
        )
        # Timing stamps ride whenever ANY consumer wants them: a traced
        # member (phase spans), the program catalog (device-time
        # attribution + program key), or a live registry (jit-fallback
        # counter reads the dispatch provenance stamp).
        timings: dict | None = (
            {}
            if member_ids
            or self._catalog is not None
            or self._metrics is not None
            else None
        )
        try:
            if plan is not None:
                outs = self.engine.infer_packed(
                    [r.sample for r in live],
                    plan,
                    placements=placements,
                    timings=timings,
                    clock=self._clock if timings is not None else None,
                )
            else:
                outs = self.engine.infer(
                    [r.sample for r in live],
                    pad_nodes=pn,
                    pad_funcs=pf,
                    rows=self.max_batch,
                    timings=timings,
                    clock=self._clock if timings is not None else None,
                )
        except Exception as err:  # noqa: BLE001 — device errors feed the breaker
            for r in live:
                if r.trace is None:
                    continue
                self._trace_span(
                    r.trace, "dispatch", now, bucket=bucket,
                    dispatch=dispatch, error="error_dispatch",
                )
                # The queue_wait spans above are already in the trace;
                # mirror them into the rollup so serve_summary and a
                # trace_report over the file agree on this path too.
                self._note_bucket(
                    bucket, queue_ms=[(now - r.submitted) * 1e3]
                )
            self._fail_dispatch(
                live, "error_dispatch", f"{type(err).__name__}: {err}"
            )
            return
        # The program ran: its pad waste is real whatever the outputs
        # hold, so the packing rollup counts it here — and the catalog
        # attributes the dispatch to its compiled program (the cost x
        # traffic join; rollout steps ride the same path).
        self._note_pack(bucket, real_tokens, capacity_tokens)
        if timings is not None:
            if timings.get("path") == "jit":
                self._note_jit_fallback()
            if self._catalog is not None:
                dev = timings.get("device")
                self._catalog.note_dispatch(
                    timings.get("program") or bucket,
                    requests=len(live),
                    real_tokens=real_tokens,
                    capacity_tokens=capacity_tokens,
                    device_s=(dev[1] - dev[0]) if dev else None,
                    replica=self.replica,
                )
        if self.faults is not None and self.faults.maybe_nan_output(dispatch):
            outs = [np.full_like(o, np.nan) for o in outs]
        if self.faults is not None and [
            r
            for r in live
            if r.session is not None
            and self.faults.maybe_rollout_nan(r.rollout_ordinal)
        ]:
            # rollout_nan: the whole dispatch is poisoned (a sick chip
            # does not scope its garbage to one segment) — the victim
            # session and any riders fail and replay/resolve.
            outs = [np.full_like(o, np.nan) for o in outs]
        bad = [
            i for i, o in enumerate(outs) if not np.all(np.isfinite(o))
        ]
        if bad:
            self._trace_batch_phases(
                live, timings, now, self._clock(), dispatch, bucket,
                member_ids,
            )
            self._fail_dispatch(
                live,
                "error_nan_output",
                f"non-finite outputs for {len(bad)}/{len(live)} "
                f"requests in dispatch {dispatch}",
            )
            return
        if self.breaker.record_success():
            self._event(events.BREAKER_CLOSE, state="closed")
        # `done` is stamped AFTER the output-finiteness scan and breaker
        # bookkeeping (the pre-tracing semantics): latency_ms and the
        # resolve span must cover everything up to the result being
        # publishable, and the dispatch span ends here too so
        # queue_wait + dispatch == latency holds exactly.
        done = self._clock()
        self._trace_batch_phases(
            live, timings, now, done, dispatch, bucket, member_ids
        )
        for r, o in zip(live, outs):
            lat = (done - r.submitted) * 1e3
            with self._lock:
                self._completed += 1
            self._note_latency(lat, bucket)
            self._note_tenant_done(r.tenant, lat)
            self._finish(
                r,
                ServeResult(ok=True, reason="ok", output=o, latency_ms=lat),
            )
            self._trace_span(
                r.trace, "resolve", done, reason="ok", latency_ms=lat,
                **({"tenant": r.tenant} if r.tenant is not None else {}),
            )

    def _trace_batch_phases(
        self, live, timings, start, done, dispatch, bucket, member_ids
    ) -> None:
        """Record the batch-level phase spans (batch_assembly / device /
        unpad from the engine's phase stamps, plus the enclosing
        dispatch span) once per traced member, and feed the per-bucket
        queue/device rollup serve_summary reports — one queue and one
        device observation per TRACED member, exactly the population
        trace_report sees in the file. No-op when no batch member was
        sampled."""
        if timings is None:
            return
        link = {"dispatch": dispatch, "bucket": bucket,
                "member_trace_ids": member_ids}
        device_ms = None
        if "device" in timings:
            t0, t1 = timings["device"]
            device_ms = (t1 - t0) * 1e3
        # A fresh-signature jit dispatch paid its XLA compile inside
        # the device window: record a dedicated `compile` span over it
        # so the trace critical path attributes cold-path compiles
        # instead of lumping them into an unattributed gap. (An AOT
        # dispatch never compiles; a warm jit signature already has its
        # executable cached.)
        compile_span = (
            timings.get("path") == "jit"
            and timings.get("fresh_signature")
            and "device" in timings
        )
        for r in live:
            if r.trace is None:
                continue
            # Tenant rides every per-member phase span so a trace file
            # alone supports the per-tenant queue-vs-device breakdown
            # (tools/trace_report.py) without consulting the sink.
            ten = {"tenant": r.tenant} if r.tenant is not None else {}
            self._trace_span(r.trace, "dispatch", start, done, **link, **ten)
            for phase in ("batch_assembly", "device", "unpad"):
                if phase in timings:
                    t0, t1 = timings[phase]
                    self._trace_span(r.trace, phase, t0, t1, **link, **ten)
            if compile_span:
                t0, t1 = timings["device"]
                self._trace_span(
                    r.trace, "compile", t0, t1,
                    program=timings.get("program"), **link,
                )
            self._note_bucket(
                bucket,
                queue_ms=[(start - r.submitted) * 1e3],
                device_ms=[device_ms] if device_ms is not None else (),
            )

    def _note_pack(
        self, bucket: str, real_tokens: int, capacity_tokens: int
    ) -> None:
        """One executed dispatch's contribution to the per-bucket
        packing-efficiency rollup (serve_summary.pad_waste_by_bucket).
        With a live registry the per-bucket counters are the ONLY
        ledger (_summary reads their values back), so the summary and
        the registry series cannot diverge; without one, the plain
        dict accounting stands as before."""
        if self._metrics is not None:
            cs = self._pack_counters.get(bucket)
            if cs is None:
                lbl = {"bucket": bucket, **self._metric_labels}
                cs = {
                    "dispatches": self._metrics.counter(
                        "serve_bucket_dispatches_total", **lbl
                    ),
                    "real_tokens": self._metrics.counter(
                        "serve_bucket_real_tokens_total", **lbl
                    ),
                    "capacity_tokens": self._metrics.counter(
                        "serve_bucket_capacity_tokens_total", **lbl
                    ),
                }
                self._pack_counters[bucket] = cs
            cs["dispatches"].inc()
            cs["real_tokens"].inc(real_tokens)
            cs["capacity_tokens"].inc(capacity_tokens)
            return
        with self._lock:
            st = self._pack_stats.setdefault(
                bucket,
                {"dispatches": 0, "real_tokens": 0, "capacity_tokens": 0},
            )
            st["dispatches"] += 1
            st["real_tokens"] += real_tokens
            st["capacity_tokens"] += capacity_tokens

    def _note_jit_fallback(self) -> None:
        """One dispatch ran the JITTED forward (its signature missing
        from the AOT table) — the cold path a prewarmed tier must never
        take. Previously invisible; now a per-replica count in
        serve_summary and, with a live registry, the
        ``serve_jit_fallback_total`` series an operator can alert on."""
        with self._lock:
            self._jit_fallbacks += 1
        if self._c_jit_fallback is not None:
            self._c_jit_fallback.inc()

    def _note_bucket(self, bucket: str, queue_ms=(), device_ms=()) -> None:
        """One traced request's contribution to the per-bucket
        queue/device rollup (serve_summary.queue_device_by_bucket)."""
        with self._lock:
            st = self._bucket_stats.setdefault(
                bucket, {"queue_ms": [], "device_ms": []}
            )
            st["queue_ms"].extend(queue_ms)
            st["device_ms"].extend(device_ms)

    def _fail_dispatch(self, reqs, reason: str, detail: str) -> None:
        """A whole-dispatch failure: every rider gets a degraded
        response NOW (no hang, no retry queue growth) and the breaker
        counts one failure."""
        now = self._clock()
        for r in reqs:
            self._finish(r, ServeResult(ok=False, reason=reason, detail=detail))
            self._trace_span(r.trace, "resolve", now, reason=reason)
            self._note_tenant_shed(r.tenant, reason)
        self._count_shed(reason, n=len(reqs))
        if self.breaker.record_failure():
            first_trace = next(
                (r.trace for r in reqs if r.trace is not None), None
            )
            self._event(
                events.BREAKER_OPEN,
                state="open",
                reason=reason,
                detail=detail,
                trips=self.breaker.trips,
                **({"trace_id": first_trace} if first_trace else {}),
            )

    # -- replica health / rollup probes (serve/router.py) ------------------

    def progress_age_s(self, now: float | None = None) -> float:
        """Seconds since the worker loop last completed an iteration —
        the router's wedge signal: a large age while ``depth() > 0``
        means the worker is stuck inside a dispatch (straggler,
        runaway compile) and traffic should drain to siblings."""
        now = self._clock() if now is None else now
        with self._lock:
            return max(0.0, now - self._last_progress)

    def depth(self) -> int:
        """Requests currently in the system (queued + batched + in
        dispatch) — the router's load signal."""
        return self.admission.depth

    def latencies_ms(self) -> list[float]:
        """BOUNDED snapshot of completed-request latencies (ms): the
        raw reservoir sample (exact for populations up to its size,
        uniform beyond — obs/metrics.py). Pool percentiles no longer
        concatenate raw lists; they merge the per-replica histograms
        losslessly (`latency_histogram`)."""
        return self._lat_res.values()

    def latency_histogram(self) -> LogHistogram:
        """Point-in-time copy of the request-latency histogram — the
        router's pool merge input (lossless: per-replica bucket counts
        sum exactly to the pool histogram)."""
        return self._lat_hist.copy()

    def step_latency_histogram(self) -> LogHistogram:
        """Point-in-time copy of the rollout-step latency histogram."""
        return self._step_hist.copy()

    def tenant_rollup(self) -> dict:
        """Per-tenant counts + latency-histogram copies — the router's
        pool-merge input (histograms merge losslessly, counts sum).
        Empty dicts when no request ever carried a tenant tag."""
        with self._lock:
            counts = {
                t: {
                    "requests": v["requests"],
                    "completed": v["completed"],
                    "shed": dict(v["shed"]),
                }
                for t, v in self._tenant_stats.items()
            }
        hists = {t: h.copy() for t, h in dict(self._tenant_hists).items()}
        return {"counts": counts, "hists": hists}

    def resident_sessions(self) -> int:
        """Rollout sessions currently resident on this server — the
        router's session-aware load signal: a replica with few
        in-flight requests but many live sessions has K-step commitments
        queued behind every new placement and must not read as idle."""
        with self._lock:
            return len(self._sessions)

    def has_session(self, sid: str) -> bool:
        """Is a session with this id resident here? (The router's
        duplicate-name guard scans the pool with it.)"""
        with self._lock:
            return sid in self._sessions

    def step_latencies_ms(self) -> list[float]:
        """BOUNDED snapshot of committed rollout-step latencies (ms) —
        the raw reservoir sample (see ``latencies_ms``)."""
        return self._step_res.values()

    def worker_alive(self) -> bool:
        """False only when a started worker thread has EXITED (a crash
        — drain sets ``_draining`` first, so a drained server reads as
        draining, not dead) or is mid-death (``_die`` — migration
        callbacks run on the dying thread itself, and the router must
        already see it dead). Not-yet-started reads True: the router
        assesses replicas it is still warming."""
        with self._lock:
            if self._dead:
                return False
        w = self._worker
        return w.is_alive() if w is not None else True

    # -- bookkeeping -------------------------------------------------------

    def _trace_span(
        self, trace, name: str, start: float, end: float | None = None,
        **args,
    ):
        """One request-lifecycle span on the server's clock (end
        defaults to now). No-op (one None check) when tracing is off or
        this request's trace was sampled out. Returns the span id."""
        if self._tracer is None or trace is None:
            return None
        if self.replica is not None:
            args = {"replica": self.replica, **args}
        return self._tracer.add_span(
            name,
            start,
            end if end is not None else self._clock(),
            trace=trace,
            args=args or None,
        )

    def _finish(self, req: _Request, result: ServeResult) -> None:
        self.admission.release()
        self._release_tenant(req.tenant)
        if not req.future.done():
            req.future.set_result(result)
        # A session step's result chains the session forward (commit +
        # next step, finalize, or migrate) — AFTER the request-level
        # bookkeeping, on the finishing thread.
        if req.session is not None:
            self._session_step_done(req, result)

    def _resolve_now(
        self,
        fut: Future,
        reason: str,
        now: float,
        *,
        detail: str = "",
        tenant: str | None = None,
    ) -> Future:
        self._count_shed(reason)
        self._note_tenant_shed(tenant, reason)
        fut.set_result(ServeResult(ok=False, reason=reason, detail=detail))
        return fut

    # -- per-tenant accounting (docs/serving.md "Multi-tenant
    # isolation"): every helper is a no-op for untagged (tenant=None)
    # traffic, so the default single-tenant path records nothing new. --

    def _release_tenant(self, tenant: str | None) -> None:
        """The quota twin of ``admission.release()``: one in-system
        request of this tenant left. Mirrors every path that admitted
        through ``TenantPolicy.try_admit`` (untagged requests admitted
        under an active policy ride the default tenant)."""
        if self.tenants is not None:
            self.tenants.release(
                tenant if tenant is not None else DEFAULT_TENANT
            )

    def _tenant_stat(self, tenant: str) -> dict:
        """The tenant's summary-rollup record. Caller holds ``_lock``
        (every ``_note_tenant_*`` call site takes it; taking it here
        too would self-deadlock on the non-reentrant lock)."""
        st = self._tenant_stats.get(tenant)  # graftlint: disable=GL004 — caller holds _lock (see docstring)
        if st is None:
            st = self._tenant_stats[tenant] = {  # graftlint: disable=GL004 — caller holds _lock (see docstring)
                "requests": 0, "completed": 0, "shed": {}
            }
        return st

    def _tenant_counter(self, name: str, tenant: str, **labels):
        key = (name, tenant, tuple(sorted(labels.items())))
        c = self._tenant_counters.get(key)
        if c is None:
            c = self._metrics.counter(
                name, tenant=tenant, **labels, **self._metric_labels
            )
            self._tenant_counters[key] = c
        return c

    def _note_tenant_request(self, tenant: str | None) -> None:
        if tenant is None:
            return
        with self._lock:
            self._tenant_stat(tenant)["requests"] += 1
        if self._metrics is not None:
            self._tenant_counter("tenant_requests_total", tenant).inc()

    def _note_tenant_shed(
        self, tenant: str | None, reason: str, n: int = 1
    ) -> None:
        if tenant is None:
            return
        with self._lock:
            shed = self._tenant_stat(tenant)["shed"]
            shed[reason] = shed.get(reason, 0) + n
        if self._metrics is not None:
            self._tenant_counter(
                "tenant_shed_total", tenant, reason=reason
            ).inc(n)

    def _note_tenant_done(self, tenant: str | None, lat_ms: float) -> None:
        if tenant is None:
            return
        with self._lock:
            self._tenant_stat(tenant)["completed"] += 1
        h = self._tenant_hists.get(tenant)
        if h is None:
            h = (
                self._metrics.histogram(
                    "tenant_latency_ms", tenant=tenant,
                    **self._metric_labels,
                )
                if self._metrics is not None
                else LogHistogram()
            )
            self._tenant_hists[tenant] = h
        h.record(lat_ms)
        if self._metrics is not None:
            self._tenant_counter("tenant_completed_total", tenant).inc()

    def _count_shed(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self._shed[reason] = self._shed.get(reason, 0) + n
        if self._metrics is not None:
            c = self._shed_counters.get(reason)
            if c is None:
                c = self._metrics.counter(
                    "serve_shed_total", reason=reason, **self._metric_labels
                )
                self._shed_counters[reason] = c
            c.inc(n)

    def _note_latency(self, lat_ms: float, bucket: str) -> None:
        """One completed request's latency into the bounded retention:
        the per-server histogram (the percentile source serve_summary
        and the pool merge read), the raw reservoir, and — with a live
        registry — the per-bucket latency series and completion
        counter. All targets are internally locked; never called under
        ``_lock``."""
        self._lat_hist.record(lat_ms)
        self._lat_res.add(lat_ms)
        if self._metrics is not None:
            self._c_completed.inc()
            h = self._bucket_hists.get(bucket)
            if h is None:
                h = self._metrics.histogram(
                    "serve_bucket_latency_ms", bucket=bucket,
                    **self._metric_labels,
                )
                self._bucket_hists[bucket] = h
            h.record(lat_ms)

    def _note_session(self, outcome: str, lost: bool = False) -> None:
        """One session outcome into the live registry (`started`,
        `completed`, `drained`, `shed`, `failed`); ``lost`` additionally
        bumps the SLO evaluator's session-loss counter (a session that
        terminally failed on a backend signal with nobody to migrate
        it)."""
        if self._metrics is None:
            return
        self._metrics.counter(
            "rollout_sessions_total", outcome=outcome, **self._metric_labels
        ).inc()
        if lost:
            self._metrics.counter(
                "rollout_sessions_lost_total", **self._metric_labels
            ).inc()

    def _event(self, event: str, **fields) -> None:
        if self.sink is not None:
            if self.replica is not None:
                fields.setdefault("replica", self.replica)
            self.sink.log(event=event, **fields)

    def _summary(self, *, emit: bool) -> dict:
        # Snapshot the shared counters under the lock (drain() may be
        # summarizing while a wedged worker still mutates them — the
        # drain_timeout path); the percentile math runs on the copies.
        # Percentiles come from the bounded log-bucketed histograms
        # (obs/metrics.py): estimates within metrics.REL_ERROR of the
        # exact nearest-rank values (documented in
        # docs/observability.md "Live metrics"), and — when a live
        # registry is attached — the SAME buckets every
        # metrics_snapshot published, so the drain-time view and the
        # final snapshot agree by construction (summary_agrees).
        with self._lock:
            summary = {
                "requests": self._submitted,
                "admitted": self._admitted,
                "completed": self._completed,
                "shed": dict(self._shed),
                "dispatches": self._dispatches,
                "reloads": self._reloads,
            }
            bucket_stats = {
                k: {kk: list(vv) for kk, vv in v.items()}
                for k, v in self._bucket_stats.items()
            }
            pack_stats = {k: dict(v) for k, v in self._pack_stats.items()}
            jit_fallbacks = self._jit_fallbacks
            tenant_stats = {
                t: {
                    "requests": v["requests"],
                    "completed": v["completed"],
                    "shed": dict(v["shed"]),
                }
                for t, v in self._tenant_stats.items()
            }
            if self._sessions_started:
                # Rollout-session rollup (serve/rollout.py): sessions
                # ACCEPTED here (migrated arrivals included) and how
                # each left, plus the per-step latency percentiles.
                summary["sessions"] = {
                    "started": self._sessions_started,
                    "completed": self._sessions_completed,
                    "drained": self._sessions_drained,
                    "shed": self._sessions_shed,
                    "failed": self._sessions_failed,
                    "resident": len(self._sessions),
                    "steps": self._step_hist.count,
                    "step_latency_p50_ms": self._step_hist.percentile(0.50),
                    "step_latency_p99_ms": self._step_hist.percentile(0.99),
                }
        if self._metrics is not None:
            # With a live registry the per-bucket counters ARE the
            # ledger (_note_pack): read their values back so the
            # summary's pad_waste_by_bucket and the registry series are
            # one accounting, not two that can drift.
            pack_stats = {
                k: {kk: c.value for kk, c in cs.items()}
                for k, cs in dict(self._pack_counters).items()
            }
        summary["jit_fallbacks"] = jit_fallbacks
        if tenant_stats:
            # Per-tenant rollup (docs/serving.md "Multi-tenant
            # isolation"): how each tenant's traffic fared — the
            # noisy-neighbor A/B's per-arm evidence. Absent entirely
            # when no request ever carried a tenant tag.
            summary["tenants"] = {
                t: {
                    **st,
                    "latency_p50_ms": (
                        self._tenant_hists[t].percentile(0.50)
                        if t in self._tenant_hists
                        else None
                    ),
                    "latency_p99_ms": (
                        self._tenant_hists[t].percentile(0.99)
                        if t in self._tenant_hists
                        else None
                    ),
                }
                for t, st in sorted(tenant_stats.items())
            }
        if pack_stats:
            # Per-bucket pad-waste / packing efficiency over every
            # executed dispatch: fill = real/capacity node tokens,
            # pad_waste = 1 - fill. The packed bucket (when pack_plan
            # is set) reports alongside the padded ones, so one summary
            # shows what packing bought (tools/pack_ab.py compares
            # these across arms).
            summary["pad_waste_by_bucket"] = {
                key: {
                    **st,
                    "fill_frac": (
                        st["real_tokens"] / st["capacity_tokens"]
                        if st["capacity_tokens"]
                        else None
                    ),
                    "pad_waste_frac": (
                        1.0 - st["real_tokens"] / st["capacity_tokens"]
                        if st["capacity_tokens"]
                        else None
                    ),
                }
                for key, st in sorted(pack_stats.items())
            }
        if self._tracer is not None:
            # Span-derived queue-wait vs device-time breakdown per
            # bucket — where a request's latency went, by shape class.
            # Same population AND same nearest-rank percentiles as
            # tools/trace_report.py::bucket_breakdown, so this rollup
            # and a report over the trace file agree number-for-number.
            summary["queue_device_by_bucket"] = {
                key: {
                    "n": len(st["queue_ms"]),
                    **{
                        f"queue_{k}": v
                        for k, v in percentiles(st["queue_ms"]).items()
                    },
                    **{
                        f"device_{k}": v
                        for k, v in percentiles(st["device_ms"]).items()
                    },
                }
                for key, st in sorted(bucket_stats.items())
            }
            # Trace-coverage stats (ISSUE 20 satellite): how much of
            # the traffic the sampled trace file actually represents,
            # plus what sampling silently dropped — a trace_report
            # number without this denominator overclaims.
            summary["trace"] = self._tracer.coverage()
        summary.update(
            # Serving compute dtype (models/precision.py): every rollup
            # names the precision it measured — a bench artifact from a
            # bf16 run cannot masquerade as f32. getattr: chaos-test
            # stub engines predate the policy.
            dtype=getattr(self.engine, "dtype", "float32"),
            breaker_trips=self.breaker.trips,
            compiled_shapes=self.engine.compiled_shapes,
            latency_p50_ms=self._lat_hist.percentile(0.50),
            latency_p99_ms=self._lat_hist.percentile(0.99),
        )
        if self._catalog is not None and self.replica is None:
            # Standalone server (router-owned replicas carry integer
            # ids, 0 included, and the router's drain builds the pool
            # rollup instead): join the catalog's cost entries with the
            # traffic this server attributed to them. emit=True also
            # publishes the capacity_snapshot event exactly once.
            model = self._catalog.emit_snapshot() if emit else None
            summary["capacity_model"] = (
                model if model is not None else self._catalog.capacity_model()
            )
        if emit:
            self._event(events.SERVE_SUMMARY, **summary)
            if self.sink is not None:
                self.sink.flush()
        return summary


class CheckpointReloader:
    """The hot-reload source wrapping a ``train.checkpoint.Checkpointer``:
    restores ``latest`` (walking the full fallback chain — a corrupted
    dir degrades to an older checkpoint, loudly) into a template state
    and returns its params. The caller's ``deadline_ms`` clamps the
    restore's retry backoff (resilience.retry), so a reload against
    flaky storage never stalls serving past its budget.

    ``template`` is a TrainState (or params-bearing pytree) with the
    target structure — typically the trainer's live state.
    """

    def __init__(self, checkpointer, template):
        self.checkpointer = checkpointer
        self.template = template

    @property
    def directory(self) -> str:
        return self.checkpointer.directory

    def __call__(self, *, deadline_ms: float | None = None):
        deadline = (
            time.monotonic() + deadline_ms / 1e3
            if deadline_ms is not None
            else None
        )
        out = self.checkpointer.restore_latest(
            self.template, deadline=deadline
        )
        if out is None:
            return None
        state, epoch, best_metric = out
        info = dict(self.checkpointer.last_restore or {})
        info.update(epoch=epoch, best_metric=best_metric)
        return getattr(state, "params", state), info
