"""Serving robustness policies as first-class, individually-tested
objects: request deadlines, bounded-queue admission control, and a
circuit breaker.

Each is deterministic given an injectable ``clock`` (tests pass a fake
monotonic clock; production uses ``time.monotonic``), holds no thread
of its own, and decides ONE thing — the server composes them. The
policy semantics are documented in docs/serving.md.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute monotonic expiry. Requests carry one (or None);
    expired requests are shed BEFORE dispatch — compiled-forward time
    is never spent on an answer nobody is waiting for — and the same
    absolute time bounds downstream retries (resilience.retry)."""

    at: float  # absolute clock() time

    def expired(self, now: float) -> bool:
        return now >= self.at

    def remaining_s(self, now: float) -> float:
        return max(0.0, self.at - now)

    def remaining_ms(self, now: float) -> float:
        """Milliseconds of budget left — the unit the serve events and
        trace span args report in (a shed's ``waited_ms`` plus the
        victim's ``remaining_ms`` at dispatch reconstructs the full
        deadline arithmetic from the trace alone)."""
        return self.remaining_s(now) * 1e3


class AdmissionController:
    """Bounded-queue admission: at most ``limit`` requests in the
    system (queued + batched + in dispatch). ``try_admit`` is the fast
    path — a full queue fast-fails the caller in O(1) instead of
    letting an overload storm grow an unbounded backlog whose every
    entry then misses its deadline (shed at the door, not at the
    dispatcher)."""

    def __init__(self, limit: int):
        if limit < 1:
            raise ValueError(f"admission limit must be >= 1, got {limit}")
        self.limit = limit
        self._n = 0
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return self._n

    def try_admit(self) -> bool:
        with self._lock:
            if self._n >= self.limit:
                return False
            self._n += 1
            return True

    def release(self) -> None:
        """One admitted request left the system (completed or shed)."""
        with self._lock:
            if self._n <= 0:
                raise RuntimeError("release() without a matching admit")
            self._n -= 1


#: The implicit tenant of untagged traffic under an active
#: ``TenantPolicy``: weight 1, interactive class, no quota — i.e. the
#: historical single-tenant behavior. (With NO policy configured,
#: requests carry no tenant at all and every path stays byte-for-byte
#: pre-tenant.)
DEFAULT_TENANT = "default"

#: Priority classes, highest first: under overload, ``batch`` work is
#: deferred/shed FIRST — brownout before blackout (docs/serving.md
#: "Multi-tenant isolation").
PRIORITY_CLASSES = ("interactive", "batch")


class TenantPolicy:
    """Per-tenant isolation policy — WFQ weights, admission quotas, and
    priority classes — parsed from the config's three spec strings
    (``--tenant_weights interactive:3,batch:1``) and composed by the
    batcher/server/autoscaler (docs/serving.md "Multi-tenant
    isolation"):

    * ``weight(t)`` — the tenant's deficit-round-robin share within its
      priority tier (unlisted tenants weigh 1);
    * ``priority(t)`` — ``"interactive"`` or ``"batch"``: strict drain
      order under contention (batch defers first). Unlisted tenants are
      interactive — except one literally NAMED "batch", so the README's
      two-tenant example reads the way it behaves;
    * ``try_admit(t)`` / ``release(t)`` — per-tenant bounded in-system
      count: one ``AdmissionController`` per quota'd tenant, O(1)
      fast-fail (``shed_tenant_quota``); tenants without a quota are
      never quota-limited.

    One policy object can be SHARED across a replica pool (the router
    passes it to every replica): the admission controllers are
    internally locked, so a tenant's quota bounds its pool-wide
    in-system count. Weights/priorities are frozen at construction.
    """

    def __init__(self, *, weights=None, quotas=None, priorities=None):
        self.weights = {t: int(w) for t, w in dict(weights or {}).items()}
        self.quotas = {t: int(q) for t, q in dict(quotas or {}).items()}
        self.priorities = dict(priorities or {})
        for t, w in self.weights.items():
            if w < 1:
                raise ValueError(
                    f"tenant weight for {t!r} must be >= 1, got {w}"
                )
        for t, p in self.priorities.items():
            if p not in PRIORITY_CLASSES:
                raise ValueError(
                    f"tenant priority for {t!r} must be one of "
                    f"{PRIORITY_CLASSES}, got {p!r}"
                )
        # AdmissionController validates quota >= 1.
        self._admission = {
            t: AdmissionController(q) for t, q in self.quotas.items()
        }

    @classmethod
    def from_specs(
        cls, weights: str = "", quotas: str = "", priorities: str = ""
    ) -> "TenantPolicy | None":
        """Build from the raw ``ServeConfig`` spec strings; all three
        empty returns None (tenant mode off — the byte-for-byte
        single-tenant path)."""
        if not (weights or quotas or priorities):
            return None
        from gnot_tpu.config import parse_tenant_spec

        return cls(
            weights=parse_tenant_spec(weights, what="weight"),
            quotas=parse_tenant_spec(quotas, what="quota"),
            priorities=parse_tenant_spec(priorities, what="priority"),
        )

    @property
    def tenants(self) -> list[str]:
        """Every tenant any spec names (sorted; the metrics/SLO plane
        pre-registers series for these)."""
        return sorted(
            set(self.weights) | set(self.quotas) | set(self.priorities)
        )

    def weight(self, tenant: str) -> int:
        return self.weights.get(tenant, 1)

    def priority(self, tenant: str) -> str:
        p = self.priorities.get(tenant)
        if p is None:
            p = "batch" if tenant == "batch" else "interactive"
        return p

    def quota(self, tenant: str) -> int | None:
        a = self._admission.get(tenant)
        return a.limit if a is not None else None

    def in_system(self, tenant: str) -> int:
        a = self._admission.get(tenant)
        return a.depth if a is not None else 0

    def try_admit(self, tenant: str) -> bool:
        """O(1) per-tenant quota gate; True for un-quota'd tenants."""
        a = self._admission.get(tenant)
        return True if a is None else a.try_admit()

    def release(self, tenant: str) -> None:
        """One of this tenant's admitted requests left the system."""
        a = self._admission.get(tenant)
        if a is not None:
            a.release()


#: Router request-placement policies (serve/router.py, docs/serving.md
#: "Replicated serving"). ``affinity`` is the default: prefer a replica
#: that has already compiled the request's bucket, so steady-state
#: recompiles per replica stay O(log L_max) and a cold compile stalls
#: one replica, never the pool.
ROUTE_POLICIES = ("affinity", "least_loaded", "round_robin")


@dataclasses.dataclass(frozen=True)
class HealthVerdict:
    """One replica's routability verdict: ``healthy`` replicas take new
    traffic; unhealthy ones are DRAINED (siblings absorb their share)
    rather than shed — the reason names the signal that drained it."""

    healthy: bool
    # "ok" | "warming" | "breaker_open" | "wedged" | "dead" | "retiring"
    reason: str


class ReplicaHealthPolicy:
    """Routability decision for one replica from the signals the serve
    stack already produces — no new probes, no health-check RPCs:

    * ``breaker_open`` — the replica's own ``CircuitBreaker`` is open
      (repeated NaN outputs / device errors): it is rejecting anyway,
      so route around it. Once the cooldown elapses
      (``breaker_trial_due``) the replica reads healthy again so a
      half-open trial dispatch can reach it — a drained replica
      otherwise never dispatches and the breaker could never recover.
    * ``wedged`` — requests are in the replica's system but its worker
      loop has not completed an iteration for ``wedge_after_s``
      (straggling device, runaway compile): drain to siblings instead
      of queueing behind the stall.
    * ``warming`` — the rolling hot-reload marks the replica warming;
      old weights keep serving what it already holds, but new traffic
      goes to siblings until the swap publishes.
    * ``dead`` — the worker thread exited (crash): never route to it.
    * ``retiring`` — a scale-in (``ReplicaRouter.remove_replica``) is
      draining this replica out of the pool: it keeps serving what it
      already holds (and its resident sessions until they hand over),
      but new placement goes to siblings — drain-then-remove, never
      remove-then-shed.

    Stateless and deterministic given the inputs — the router samples
    the signals and emits ``replica_health`` events on transitions.
    """

    def __init__(self, *, wedge_after_s: float = 2.0):
        if wedge_after_s <= 0:
            raise ValueError(
                f"wedge_after_s must be > 0, got {wedge_after_s}"
            )
        self.wedge_after_s = wedge_after_s

    def assess(
        self,
        *,
        breaker_state: str,
        warming: bool,
        progress_age_s: float,
        depth: int,
        worker_alive: bool = True,
        breaker_trial_due: bool = False,
        retiring: bool = False,
    ) -> HealthVerdict:
        if not worker_alive:
            return HealthVerdict(False, "dead")
        if retiring:
            return HealthVerdict(False, "retiring")
        if warming:
            return HealthVerdict(False, "warming")
        if breaker_state == "open" and not breaker_trial_due:
            return HealthVerdict(False, "breaker_open")
        if depth > 0 and progress_age_s >= self.wedge_after_s:
            return HealthVerdict(False, "wedged")
        if breaker_state == "open":
            # Cooldown elapsed: routable so the half-open trial can
            # happen (the reason names why it is being offered traffic).
            return HealthVerdict(True, "trial")
        return HealthVerdict(True, "ok")


class CircuitBreaker:
    """Trips open after ``threshold`` consecutive dispatch failures
    (non-finite outputs, device errors); while open, requests are
    rejected instantly with a reason instead of queueing behind a sick
    backend until they time out. After ``cooldown_s`` one trial
    dispatch is allowed (half-open): success closes the breaker,
    failure re-opens it for another cooldown.

    States: ``closed`` (serving), ``open`` (rejecting),
    ``half_open`` (one trial in flight). Thread-safe; the server emits
    ``breaker_open`` / ``breaker_close`` events on transitions.
    """

    def __init__(
        self,
        *,
        threshold: int = 3,
        cooldown_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self.trips = 0  # lifetime open transitions (serve_summary)

    @property
    def state(self) -> str:
        return self._state

    def trial_due(self) -> bool:
        """Read-only peek: would ``allow()`` admit a half-open trial
        right now? The replica router's health check uses this to route
        ONE trial's worth of traffic back to an open-breaker replica —
        without it a drained replica never dispatches, ``allow()``
        never runs, and the breaker (whose only open->half_open
        transition lives there) could never recover."""
        with self._lock:
            return (
                self._state == "open"
                and self._clock() - self._opened_at >= self.cooldown_s
            )

    def allow(self) -> bool:
        """May a dispatch proceed right now? Open -> False until the
        cooldown elapses, then one half-open trial is admitted."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = "half_open"
                    return True
                return False
            # half_open: one trial at a time; further dispatches wait.
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a previously-open
        breaker (the recovery transition, worth an event)."""
        with self._lock:
            recovered = self._state == "half_open"
            self._state = "closed"
            self._failures = 0
            return recovered

    def record_failure(self) -> bool:
        """Returns True when this failure TRIPPED the breaker open
        (threshold reached, or a half-open trial failed)."""
        with self._lock:
            self._failures += 1
            should_open = (
                self._state == "half_open"
                or self._failures >= self.threshold
            )
            if should_open and self._state != "open":
                self._state = "open"
                self._opened_at = self._clock()
                self.trips += 1
                return True
            if should_open:  # already open (counting extra failures)
                self._opened_at = self._clock()
            return False
