"""Serving robustness policies as first-class, individually-tested
objects: request deadlines, bounded-queue admission control, and a
circuit breaker.

Each is deterministic given an injectable ``clock`` (tests pass a fake
monotonic clock; production uses ``time.monotonic``), holds no thread
of its own, and decides ONE thing — the server composes them. The
policy semantics are documented in docs/serving.md.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute monotonic expiry. Requests carry one (or None);
    expired requests are shed BEFORE dispatch — compiled-forward time
    is never spent on an answer nobody is waiting for — and the same
    absolute time bounds downstream retries (resilience.retry)."""

    at: float  # absolute clock() time

    def expired(self, now: float) -> bool:
        return now >= self.at

    def remaining_s(self, now: float) -> float:
        return max(0.0, self.at - now)

    def remaining_ms(self, now: float) -> float:
        """Milliseconds of budget left — the unit the serve events and
        trace span args report in (a shed's ``waited_ms`` plus the
        victim's ``remaining_ms`` at dispatch reconstructs the full
        deadline arithmetic from the trace alone)."""
        return self.remaining_s(now) * 1e3


class AdmissionController:
    """Bounded-queue admission: at most ``limit`` requests in the
    system (queued + batched + in dispatch). ``try_admit`` is the fast
    path — a full queue fast-fails the caller in O(1) instead of
    letting an overload storm grow an unbounded backlog whose every
    entry then misses its deadline (shed at the door, not at the
    dispatcher)."""

    def __init__(self, limit: int):
        if limit < 1:
            raise ValueError(f"admission limit must be >= 1, got {limit}")
        self.limit = limit
        self._n = 0
        self._lock = threading.Lock()

    @property
    def depth(self) -> int:
        return self._n

    def try_admit(self) -> bool:
        with self._lock:
            if self._n >= self.limit:
                return False
            self._n += 1
            return True

    def release(self) -> None:
        """One admitted request left the system (completed or shed)."""
        with self._lock:
            if self._n <= 0:
                raise RuntimeError("release() without a matching admit")
            self._n -= 1


#: Router request-placement policies (serve/router.py, docs/serving.md
#: "Replicated serving"). ``affinity`` is the default: prefer a replica
#: that has already compiled the request's bucket, so steady-state
#: recompiles per replica stay O(log L_max) and a cold compile stalls
#: one replica, never the pool.
ROUTE_POLICIES = ("affinity", "least_loaded", "round_robin")


@dataclasses.dataclass(frozen=True)
class HealthVerdict:
    """One replica's routability verdict: ``healthy`` replicas take new
    traffic; unhealthy ones are DRAINED (siblings absorb their share)
    rather than shed — the reason names the signal that drained it."""

    healthy: bool
    # "ok" | "warming" | "breaker_open" | "wedged" | "dead" | "retiring"
    reason: str


class ReplicaHealthPolicy:
    """Routability decision for one replica from the signals the serve
    stack already produces — no new probes, no health-check RPCs:

    * ``breaker_open`` — the replica's own ``CircuitBreaker`` is open
      (repeated NaN outputs / device errors): it is rejecting anyway,
      so route around it. Once the cooldown elapses
      (``breaker_trial_due``) the replica reads healthy again so a
      half-open trial dispatch can reach it — a drained replica
      otherwise never dispatches and the breaker could never recover.
    * ``wedged`` — requests are in the replica's system but its worker
      loop has not completed an iteration for ``wedge_after_s``
      (straggling device, runaway compile): drain to siblings instead
      of queueing behind the stall.
    * ``warming`` — the rolling hot-reload marks the replica warming;
      old weights keep serving what it already holds, but new traffic
      goes to siblings until the swap publishes.
    * ``dead`` — the worker thread exited (crash): never route to it.
    * ``retiring`` — a scale-in (``ReplicaRouter.remove_replica``) is
      draining this replica out of the pool: it keeps serving what it
      already holds (and its resident sessions until they hand over),
      but new placement goes to siblings — drain-then-remove, never
      remove-then-shed.

    Stateless and deterministic given the inputs — the router samples
    the signals and emits ``replica_health`` events on transitions.
    """

    def __init__(self, *, wedge_after_s: float = 2.0):
        if wedge_after_s <= 0:
            raise ValueError(
                f"wedge_after_s must be > 0, got {wedge_after_s}"
            )
        self.wedge_after_s = wedge_after_s

    def assess(
        self,
        *,
        breaker_state: str,
        warming: bool,
        progress_age_s: float,
        depth: int,
        worker_alive: bool = True,
        breaker_trial_due: bool = False,
        retiring: bool = False,
    ) -> HealthVerdict:
        if not worker_alive:
            return HealthVerdict(False, "dead")
        if retiring:
            return HealthVerdict(False, "retiring")
        if warming:
            return HealthVerdict(False, "warming")
        if breaker_state == "open" and not breaker_trial_due:
            return HealthVerdict(False, "breaker_open")
        if depth > 0 and progress_age_s >= self.wedge_after_s:
            return HealthVerdict(False, "wedged")
        if breaker_state == "open":
            # Cooldown elapsed: routable so the half-open trial can
            # happen (the reason names why it is being offered traffic).
            return HealthVerdict(True, "trial")
        return HealthVerdict(True, "ok")


class CircuitBreaker:
    """Trips open after ``threshold`` consecutive dispatch failures
    (non-finite outputs, device errors); while open, requests are
    rejected instantly with a reason instead of queueing behind a sick
    backend until they time out. After ``cooldown_s`` one trial
    dispatch is allowed (half-open): success closes the breaker,
    failure re-opens it for another cooldown.

    States: ``closed`` (serving), ``open`` (rejecting),
    ``half_open`` (one trial in flight). Thread-safe; the server emits
    ``breaker_open`` / ``breaker_close`` events on transitions.
    """

    def __init__(
        self,
        *,
        threshold: int = 3,
        cooldown_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self.trips = 0  # lifetime open transitions (serve_summary)

    @property
    def state(self) -> str:
        return self._state

    def trial_due(self) -> bool:
        """Read-only peek: would ``allow()`` admit a half-open trial
        right now? The replica router's health check uses this to route
        ONE trial's worth of traffic back to an open-breaker replica —
        without it a drained replica never dispatches, ``allow()``
        never runs, and the breaker (whose only open->half_open
        transition lives there) could never recover."""
        with self._lock:
            return (
                self._state == "open"
                and self._clock() - self._opened_at >= self.cooldown_s
            )

    def allow(self) -> bool:
        """May a dispatch proceed right now? Open -> False until the
        cooldown elapses, then one half-open trial is admitted."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = "half_open"
                    return True
                return False
            # half_open: one trial at a time; further dispatches wait.
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a previously-open
        breaker (the recovery transition, worth an event)."""
        with self._lock:
            recovered = self._state == "half_open"
            self._state = "closed"
            self._failures = 0
            return recovered

    def record_failure(self) -> bool:
        """Returns True when this failure TRIPPED the breaker open
        (threshold reached, or a half-open trial failed)."""
        with self._lock:
            self._failures += 1
            should_open = (
                self._state == "half_open"
                or self._failures >= self.threshold
            )
            if should_open and self._state != "open":
                self._state = "open"
                self._opened_at = self._clock()
                self.trips += 1
                return True
            if should_open:  # already open (counting extra failures)
                self._opened_at = self._clock()
            return False
