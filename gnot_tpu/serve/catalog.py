"""Program catalog: XLA cost analysis joined with live traffic.

The serving tier dispatches a CLOSED set of programs — one compiled
executable per dtype-keyed bucket shape plus one per ``PackPlan``
(docs/serving.md) — and GNOT's linear attention makes each program's
cost a closed-form function of its shape (tokens x width, never
tokens^2; arXiv 2302.14376). So the capacity question "what can a
replica sustain?" decomposes exactly: per-program device cost (known
at compile time, from XLA's own ``cost_analysis``/``memory_analysis``
via obs/costs.py) times per-program traffic (known at dispatch time).
This module is the join.

Two ledgers, one key namespace:

* **entries** — one per program signature, recorded when the program
  is compiled (engine capture), AOT-compiled (serve/aot.py manifest)
  or hydrated from a snapshot: the cost dict, its provenance
  (``source``: compile / hydrate / manifest) and a ``program_catalog``
  event on first sight. Keys are the AOT table's own program keys
  (``bucket:{nodes}x{funcs}@{rows}@{dtype}`` /
  ``packed:{rows}x{len}@{dtype}``) so the catalog, the prewarm
  manifest and the dispatch provenance counters all speak one name.
* **traffic** — per (program, replica): dispatches, dispatched
  requests, real vs capacity tokens, device seconds. Fed by every
  server dispatch (padded, packed, rollout step); rows are never
  deleted, so a replica retired by scale-in keeps its served history
  in the pool capacity model exactly like the drain-time summary
  rollup does.

When a ``MetricsRegistry`` is attached the join is live, not just
drain-time: per-program counters (dispatches/requests/tokens),
device-time histograms (per dispatch and per token), and gauges for
achieved FLOPs/s and useful-token fraction — the series the ROADMAP's
adaptive-PackPlan controller will read.

:meth:`capacity_model` folds both ledgers into the serve_summary /
capacity_snapshot export: per-program throughput rates and pool-level
sustainable tokens/s and requests/s per replica (device-seconds are
the denominator — what the replica could sustain at 100% device duty,
the headroom baseline tools/capacity_report.py compares offered load
against).

Thread-safety: one lock guards both ledgers; servers on worker
threads feed ``note_dispatch`` while engines record entries and the
publisher's gauge closures read — all under ``_lock`` (GL004).
"""

from __future__ import annotations

import threading

from gnot_tpu.models.precision import DTYPE_TAGS
from gnot_tpu.obs import events
from gnot_tpu.obs.costs import unavailable_costs


def bucket_program_key(
    pad_nodes: int, pad_funcs: int, rows: int, dtype: str
) -> str:
    """The padded-bucket program key — the SAME string serve/aot.py
    names this program in the prewarm manifest, so catalog entries
    recorded at compile time and at hydrate time collide correctly."""
    return f"bucket:{pad_nodes}x{pad_funcs}@{rows}@{DTYPE_TAGS[dtype]}"


def packed_program_key(plan, dtype: str) -> str:
    """The pack-plan program key (one fixed shape per plan)."""
    return f"packed:{plan.n_rows}x{plan.row_len}@{DTYPE_TAGS[dtype]}"


class ProgramCatalog:
    """Cost entries + live traffic attribution for every program the
    tier dispatches. Share ONE catalog across a deployment (engine(s),
    server(s) or router): program identity is pool-wide by
    construction — replicas compile the same programs."""

    def __init__(self, metrics=None, sink=None):
        self._metrics = metrics
        self._sink = sink
        self._lock = threading.Lock()
        # Program key -> {"costs": dict, "source": str}.
        self._entries: dict[str, dict] = {}  #: guarded_by _lock
        # (program key, replica) -> accumulated dispatch traffic.
        self._traffic: dict[tuple, dict] = {}  #: guarded_by _lock
        self._snapshot_emitted = False  #: guarded_by _lock
        # Registry series cache, off the note_dispatch hot path
        # (get-or-create only on first sight of a (program, replica);
        # benign races resolve to the same registry objects).
        self._series: dict[tuple, dict] = {}

    def attach_outputs(self, *, metrics=None, sink=None) -> None:
        """Late-bind the registry and/or event sink: a deployment
        harness builds engines (and hydrates snapshots — which records
        entries) before its sink or registry exists. Entries recorded
        before a sink attached are REPLAYED into it, so the event
        stream still carries one ``program_catalog`` record per
        program regardless of wiring order."""
        backlog: list = []
        with self._lock:
            if metrics is not None:
                self._metrics = metrics
            if sink is not None and self._sink is None:
                self._sink = sink
                backlog = [
                    (k, e["source"], dict(e["costs"]))
                    for k, e in self._entries.items()
                ]
        for key, source, costs in backlog:
            sink.log(
                event=events.PROGRAM_CATALOG,
                key=key,
                source=source,
                costs=costs,
            )

    # -- entries (compile / hydrate time) ----------------------------------

    def record(self, key: str, costs: dict | None, *, source: str) -> bool:
        """Record one program's cost entry. First sight wins and emits
        a ``program_catalog`` event; a later recording replaces the
        entry only when it knows strictly MORE (fewer ``unavailable``
        fields) — e.g. a live ``cost_analysis`` upgrading a thin
        manifest-carried entry. Returns True iff the entry changed."""
        if costs is None:
            costs = unavailable_costs(f"no costs from {source}")
        with self._lock:
            prev = self._entries.get(key)
            if prev is not None:
                if len(costs.get("unavailable", ())) >= len(
                    prev["costs"].get("unavailable", ())
                ):
                    return False
            self._entries[key] = {"costs": dict(costs), "source": source}
            fresh = prev is None
        if fresh and self._sink is not None:
            self._sink.log(
                event=events.PROGRAM_CATALOG,
                key=key,
                source=source,
                costs=dict(costs),
            )
        return True

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key: str) -> dict | None:
        with self._lock:
            e = self._entries.get(key)
            return None if e is None else {**e, "costs": dict(e["costs"])}

    def entries(self) -> dict:
        """Snapshot of every recorded entry (key -> {costs, source})."""
        with self._lock:
            return {
                k: {**e, "costs": dict(e["costs"])}
                for k, e in self._entries.items()
            }

    # -- traffic (dispatch time) -------------------------------------------

    def note_dispatch(
        self,
        key: str,
        *,
        requests: int,
        real_tokens: int,
        capacity_tokens: int,
        device_s: float | None,
        replica=None,
    ) -> None:
        """Attribute one executed dispatch to its program: the join's
        write side, called by the server right where the pad-waste
        rollup is fed (the program RAN; its tokens and device time are
        real). ``device_s`` may be None when the dispatch carried no
        timing probe — the dispatch still counts, its device time is
        simply unknown (never invented)."""
        dev = float(device_s) if device_s else 0.0
        tkey = (key, replica)
        with self._lock:
            t = self._traffic.get(tkey)
            if t is None:
                t = self._traffic[tkey] = {
                    "dispatches": 0,
                    "requests": 0,
                    "real_tokens": 0,
                    "capacity_tokens": 0,
                    "device_s": 0.0,
                }
            t["dispatches"] += 1
            t["requests"] += int(requests)
            t["real_tokens"] += int(real_tokens)
            t["capacity_tokens"] += int(capacity_tokens)
            t["device_s"] += dev
        if self._metrics is not None:
            s = self._series.get(tkey)
            if s is None:
                s = self._make_series(key, replica)
            s["dispatches"].inc()
            s["requests"].inc(requests)
            s["real_tokens"].inc(real_tokens)
            s["capacity_tokens"].inc(capacity_tokens)
            if device_s:
                s["device_ms"].record(dev * 1e3)
                if real_tokens:
                    s["device_us_per_token"].record(
                        dev * 1e6 / real_tokens
                    )

    def _make_series(self, key: str, replica) -> dict:
        """Get-or-create the per-(program, replica) registry series.
        Gauges are CALLBACK gauges over the catalog's own ledgers, so
        achieved FLOPs/s and useful-token fraction are whatever is
        true at snapshot time — no second accounting to drift."""
        lbl = {"program": key}
        if replica is not None:
            lbl["replica"] = replica
        m = self._metrics
        s = {
            "dispatches": m.counter("program_dispatches_total", **lbl),
            "requests": m.counter("program_requests_total", **lbl),
            "real_tokens": m.counter("program_real_tokens_total", **lbl),
            "capacity_tokens": m.counter(
                "program_capacity_tokens_total", **lbl
            ),
            "device_ms": m.histogram("program_device_ms", **lbl),
            "device_us_per_token": m.histogram(
                "program_device_us_per_token", **lbl
            ),
        }
        m.gauge(
            "program_flops_per_s",
            fn=lambda k=key, r=replica: self._flops_per_s(k, r),
            **lbl,
        )
        m.gauge(
            "program_useful_token_frac",
            fn=lambda k=key, r=replica: self._useful_frac(k, r),
            **lbl,
        )
        self._series[(key, replica)] = s
        return s

    def _flops_per_s(self, key: str, replica) -> float:
        with self._lock:
            e = self._entries.get(key)
            t = self._traffic.get((key, replica))
        flops = (e or {}).get("costs", {}).get("flops")
        if not flops or t is None or not t["device_s"]:
            return 0.0
        return flops * t["dispatches"] / t["device_s"]

    def _useful_frac(self, key: str, replica) -> float:
        with self._lock:
            t = self._traffic.get((key, replica))
        if t is None or not t["capacity_tokens"]:
            return 0.0
        return t["real_tokens"] / t["capacity_tokens"]

    # -- the capacity model ------------------------------------------------

    def capacity_model(self) -> dict:
        """Costs x traffic, folded into the serve_summary export:
        per-program rates (device-time per token, achieved FLOPs/s,
        useful-token fraction) and the pool rollup of sustainable
        tokens/s and requests/s per replica — ``x / device_s``, i.e.
        what the replica would sustain at 100% device duty, the
        headroom baseline. Retired replicas merge in automatically
        (traffic rows are never deleted)."""
        with self._lock:
            entries = {
                k: {**e, "costs": dict(e["costs"])}
                for k, e in self._entries.items()
            }
            traffic = {k: dict(t) for k, t in self._traffic.items()}
        programs: dict[str, dict] = {}
        for key, entry in entries.items():
            programs[key] = {
                "source": entry["source"],
                "costs": entry["costs"],
                "dispatches": 0,
                "requests": 0,
                "real_tokens": 0,
                "capacity_tokens": 0,
                "device_s": 0.0,
                "per_replica": {},
            }
        replicas: dict[str, dict] = {}
        for (key, replica), t in sorted(
            traffic.items(), key=lambda kv: str(kv[0])
        ):
            prog = programs.get(key)
            if prog is None:
                # Dispatched but never recorded (a capture failed
                # loudly elsewhere): surface it with the explicit
                # marker rather than dropping its traffic.
                prog = programs[key] = {
                    "source": None,
                    "costs": unavailable_costs("never recorded"),
                    "dispatches": 0,
                    "requests": 0,
                    "real_tokens": 0,
                    "capacity_tokens": 0,
                    "device_s": 0.0,
                    "per_replica": {},
                }
            rid = str(replica if replica is not None else 0)
            for k in (
                "dispatches", "requests", "real_tokens",
                "capacity_tokens", "device_s",
            ):
                prog[k] += t[k]
            prog["per_replica"][rid] = dict(t)
            agg = replicas.setdefault(
                rid,
                {
                    "dispatches": 0,
                    "requests": 0,
                    "real_tokens": 0,
                    "capacity_tokens": 0,
                    "device_s": 0.0,
                },
            )
            for k in agg:
                agg[k] += t[k]
        for prog in programs.values():
            prog.update(_rates(prog, prog["costs"]))
            for t in prog["per_replica"].values():
                t.update(_rates(t, prog["costs"]))
        for agg in replicas.values():
            agg.update(_rates(agg, None))
        pool = {
            "replicas": len(replicas),
            "programs": len(programs),
            "dispatches": sum(a["dispatches"] for a in replicas.values()),
            "requests": sum(a["requests"] for a in replicas.values()),
            "real_tokens": sum(
                a["real_tokens"] for a in replicas.values()
            ),
            "capacity_tokens": sum(
                a["capacity_tokens"] for a in replicas.values()
            ),
            "device_s": sum(a["device_s"] for a in replicas.values()),
            # Pool capacity is ADDITIVE over replicas: each replica's
            # sustainable rate is its own device-duty bound.
            "sustainable_requests_per_s": sum(
                a["requests_per_device_s"] or 0.0
                for a in replicas.values()
            ),
            "sustainable_tokens_per_s": sum(
                a["tokens_per_device_s"] or 0.0
                for a in replicas.values()
            ),
            "per_replica": {
                rid: replicas[rid] for rid in sorted(replicas)
            },
        }
        cap = pool["capacity_tokens"]
        pool["useful_token_frac"] = (
            pool["real_tokens"] / cap if cap else None
        )
        return {
            "programs": {k: programs[k] for k in sorted(programs)},
            "pool": pool,
        }

    def emit_snapshot(self, summary: dict | None = None) -> dict | None:
        """One ``capacity_snapshot`` event with the current capacity
        model (idempotent — the drain that gets there first wins, like
        the serve_summary event). Returns the model, or None when the
        event already fired."""
        with self._lock:
            if self._snapshot_emitted:
                return None
            self._snapshot_emitted = True
        model = self.capacity_model()
        if self._sink is not None:
            self._sink.log(
                event=events.CAPACITY_SNAPSHOT,
                programs=model["programs"],
                pool=model["pool"],
            )
        if summary is not None:
            summary["capacity_model"] = model
        return model


def _rates(t: dict, costs: dict | None) -> dict:
    """Derived throughput rates for one traffic aggregate. None (never
    zero) when the denominator is unknown — a program with no device
    timing has an unknown rate, not an infinite one."""
    dev = t.get("device_s") or 0.0
    real = t.get("real_tokens") or 0
    cap = t.get("capacity_tokens") or 0
    out = {
        "useful_token_frac": (real / cap) if cap else None,
        "tokens_per_device_s": (real / dev) if dev else None,
        "requests_per_device_s": (
            (t.get("requests", 0) / dev) if dev else None
        ),
        "device_us_per_token": (dev * 1e6 / real) if dev and real else None,
    }
    flops = (costs or {}).get("flops")
    out["flops_per_s"] = (
        flops * t.get("dispatches", 0) / dev if flops and dev else None
    )
    return out
