"""Self-healing elastic serving: the autoscaling controller that
closes the loop from SLO burn to pool capacity.

Everything this module needs already existed as manual verbs: the live
metrics plane (obs/metrics.py — per-replica/pool gauges and the
``SLOEvaluator``'s burn-rate fire/clear state), scale-OUT
(``ReplicaRouter.add_replica`` + AOT prewarm-before-join), and — new
with this controller — scale-IN (``ReplicaRouter.remove_replica``,
drain-then-remove). ``AutoscaleController`` is the loop that connects
them: it subscribes to the metrics registry and the SLO evaluator (NOT
raw events), and on each tick takes at most ONE action:

1. **Self-heal** (highest priority): a replica whose worker died is
   replaced immediately; one wedged or breaker-stuck past
   ``heal_after_s`` is replaced after the dwell. Replacement is
   remove-then-rebuild onto the freed device slot under a fresh
   replica id (``replica_replace`` event) — pool size is preserved,
   so healing is exempt from the min/max bounds.
2. **Scale out**: per-replica in-system load (queue depth + resident
   rollout sessions, read from the registry's ``serve_queue_depth`` /
   ``serve_resident_sessions`` gauges) at or above ``up_load``, or any
   active *pressure* SLO alert (latency/shed/queue-saturation burn).
   The new replica is built on a free device slot, warmed BEFORE it
   joins — hydrated from the AOT manifest when one covers its slot
   (``prewarm_before_join``), cold warmup otherwise — and only then
   admitted to routing (``scale_up`` event).
3. **Scale in**: load at or below ``down_load`` (hysteresis:
   ``down_load < up_load``) with NO active alert, sustained for
   ``down_ticks`` consecutive ticks. The least-loaded replica is
   retired via drain-then-remove (``scale_down`` event); its resident
   sessions migrate to siblings and its latency history stays in the
   pool rollup.

Stability guards are first-class, all config-declared
(``--autoscale*``): min/max pool bounds, PER-DIRECTION cooldowns,
up/down threshold hysteresis, the consecutive-calm-ticks requirement,
and a flap suppressor (scale-in is vetoed within ``flap_suppress_s``
of the last scale-out — the pool grows before it sheds, and never
oscillates on the tail of a burst). Vetoed moves emit
``autoscale_decision`` events with ``action="hold"`` on EDGES only.

``tick()`` is the synchronous core (tests drive it on a fake clock);
``start()``/``close()`` run it on a daemon thread every ``interval_s``
— the same lifecycle shape as ``MetricsPublisher``. The controller
also keeps the replica-seconds ledger (the integral of pool size over
time) that the A/B (``tools/autoscale_ab.py``) compares against a
static pool: equal p99, strictly fewer replica-seconds, zero shed on
the up-ramp.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable

import threading

from gnot_tpu.obs import events

#: SLO objectives that read as CAPACITY pressure (scale out): burn on
#: these means the pool is too small. Health objectives (breaker,
#: wedge, session loss) are healing signals, not sizing ones.
PRESSURE_OBJECTIVES = ("latency_p99", "shed_fraction", "queue_saturation")

#: Health-verdict reasons that condemn a replica to replacement once
#: they persist past ``heal_after_s`` ("dead" skips the dwell).
HEAL_REASONS = ("dead", "wedged", "breaker_open")


class AutoscaleController:
    """The control loop. One action per tick, stability guards first.

    ``replica_factory(replica_id, slot) -> EngineReplica`` builds a new
    (unwarmed) replica on device slot ``slot`` — slots ``0..max-1``
    partition the device set exactly as a ``max_replicas``-wide
    ``build_replicas`` would, so an AOT manifest compiled for the max
    topology hydrates any slot. The controller owns slot allocation:
    founding replicas occupy slots ``0..n-1``; a removed replica frees
    its slot for the next scale-out/replacement.

    ``registry`` (obs.metrics.MetricsRegistry) is the load sensor;
    without one the controller falls back to probing the replica
    servers directly (the unit-test path). ``evaluator`` contributes
    the burn-rate alert state. ``prewarm_manifest`` enables
    prewarm-before-join; ``warm_samples`` is the cold-warmup fallback
    (one of the two should be provided, or a joining replica takes
    affinity assignments straight into cold compiles).
    """

    def __init__(
        self,
        router,
        *,
        replica_factory: Callable,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 0.5,
        cooldown_s: float = 2.0,
        up_load: float = 8.0,
        down_load: float = 1.0,
        surge_mult: float = 2.0,
        down_ticks: int = 3,
        flap_suppress_s: float | None = None,
        heal_after_s: float = 5.0,
        drain_timeout_s: float = 30.0,
        registry=None,
        evaluator=None,
        pressure_objectives: Iterable[str] = PRESSURE_OBJECTIVES,
        warm_samples=None,
        pack_plan=None,
        prewarm_manifest: dict | None = None,
        sink=None,
        clock: Callable[[], float] = time.monotonic,
        tenants=None,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= autoscale min <= max, got "
                f"{min_replicas}/{max_replicas}"
            )
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        if not 0 <= down_load < up_load:
            raise ValueError(
                "hysteresis needs 0 <= down_load < up_load, got "
                f"{down_load}/{up_load}"
            )
        if down_ticks < 1:
            raise ValueError(f"down_ticks must be >= 1, got {down_ticks}")
        if heal_after_s <= 0:
            raise ValueError(
                f"heal_after_s must be > 0, got {heal_after_s}"
            )
        self.router = router
        self.replica_factory = replica_factory
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.up_load = float(up_load)
        self.down_load = float(down_load)
        # Surge scaling: load this far past up_load bypasses the up
        # cooldown (a step change in demand must not pay one cooldown
        # per replica while the backlog compounds). <= 1 disables.
        self.surge_mult = float(surge_mult)
        self.down_ticks = down_ticks
        # Flap suppressor: scale-in is vetoed this close after a
        # scale-out (a burst's tail must not retire the replica the
        # burst just bought). Default: three cooldowns.
        self.flap_suppress_s = (
            float(flap_suppress_s)
            if flap_suppress_s is not None
            else 3.0 * self.cooldown_s
        )
        self.heal_after_s = float(heal_after_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.registry = registry
        self.evaluator = evaluator
        self.pressure_objectives = tuple(pressure_objectives)
        self.warm_samples = warm_samples
        self.pack_plan = pack_plan
        self.prewarm_manifest = prewarm_manifest
        self.sink = sink
        # TenantPolicy (serve/policies.py), when the pool is multi-
        # tenant: per-tenant slo_alert edges (latency_p99:<tenant>)
        # ATTRIBUTE scale-out to the tenant burning budget, and
        # pressure owned entirely by batch-class tenants is answered by
        # deferral (WFQ/priority already shields interactive), not
        # replicas — the batch-deferral veto.
        self.tenants = tenants
        self._clock = clock
        pool = router.pool()
        # Slot ledger: founding replicas occupy the first slots in pool
        # order; everything else is free for scale-out/replacement.
        self._slot_of = {
            r.replica_id: i for i, r in enumerate(pool)
        }  #: guarded_by _lock
        self._free_slots = sorted(
            set(range(max_replicas)) - set(self._slot_of.values())
        )  #: guarded_by _lock
        self._next_id = (
            max((r.replica_id for r in pool), default=-1) + 1
        )  #: guarded_by _lock
        # Guard state: per-direction last-action stamps, the calm-tick
        # counter, the per-replica first-seen-unhealthy dwell stamps,
        # and the last emitted hold reason (vetoes are edge events).
        self._last_up = -float("inf")  #: guarded_by _lock
        self._last_down = -float("inf")  #: guarded_by _lock
        self._last_heal = -float("inf")  #: guarded_by _lock
        self._calm_ticks = 0  #: guarded_by _lock
        self._unhealthy_since: dict[int, float] = {}  #: guarded_by _lock
        self._last_hold: str | None = None  #: guarded_by _lock
        # Replica-seconds ledger (the A/B's efficiency axis): integral
        # of pool size over time, stepped at every tick/size change.
        self._rs_total = 0.0  #: guarded_by _lock
        self._rs_since: float | None = None  #: guarded_by _lock
        self._rs_size = 0  #: guarded_by _lock
        self._ticks = 0  #: guarded_by _lock
        self._scale_ups = 0  #: guarded_by _lock
        self._scale_downs = 0  #: guarded_by _lock
        self._replaces = 0  #: guarded_by _lock
        self._holds = 0  #: guarded_by _lock
        self._errors = 0  #: guarded_by _lock
        self._last_tick_error: str | None = None  #: guarded_by _lock
        self._lock = threading.Lock()
        # Serializes whole ticks: manual test ticks must not interleave
        # with the cadence thread's (one action per tick, globally).
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._closed = False

    # -- observation --------------------------------------------------------

    def observed_load(self) -> float:
        """Per-replica in-system load: pool queue depth + resident
        rollout sessions, divided by pool size. Read from the metrics
        registry's gauges when one is attached (the controller
        subscribes to the sensor plane, not raw events); probed from
        the replica servers directly otherwise."""
        pool = self.router.pool()
        n = max(1, len(pool))
        if self.registry is not None:
            total = self.registry.aggregate_gauge(
                "serve_queue_depth"
            ) + self.registry.aggregate_gauge("serve_resident_sessions")
        else:
            total = float(
                sum(
                    r.server.depth() + r.server.resident_sessions()
                    for r in pool
                )
            )
        return total / n

    def _active_alerts(self) -> list[str]:
        if self.evaluator is None:
            return []
        return sorted(
            name for name, on in self.evaluator.active().items() if on
        )

    # -- the loop -----------------------------------------------------------

    def tick(self) -> dict:
        """One control cycle: observe -> decide -> act (at most one
        action). Returns the decision record."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self) -> dict:
        now = self._clock()
        pool = self.router.pool()
        self._note_pool_size(len(pool), now)
        with self._lock:
            self._ticks += 1
        healed = self._heal(now, pool)
        if healed is not None:
            return healed
        n = len(pool)
        load = self.observed_load()
        alerts = self._active_alerts()
        # Tenant-scoped alerts are named ``<objective>:<tenant>``
        # (metrics.tenant_objectives); their BASE name decides whether
        # they are capacity pressure, and their suffix attributes it.
        pressure = [
            a
            for a in alerts
            if a.split(":", 1)[0] in self.pressure_objectives
        ]
        want_up = load >= self.up_load or bool(pressure)
        calm = load <= self.down_load and not alerts
        with self._lock:
            self._calm_ticks = self._calm_ticks + 1 if calm else 0
            calm_ticks = self._calm_ticks
            last_up, last_down = self._last_up, self._last_down
        if n < self.min_replicas:
            # Below the floor (a replacement build failed mid-heal):
            # restore the minimum before any other consideration — but
            # still on the up cooldown, so a persistently failing
            # factory cannot hot-loop the build.
            if now - last_up < self.cooldown_s:
                return self._hold(now, n, "cooldown_up", load, alerts)
            return self._scale_up(now, n, "below_min", load, alerts)
        if want_up:
            # Batch-deferral veto: when the ONLY pressure is SLO burn
            # attributed entirely to batch-class tenants (raw load is
            # below up_load), the right answer is deferral — WFQ +
            # priority classes already push the pain onto batch work —
            # not buying replicas for a flood the policy exists to
            # absorb. Interactive-attributed or pool-level burn still
            # scales out.
            if (
                self.tenants is not None
                and pressure
                and load < self.up_load
                and all(
                    ":" in a
                    and self.tenants.priority(a.split(":", 1)[1])
                    == "batch"
                    for a in pressure
                )
            ):
                return self._hold(now, n, "batch_deferral", load, alerts)
            # Attribution: prefer a tenant-scoped alert for the reason
            # (``slo:latency_p99:alice`` names who is burning budget).
            attributed = [a for a in pressure if ":" in a]
            reason = (
                f"slo:{(attributed or pressure)[0]}"
                if pressure
                else "load"
            )
            if n >= self.max_replicas:
                return self._hold(now, n, "at_max", load, alerts)
            surge = (
                self.surge_mult > 1.0
                and load >= self.surge_mult * self.up_load
            )
            if surge:
                reason = "surge"
            elif now - last_up < self.cooldown_s:
                return self._hold(now, n, "cooldown_up", load, alerts)
            return self._scale_up(now, n, reason, load, alerts)
        if n > self.min_replicas and calm and calm_ticks >= self.down_ticks:
            if now - last_up < self.flap_suppress_s:
                return self._hold(now, n, "flap_suppressed", load, alerts)
            if now - last_down < self.cooldown_s:
                return self._hold(now, n, "cooldown_down", load, alerts)
            return self._scale_down(now, n, load, alerts)
        with self._lock:
            self._last_hold = None  # nothing wanted: reset the veto edge
        return {"action": "none", "pool": n, "load": load}

    # -- actions ------------------------------------------------------------

    def _scale_up(
        self, now: float, n: int, reason: str, load: float, alerts
    ) -> dict:
        with self._lock:
            if not self._free_slots:
                # Every slot occupied at sub-max pool size can only
                # mean an id/slot leak — surface it, don't wedge.
                self._errors += 1
                return {"action": "error", "reason": "no_free_slot"}
            slot = self._free_slots.pop(0)
            rid = self._next_id
            self._next_id += 1
        t0 = self._clock()
        try:
            replica = self.replica_factory(rid, slot)
            warm_source = self._warm_before_join(replica, slot)
            self.router.add_replica(replica)
        except Exception as err:  # noqa: BLE001 — the loop must outlive one failed join
            with self._lock:
                self._free_slots.append(slot)
                self._free_slots.sort()
                self._errors += 1
                # Stamp the cooldown anyway: a persistently failing
                # factory retries at cooldown cadence, not per tick.
                self._last_up = now
            self._decision(
                "hold", f"scale_up_failed:{type(err).__name__}", n,
                load=load, alerts=alerts, detail=str(err),
            )
            return {"action": "error", "reason": str(err)}
        with self._lock:
            self._slot_of[rid] = slot
            self._last_up = now
            self._calm_ticks = 0
            self._last_hold = None
            self._scale_ups += 1
        self._note_pool_size(n + 1, self._clock())
        self._decision(
            "scale_up", reason, n + 1, replica=rid, load=load,
            alerts=alerts,
        )
        self._event(
            events.SCALE_UP,
            replica=rid,
            pool=n + 1,
            reason=reason,
            warm_source=warm_source,
            seconds=self._clock() - t0,
            load=load,
        )
        return {
            "action": "scale_up", "replica": rid, "pool": n + 1,
            "reason": reason, "warm_source": warm_source,
        }

    def _warm_before_join(self, replica, slot: int) -> str:
        """Prewarm-before-join: hydrate from the AOT manifest when it
        covers this replica's device slot (the manifest keys blocks by
        the founding topology's ids == slots; a replacement under a
        fresh id re-keys the slot's block), cold warmup otherwise. The
        replica is serve-ready BEFORE add_replica admits it to routing
        — a cold join would take affinity assignments straight into
        the compile stall this tier exists to prevent."""
        manifest = self.prewarm_manifest
        if manifest is not None and str(slot) in manifest.get(
            "per_replica", {}
        ):
            remapped = {
                **manifest,
                "per_replica": {
                    str(replica.replica_id): manifest["per_replica"][
                        str(slot)
                    ]
                },
            }
            stats = replica.prewarm_from(remapped)
            if stats.get("source") == "snapshot":
                return "snapshot"
        if self.warm_samples is not None:
            replica.warm(
                self.warm_samples, pack_plan=self.pack_plan
            )
            return "compile"
        return (replica.warm_stats or {}).get("source", "none")

    def _scale_down(self, now: float, n: int, load: float, alerts) -> dict:
        pool = self.router.pool()
        # Victim: fewest resident sessions first (least state to hand
        # over), then lowest depth; newest replica on ties — founding
        # (manifest-covered) replicas stick around longest.
        victim = min(
            pool,
            key=lambda r: (
                r.server.resident_sessions(),
                r.server.depth(),
                -r.replica_id,
            ),
        )
        rid = victim.replica_id
        with self._lock:
            self._last_down = now
            self._calm_ticks = 0
            self._last_hold = None
            self._scale_downs += 1
        self._decision(
            "scale_down", "calm", n - 1, replica=rid, load=load,
            alerts=alerts,
        )
        self.router.remove_replica(
            rid, timeout_s=self.drain_timeout_s, reason="scale_in"
        )
        with self._lock:
            slot = self._slot_of.pop(rid, None)
            if slot is not None:
                self._free_slots.append(slot)
                self._free_slots.sort()
        self._note_pool_size(n - 1, self._clock())
        self._event(
            events.SCALE_DOWN,
            replica=rid,
            pool=n - 1,
            reason="calm",
            load=load,
        )
        return {"action": "scale_down", "replica": rid, "pool": n - 1}

    def _heal(self, now: float, pool) -> dict | None:
        """Replace dead/wedged/breaker-stuck replicas. Dead replicas
        replace immediately; the others after ``heal_after_s`` of
        sustained unhealth (a breaker mid-cooldown or a transient stall
        must recover on its own first). Returns the decision when an
        action (or its veto) happened, None to fall through to the
        sizing rules."""
        live_ids = set()
        condemned = None
        verdict_reason = ""
        for r in pool:
            rid = r.replica_id
            live_ids.add(rid)
            verdict = self.router.assess(r)
            if verdict.healthy or verdict.reason not in HEAL_REASONS:
                with self._lock:
                    self._unhealthy_since.pop(rid, None)
                continue
            dead = verdict.reason == "dead"
            with self._lock:
                since = self._unhealthy_since.setdefault(rid, now)
            if condemned is None and (
                dead or now - since >= self.heal_after_s
            ):
                condemned = r
                verdict_reason = verdict.reason
        with self._lock:
            for rid in list(self._unhealthy_since):
                if rid not in live_ids:
                    self._unhealthy_since.pop(rid)
            last_heal = self._last_heal
        if condemned is None:
            return None
        n = len(pool)
        if n == 1:
            # remove_replica refuses the last replica; a 1-replica pool
            # heals by scaling OUT first (next tick's pressure path) —
            # veto with the honest reason.
            return self._hold(now, n, "last_replica", None, [])
        if now - last_heal < self.cooldown_s:
            return self._hold(now, n, "cooldown_heal", None, [])
        rid = condemned.replica_id
        t0 = self._clock()
        self.router.remove_replica(
            rid,
            timeout_s=self.drain_timeout_s,
            reason=f"heal_{verdict_reason}",
        )
        with self._lock:
            slot = self._slot_of.pop(rid, 0)
            new_id = self._next_id
            self._next_id += 1
            self._unhealthy_since.pop(rid, None)
        try:
            replica = self.replica_factory(new_id, slot)
            self._warm_before_join(replica, slot)
            self.router.add_replica(replica)
        except Exception as err:  # noqa: BLE001 — a failed rebuild must not kill the loop
            with self._lock:
                self._free_slots.append(slot)
                self._free_slots.sort()
                self._errors += 1
                # Stamp the heal cooldown even on failure: a
                # persistently failing factory must retry at cooldown
                # cadence — condemning one replica per TICK would
                # dismantle the pool during a transient storm.
                self._last_heal = now
            self._decision(
                "hold", f"replace_failed:{type(err).__name__}", n - 1,
                replica=rid, detail=str(err),
            )
            return {"action": "error", "reason": str(err)}
        with self._lock:
            self._slot_of[new_id] = slot
            self._last_heal = now
            self._replaces += 1
            self._last_hold = None
        self._decision(
            "replace", verdict_reason, len(self.router.pool()),
            replica=rid,
        )
        self._event(
            events.REPLICA_REPLACE,
            from_replica=rid,
            to_replica=new_id,
            reason=verdict_reason,
            pool=len(self.router.pool()),
            seconds=self._clock() - t0,
        )
        return {
            "action": "replace", "from_replica": rid,
            "to_replica": new_id, "reason": verdict_reason,
        }

    def _hold(
        self, now: float, n: int, guard: str, load, alerts
    ) -> dict:
        """A wanted move was vetoed by a stability guard. Emitted as an
        ``autoscale_decision`` EDGE (the first veto for this guard;
        steady vetoes stay silent — the event stream must not spam one
        record per tick of a long cooldown)."""
        with self._lock:
            self._holds += 1
            edge = self._last_hold != guard
            self._last_hold = guard
        if edge:
            self._decision("hold", guard, n, load=load, alerts=alerts)
        return {"action": "hold", "reason": guard, "pool": n}

    # -- bookkeeping --------------------------------------------------------

    def _note_pool_size(self, n: int, now: float) -> None:
        with self._lock:
            if self._rs_since is not None:
                self._rs_total += (now - self._rs_since) * self._rs_size
            self._rs_since, self._rs_size = now, n

    def replica_seconds(self, now: float | None = None) -> float:
        """The pool-size integral so far — the capacity actually paid
        for, the number the A/B holds against a static pool."""
        now = self._clock() if now is None else now
        with self._lock:
            total = self._rs_total
            if self._rs_since is not None:
                total += (now - self._rs_since) * self._rs_size
            return total

    def _decision(
        self, action: str, reason: str, pool_n: int, *, replica=None,
        load=None, alerts=None, detail=None,
    ) -> None:
        self._event(
            events.AUTOSCALE_DECISION,
            action=action,
            reason=reason,
            pool=pool_n,
            min=self.min_replicas,
            max=self.max_replicas,
            **({"replica": replica} if replica is not None else {}),
            **({"load": round(load, 3)} if load is not None else {}),
            **({"alerts": alerts} if alerts else {}),
            **({"detail": detail} if detail else {}),
        )

    def _event(self, event: str, **fields) -> None:
        if self.sink is not None:
            self.sink.log(event=event, **fields)

    def stats(self) -> dict:
        """The run.json ``autoscale`` block."""
        with self._lock:
            return {
                "min": self.min_replicas,
                "max": self.max_replicas,
                "interval_s": self.interval_s,
                "cooldown_s": self.cooldown_s,
                "ticks": self._ticks,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "replaces": self._replaces,
                "holds": self._holds,
                "errors": self._errors,
                **(
                    {"last_error": self._last_tick_error}
                    if self._last_tick_error
                    else {}
                ),
                "pool": self._rs_size,
            }

    # -- thread lifecycle ---------------------------------------------------

    def start(self) -> "AutoscaleController":
        if self._thread is not None:
            raise RuntimeError("controller already started")
        self._thread = threading.Thread(
            target=self._run, name="gnot-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as err:  # noqa: BLE001 — one bad tick must not end elasticity
                with self._lock:
                    self._errors += 1
                    first = self._last_tick_error is None
                    self._last_tick_error = f"{type(err).__name__}: {err}"
                if first:
                    # Elasticity silently dying would be invisible
                    # until the post-run stats; put the FIRST failure
                    # in the event stream (repeats stay counted only).
                    self._decision(
                        "hold",
                        f"tick_failed:{type(err).__name__}",
                        len(self.router.pool()),
                        detail=str(err),
                    )

    def close(self) -> dict:
        """Stop the loop and settle the replica-seconds ledger.
        Idempotent. Returns ``stats()``."""
        with self._lock:
            closed, self._closed = self._closed, True
        if not closed:
            self._stop.set()
            t = self._thread
            if t is not None:
                t.join(timeout=max(5.0, 2 * self.interval_s))
                self._thread = None
            self._note_pool_size(
                len(self.router.pool()), self._clock()
            )
        return self.stats()
