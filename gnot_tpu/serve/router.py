"""Compile-affinity request router over N engine replicas.

The single-``InferenceServer`` tier serializes every dispatch through
one worker loop; replica parallelism is the remaining throughput
multiplier after packing killed padding waste (ISSUE 9 / ROADMAP). The
``ReplicaRouter`` front-ends N replicas (``serve/replica.py`` — one
engine per device or mesh slice) and decides placement per request:

1. **Health first** (``policies.ReplicaHealthPolicy``): a replica with
   an open circuit breaker, a wedged worker (requests in-system but the
   loop stalled), a warming rolling-reload, or a dead worker thread is
   DRAINED — new traffic flows to its siblings instead of being shed.
   Transitions emit ``replica_health`` events. When NO replica is
   healthy the router still routes (least-loaded) so the per-replica
   policies answer with their own reasons — the router never invents a
   new failure mode.
2. **Bucket affinity** (default policy): prefer a replica that already
   compiled this request's bucket (or ``PackPlan``). A bucket seen for
   the first time is ASSIGNED to the least-loaded healthy replica and
   recorded before the request lands, so the one-off XLA compile
   happens on exactly one replica — steady-state recompiles per replica
   stay O(log L_max) and a cold compile stalls one replica, never the
   pool. A full affinity target spills to the least-loaded sibling
   (``spill``) rather than shedding at a hot replica's door.
   ``least_loaded`` and ``round_robin`` policies are available for
   comparison (``--route_policy``).
3. **Rolling hot-reload** (``reload()``): replicas reload one at a
   time — the warming replica is drained for NEW traffic while its old
   weights keep serving what it already holds, siblings carry the load,
   and a replica whose restore fails (corrupt checkpoint, exhausted
   retries) keeps its old weights and the rollout continues. At most
   one replica warms at any moment (the rollout lock). Each step emits
   a ``rolling_reload`` event.

4. **Rollout sessions** (``submit_rollout``, serve/rollout.py): a
   K-step autoregressive session places ONCE (health + affinity, one
   ``route`` event tagged with the session id) and then stays on its
   owner — steps 2..K never re-route while the owner is healthy
   (session affinity; the carry is resident there). Load accounting is
   session-aware: placement weighs in-system requests PLUS resident
   sessions, so a replica holding many K-step commitments is not
   preferred for new work. When the owner fails mid-rollout (breaker
   open, NaN/dispatch error, ``replica_kill``/worker death, stale
   carry) the session is re-placed on a sibling FROM its last
   host-side snapshot and replays forward (``session_migrate`` event;
   at-least-once step semantics, re-delivery suppressed) — zero lost
   sessions under single-replica failures; with ``session_migration``
   off or the budget spent the future resolves with the failure,
   counted ``lost`` in the sessions rollup.

5. **Elastic membership** (``add_replica`` / ``remove_replica``): the
   pool grows live (a warmed replica joins routing at the next
   placement) and shrinks via DRAIN-then-remove — the leaving replica
   goes ``retiring`` (no new placement), hands its resident rollout
   sessions to siblings at a step boundary (``session_migrate`` with
   reason ``scale_in``; zero replay, no failure-budget spend), flushes
   its queue, and retires with its latency history RETAINED in the
   pool rollup (a membership change never drops served requests from
   the final percentiles). ``serve/autoscaler.py`` drives both ends
   from live SLO pressure. Persisted rollout sessions
   (``session_store``) resume across restarts via ``resume_rollout``.

Every placement is observable: one ``route`` event per submitted
request (replica, bucket, policy, decision reason, target depth), and
``drain()`` emits a pool-level ``serve_summary`` whose ``per_replica``
rollup and ``routing`` block sit beside the per-replica summaries the
replica servers emit themselves (each tagged ``replica: i``).

Thread-safety: routing counters, health memory and the round-robin
cursor are shared between submitting threads and the reload/drain
threads — all access is under ``_lock`` (graftlint GL004 enforces the
annotations); the rollout sequencing uses its own ``_reload_lock`` so a
slow restore never blocks request placement.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Sequence

from gnot_tpu.data.batch import MeshSample, PackPlan
from gnot_tpu.obs import events
from gnot_tpu.obs.metrics import LogHistogram
from gnot_tpu.serve.policies import (
    ROUTE_POLICIES,
    ReplicaHealthPolicy,
)
from gnot_tpu.serve.replica import EngineReplica
from gnot_tpu.serve.rollout import RolloutFuture, RolloutSession
from gnot_tpu.serve.server import PACKED_BUCKET, InferenceServer


class ReplicaRouter:
    """N per-replica ``InferenceServer``s behind one ``submit()``.

    ``replicas`` are ``EngineReplica``s (``build_replicas``); the router
    constructs one ``InferenceServer`` per replica with the given
    serving knobs — per-replica admission (``queue_limit`` each),
    per-replica batcher, per-replica breaker — and tags each with its
    ``replica_id`` so the shared sink/tracer attribute every record.

    ``faults`` arms serve-side fault injection per replica: a dict
    ``{replica_id: FaultInjector}``, or a single injector (applied to
    replica 0 — the deterministic chaos-test shape). ``reload_fn`` is
    shared: every replica restores from the same checkpoint source,
    one at a time.
    """

    def __init__(
        self,
        replicas: Sequence[EngineReplica],
        *,
        route_policy: str = "affinity",
        max_batch: int = 4,
        max_wait_ms: float = 10.0,
        queue_limit: int = 64,
        default_deadline_ms: float = 0.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 1.0,
        sink=None,
        reload_fn: Callable | None = None,
        faults=None,
        preempt=None,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        pack_plan: PackPlan | None = None,
        wedge_after_s: float = 2.0,
        session_snapshot_every: int = 1,
        session_migration: bool = True,
        max_session_migrations: int = 3,
        metrics=None,
        session_store=None,
        persist_snapshots: bool = False,
        catalog=None,
        tenants=None,
    ):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        if route_policy not in ROUTE_POLICIES:
            raise ValueError(
                f"unknown route_policy {route_policy!r}; "
                f"one of {ROUTE_POLICIES}"
            )
        # The live pool list: grown by add_replica while submit /
        # reload / drain threads iterate — snapshot via _pool().
        # (Annotated late: _lock is constructed below, but GL004
        # collects annotations class-wide.)
        self.replicas = list(replicas)  #: guarded_by _lock
        self.route_policy = route_policy
        self.pack_plan = pack_plan
        self.sink = sink
        self.reload_fn = reload_fn
        self._clock = clock
        self._tracer = tracer
        self.health = ReplicaHealthPolicy(wedge_after_s=wedge_after_s)
        if faults is None:
            fault_map: dict = {}
        elif isinstance(faults, dict):
            fault_map = dict(faults)
        else:
            fault_map = {self.replicas[0].replica_id: faults}
        # Per-replica server construction knobs, kept so a scale-out
        # replica (add_replica) gets an identically-configured server.
        # Injected faults stay with the FOUNDING replicas only — a
        # scale-out replica is a fresh process-alike, not a chaos
        # target.
        self._server_kwargs = dict(
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            queue_limit=queue_limit,
            default_deadline_ms=default_deadline_ms,
            breaker_threshold=breaker_threshold,
            breaker_cooldown_s=breaker_cooldown_s,
            sink=sink,
            reload_fn=reload_fn,
            preempt=preempt,
            clock=clock,
            tracer=tracer,
            pack_plan=pack_plan,
            session_snapshot_every=session_snapshot_every,
            metrics=metrics,
            session_store=session_store,
            persist_snapshots=persist_snapshots,
            catalog=catalog,
            tenants=tenants,
        )
        # ONE TenantPolicy shared by every replica server (scale-outs
        # included, via _server_kwargs): the per-tenant admission
        # controllers are internally locked, so a tenant's quota bounds
        # its POOL-WIDE in-system count, and WFQ weights/priorities are
        # identical at every batcher.
        self.tenants = tenants
        # Shared program catalog (serve/catalog.py): every replica's
        # server attributes its dispatches into the ONE catalog (keys
        # are dtype-scoped program signatures; traffic rows carry the
        # replica id), and drain() joins it with the XLA cost entries
        # into the pool capacity model.
        self._catalog = catalog
        # On-disk rollout-session persistence (rollout.SessionStore):
        # each per-replica server persists drained sessions' final
        # snapshots; the router resumes them (resume_rollout).
        self._session_store = session_store
        # Live metrics plane (obs/metrics.py): the ONE registry every
        # per-replica server records into (replica-labeled series merge
        # losslessly into the pool view the publisher snapshots), plus
        # the router's own placement/migration counters and pool-size
        # gauge — the sensor layer the autoscaling controller reads.
        self._metrics = metrics
        # Per-replica wedge gauges, cached off the hot path (health is
        # assessed per placement — registry get-or-create is for misses
        # only). Benign races resolve to the same registry object.
        self._wedge_gauges: dict = {}
        if metrics is not None:
            metrics.gauge(
                "pool_replicas", fn=lambda: float(len(self._pool()))
            )
        # Rollout-session policy (serve/rollout.py): whether a session
        # whose owner fails mid-rollout is re-placed from its snapshot
        # (the fault-tolerant default) or resolved with the failure
        # (the chaos A/B's no-migration twin), and how many re-
        # placements one session may consume before the failure is
        # terminal (a pool-wide outage must not bounce sessions
        # forever).
        self.session_migration = session_migration
        if max_session_migrations < 0:
            raise ValueError(
                "max_session_migrations must be >= 0, got "
                f"{max_session_migrations}"
            )
        self.max_session_migrations = max_session_migrations
        for r in self.replicas:
            r.attach_server(
                InferenceServer(
                    r.engine,
                    faults=fault_map.get(r.replica_id),
                    replica=r.replica_id,
                    **self._server_kwargs,
                )
            )
        # The pool's serving compute dtype (models/precision.py): one
        # dtype per pool BY CONSTRUCTION (mixed-precision pools would
        # break program identity for routing), read off the engines;
        # tagged onto every route event and the pool serve_summary.
        self._dtype = getattr(
            self.replicas[0].engine, "dtype", "float32"
        )
        self._lock = threading.Lock()
        # Placement counters + health memory, shared between every
        # submitting thread and the reload/drain threads.
        self._submitted = 0  #: guarded_by _lock
        self._routed: dict[int, int] = {}  #: guarded_by _lock
        self._spills = 0  #: guarded_by _lock
        self._rr_next = 0  #: guarded_by _lock
        # Last emitted health reason per replica (transition edges
        # become replica_health events; steady state stays silent).
        self._health_seen: dict[int, str] = {}  #: guarded_by _lock
        self._rollouts = 0  #: guarded_by _lock
        # Rollout-session ledger: id allocation and pool-level outcome
        # counters for the serve_summary sessions rollup. (Ownership
        # needs no router-side map: a session IS resident on its owning
        # server — the replica's session table is the affinity record.)
        # Mutated by submitting threads AND the migration callback
        # (which runs on a failed replica's worker thread).
        self._sessions_started = 0  #: guarded_by _lock
        self._sessions_migrated = 0  #: guarded_by _lock
        self._sessions_lost = 0  #: guarded_by _lock
        # Rollout sequencing: holding it means "a rolling reload is in
        # progress"; one replica warms at a time by construction.
        self._reload_lock = threading.Lock()
        self._drained = threading.Event()
        # Retired-replica history (remove_replica): the pool rollup
        # must keep every replica that EVER served — percentiles merge
        # the retired histograms, counters include the retired
        # summaries — or a scale-in would silently drop its requests
        # from the final serve_summary (the membership-change history
        # bug this ledger fixes).
        self._retired: dict[int, dict] = {}  #: guarded_by _lock
        self._retired_hist = LogHistogram()
        self._retired_step_hist = LogHistogram()
        # Per-tenant latency histograms of retired replicas (same
        # retention contract as _retired_hist: a scale-in never drops a
        # tenant's served latencies from the pool tenants rollup).
        self._retired_tenant_hists: dict = {}  #: guarded_by _lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        for r in self._pool():
            r.server.start()
        return self

    def _pool(self) -> list[EngineReplica]:
        """Snapshot of the replica list — ``add_replica`` grows it
        while submit/reload/drain threads iterate."""
        with self._lock:
            return list(self.replicas)

    def pool(self) -> list[EngineReplica]:
        """Public snapshot of the live pool (the autoscale controller's
        read of current membership)."""
        return self._pool()

    def assess(self, replica: EngineReplica):
        """Public health verdict for one pooled replica (emits the
        ``replica_health`` edge exactly like a placement would) — the
        autoscale controller's self-healing scan reads this instead of
        re-deriving health from raw signals."""
        return self._assess(replica, self._clock())

    def prewarm_from(self, manifest: dict) -> dict:
        """Hydrate EVERY pool replica from the deploy manifest's
        warm-replica snapshots (``tools/aot_prewarm.py`` →
        ``EngineReplica.prewarm_from``): each replica installs its
        AOT-compiled executables and seeds its affinity set without a
        single trace, compile, or dispatch. Emits one ``replica_warm``
        event (and a warm-vs-cold tracer span) per replica. Returns
        ``{replica_id: warm_stats}``."""
        stats = {}
        for r in self._pool():
            t0 = self._clock()
            stats[r.replica_id] = r.prewarm_from(manifest)
            self._note_warm(r, t0)
        return stats

    def add_replica(self, replica: EngineReplica) -> EngineReplica:
        """Scale-out: attach an identically-configured server to an
        already-warmed replica (``build_replica`` + ``warm`` or
        ``prewarm_from``), start it, and place it in the live pool —
        submitted traffic can route to it from the next placement on.
        Emits the replica's ``replica_warm`` event so the scale-out's
        warm provenance (cold compile vs snapshot hydration) is in the
        event stream. The replica must be warmed BEFORE it joins: an
        un-warmed replica would take affinity assignments straight
        into cold compiles — the stall this tier exists to prevent."""
        t0 = self._clock()
        # Duplicate guard FIRST: attaching/starting before it would
        # clobber the pooled replica's live server (stranding its
        # queued futures) and leak a running worker thread. Retired ids
        # are reserved too — re-using one would collide with its
        # retained history in the pool rollup.
        with self._lock:
            if any(
                r.replica_id == replica.replica_id for r in self.replicas
            ):
                raise ValueError(
                    f"replica {replica.replica_id} is already in the pool"
                )
            if replica.replica_id in self._retired:
                raise ValueError(
                    f"replica id {replica.replica_id} was retired from "
                    "this pool; scale-out replicas need fresh ids"
                )
        replica.attach_server(
            InferenceServer(
                replica.engine,
                replica=replica.replica_id,
                **self._server_kwargs,
            )
        )
        replica.server.start()
        with self._lock:
            if any(
                r.replica_id == replica.replica_id for r in self.replicas
            ):
                # Racing add of the same id slipped between the checks:
                # shut our server down before refusing.
                replica.server.drain(timeout_s=0.0)
                raise ValueError(
                    f"replica {replica.replica_id} is already in the pool"
                )
            self.replicas.append(replica)
        self._note_warm(replica, None)
        return replica

    def _note_warm(self, r: EngineReplica, t0: float | None) -> None:
        """One replica's warm provenance into the event stream + trace:
        a ``replica_warm`` event with the replica's warm_stats, and a
        span on the aux ("r") stream whose ``source`` arg says snapshot
        (prewarmed) vs compile (cold) — the warm-vs-cold latency is
        readable straight off the trace timeline."""
        stats = r.warm_stats or {
            "source": "none", "programs": 0, "seconds": 0.0,
            "hits": None, "misses": None,
        }
        self._event(
            events.REPLICA_WARM,
            replica=r.replica_id,
            source=stats["source"],
            programs=stats["programs"],
            seconds=stats["seconds"],
            hits=stats.get("hits"),
            misses=stats.get("misses"),
            # Why a replica did NOT hydrate (params_mismatch /
            # no_manifest_block) — the difference between "warm pool"
            # and "silently cold pool" in the event stream.
            **(
                {"reason": stats["reason"]} if stats.get("reason") else {}
            ),
        )
        if self._tracer is not None:
            trace = self._tracer.start_trace(stream="r")
            if trace is not None:
                # add_replica warms BEFORE joining the pool (t0=None):
                # anchor the span at now - warm duration so its length
                # still reads as the warm cost on the timeline.
                now = self._clock()
                start = t0 if t0 is not None else now - stats["seconds"]
                self._tracer.add_span(
                    "replica_warm",
                    start,
                    now,
                    trace=trace,
                    args={
                        "replica": r.replica_id,
                        "source": stats["source"],
                        "programs": stats["programs"],
                    },
                )

    def remove_replica(
        self,
        replica_id: int,
        *,
        timeout_s: float = 30.0,
        reason: str = "scale_in",
    ) -> dict:
        """Scale-in / self-healing removal: DRAIN-then-remove, never
        remove-then-shed.

        1. The replica goes ``retiring`` (a ``replica_health`` edge):
           new placement flows to siblings while it keeps serving what
           it already holds.
        2. Resident rollout sessions hand over to siblings at their
           next step boundary (``session_migrate`` events, reason
           ``scale_in``; the owner snapshots at the current cursor
           first, so the handover replays nothing). A dead replica's
           sessions already migrated through the failure path.
        3. Its server drains: queued work completes (deadline shedding
           still applies — drain never invents a new failure mode) and
           the per-replica ``serve_summary`` is emitted.
        4. The replica leaves the pool, but its history does not: its
           latency/step histograms and summary counters are retained
           and merged into the final pool rollup (``drain``), so the
           pool percentiles keep every request the retired replica
           ever served.

        Returns the retired replica's serve summary. Refuses to remove
        the last replica (the pool must keep serving). The handover
        wait is bounded by wall time, not the injected clock — a fake
        clock must not spin it forever."""
        with self._lock:
            target = next(
                (r for r in self.replicas if r.replica_id == replica_id),
                None,
            )
            if target is None:
                raise ValueError(f"replica {replica_id} is not in the pool")
            if len(self.replicas) == 1:
                raise ValueError(
                    "cannot remove the last replica; the pool must "
                    "keep serving (scale out first)"
                )
        target.set_retiring(True)
        # The retiring edge lands in the event stream NOW, not at the
        # next unrelated placement.
        self._assess(target, self._clock())
        srv = target.server
        deadline = time.monotonic() + timeout_s
        if srv.worker_alive():
            srv.begin_eviction(self._evict_session)
            while (
                srv.resident_sessions()
                and srv.worker_alive()
                and time.monotonic() < deadline
            ):
                time.sleep(0.002)
        summary = srv.drain(max(0.0, deadline - time.monotonic()))
        with self._lock:
            self._retired[replica_id] = {
                "summary": summary,
                "warm_stats": target.warm_stats,
            }
            # Histograms merge UNDER the same lock as the ledger
            # insertion: a concurrent drain() snapshots _retired and
            # excludes ledgered replicas from its live merge, so the
            # ledger entry and its histograms must appear atomically —
            # or the racing drain drops this replica's latencies from
            # the pool percentiles. (Histogram locks are leaves; no
            # ordering hazard.)
            self._retired_hist.merge(srv.latency_histogram())
            self._retired_step_hist.merge(srv.step_latency_histogram())
            for t, h in srv.tenant_rollup()["hists"].items():
                self._retired_tenant_hists.setdefault(
                    t, LogHistogram()
                ).merge(h)
            self.replicas = [
                r for r in self.replicas if r.replica_id != replica_id
            ]
            self._health_seen.pop(replica_id, None)
            pool_n = len(self.replicas)
        self._wedge_gauges.pop(replica_id, None)
        if self._metrics is not None:
            # Drop the replica's CALLBACK gauges (depth/breaker/
            # sessions/wedge): their closures would otherwise pin the
            # drained server — and its engine's device-resident weights
            # — alive forever under autoscale churn. Counters and
            # histograms stay: the live plane's cumulative pool rollup
            # must keep the retired replica's history, exactly like the
            # drain-time summary does.
            self._metrics.unregister_gauges(replica=replica_id)
        self._event(
            events.REPLICA_REMOVE,
            replica=replica_id,
            reason=reason,
            requests=summary.get("requests", 0),
            completed=summary.get("completed", 0),
            pool=pool_n,
            drain_timeout_s=timeout_s,
        )
        return summary

    def _evict_session(self, session, from_replica: int | None) -> bool:
        """Re-place one resident session from a retiring replica onto a
        sibling (called by the retiring owner's worker at a step
        boundary; the owner snapshotted at the current cursor, so
        nothing replays). Returns False when no sibling can take it —
        the owner keeps it and the removal's drain resolves it
        honestly. Planned handovers do not consume the session's
        failure-migration budget."""
        now = self._clock()
        candidates = [
            r
            for r in self._pool()
            if r.replica_id != from_replica and not r.retiring
        ]
        healthy = [r for r in candidates if self._assess(r, now).healthy]
        pool = healthy or [
            r for r in candidates if r.server.worker_alive()
        ]
        if not pool:
            return False
        with self._lock:
            target = min(pool, key=self._load)
            self._sessions_migrated += 1
        if self._metrics is not None:
            self._metrics.counter("router_migrations_total").inc()
        at_step = session.cursor
        self._event(
            events.SESSION_MIGRATE,
            session=session.sid,
            from_replica=from_replica,
            to_replica=target.replica_id,
            at_step=at_step,
            replay_from=at_step,
            reason="scale_in",
        )
        target.server.submit_rollout(session=session)
        return True

    # -- placement ---------------------------------------------------------

    def submit(
        self,
        sample: MeshSample,
        *,
        deadline_ms: float | None = None,
        tenant: str | None = None,
        trace_ctx=None,
    ) -> Future:
        """Route one request to a replica and submit it there. The
        returned Future resolves exactly as a single server's would —
        the router adds placement, never a new failure mode. ``tenant``
        tags the request for the isolation plane (quota/WFQ/priority at
        the placed replica); placement itself is tenant-blind — fairness
        is enforced where queues live, not where routing happens.
        ``trace_ctx`` (an ``obs/dtrace.TraceContext``) carries a
        cluster-made sampling decision — the placed server adopts it
        instead of consulting its own sampling counter."""
        key, label = self._bucket_of(sample)
        replica, reason = self._place(key)
        with self._lock:
            self._submitted += 1
            rid = replica.replica_id
            self._routed[rid] = self._routed.get(rid, 0) + 1
            if reason == "spill":
                self._spills += 1
        self._note_route(reason)
        self._event(
            events.ROUTE,
            replica=replica.replica_id,
            bucket=label,
            policy=self.route_policy,
            reason=reason,
            depth=replica.server.depth(),
            dtype=self._dtype,
        )
        return replica.server.submit(
            sample, deadline_ms=deadline_ms, tenant=tenant,
            trace_ctx=trace_ctx,
        )

    def _note_route(self, reason: str) -> None:
        """One placement decision into the live registry: the per-
        reason route counter (spills therefore have their own series —
        the duplicated-compile pressure gauge the affinity policy is
        judged by)."""
        if self._metrics is not None:
            self._metrics.counter("router_routes_total", reason=reason).inc()

    def _bucket_of(self, sample: MeshSample) -> tuple:
        """(affinity key, human label) for a request — the same bucket
        the replica's own server will batch it under."""
        plan = self.pack_plan
        if plan is not None and plan.packable(sample):
            return PACKED_BUCKET, f"packed:{plan.n_rows}x{plan.row_len}"
        # Pool snapshot, not a bare list index: add_replica can resize
        # the list concurrently (all replicas share one bucket_key
        # impl, so replica 0 of the snapshot is as good as any).
        pn, pf = self._pool()[0].engine.bucket_key(sample)
        return (pn, pf), f"{pn}x{pf}"

    def _place(self, key) -> tuple[EngineReplica, str]:
        """One placement decision. Health gates the candidate pool
        (assessed outside the lock — it emits events); the policy then
        picks UNDER ``_lock`` so two concurrent first requests of the
        same cold bucket cannot both take the cold_assign path and pin
        it to two replicas; full targets spill."""
        now = self._clock()
        replicas = self._pool()
        healthy = [r for r in replicas if self._assess(r, now).healthy]
        pool = healthy
        degraded = not pool
        if degraded:
            # Nobody healthy: still place (least-loaded) — the chosen
            # replica's own breaker/admission answers with its reason.
            pool = replicas
        with self._lock:
            if self.route_policy == "round_robin" and not degraded:
                idx = self._rr_next % len(pool)
                self._rr_next += 1
                return pool[idx], "round_robin"
            open_pool = [r for r in pool if self._has_room(r)]
            if self.route_policy == "least_loaded" or degraded:
                target = min(open_pool or pool, key=self._load)
                return target, ("no_healthy" if degraded else "least_loaded")
            # affinity (the default)
            warm = [r for r in open_pool if r.has_bucket(key)]
            if warm:
                return min(warm, key=self._load), "affinity"
            # Assignment is checked over ALL replicas, not the health-
            # filtered pool: a bucket whose warm replica is temporarily
            # drained (warming/breaker) is a SPILL — the duplicated
            # compile the ledger must count — not a fresh cold bucket.
            assigned = any(r.has_bucket(key) for r in replicas)
            if open_pool:
                target = min(open_pool, key=self._load)
                if assigned:
                    # Its warm replica is full: spill to a sibling
                    # (which will compile this bucket — bounded by the
                    # replica count, still never O(traffic)).
                    target.note_bucket(key)
                    return target, "spill"
                # Cold bucket: assign it before the request lands, so
                # every later request of this bucket prefers the same
                # replica and the compile happens exactly once in the
                # pool.
                target.note_bucket(key)
                return target, "cold_assign"
            # Every candidate full: place at the least-loaded anyway;
            # its admission controller sheds at the door with the
            # honest reason.
            return min(pool, key=self._load), "pool_full"

    @staticmethod
    def _load(r: EngineReplica) -> tuple:
        # In-system requests PLUS resident rollout sessions: a session
        # is a standing K-step commitment that keeps re-entering the
        # replica's queue between its visible requests, so a replica
        # holding many sessions must not read as idle to least_loaded/
        # cold_assign placement (the ISSUE 13 load-accounting audit).
        # Tie-break on replica_id for determinism under equal load.
        return (
            r.server.depth() + r.server.resident_sessions(),
            r.replica_id,
        )

    @staticmethod
    def _has_room(r: EngineReplica) -> bool:
        return r.server.depth() < r.server.admission.limit

    def _assess(self, r: EngineReplica, now: float):
        """One replica's health verdict from live signals, emitting a
        ``replica_health`` event when the reason changes."""
        verdict = self.health.assess(
            breaker_state=r.server.breaker.state,
            warming=r.warming,
            progress_age_s=r.server.progress_age_s(now),
            depth=r.server.depth(),
            worker_alive=r.server.worker_alive(),
            # Post-cooldown open breaker: routable again so the half-
            # open trial dispatch can happen (a drained replica never
            # dispatches, and allow() — the only open->half_open
            # transition — runs only at dispatch).
            breaker_trial_due=r.server.breaker.trial_due(),
            # Mid-removal (remove_replica): drained for NEW placement
            # while it finishes what it holds.
            retiring=r.retiring,
        )
        if self._metrics is not None:
            # The SLO evaluator's `wedged` objective reads this level:
            # 1.0 while the policy judges the replica wedged (requests
            # in-system, worker loop silent past wedge_after_s).
            g = self._wedge_gauges.get(r.replica_id)
            if g is None:
                g = self._metrics.gauge(
                    "serve_wedged", replica=r.replica_id
                )
                self._wedge_gauges[r.replica_id] = g
            g.set(1.0 if verdict.reason == "wedged" else 0.0)
        with self._lock:
            changed = self._health_seen.get(r.replica_id) != verdict.reason
            if changed:
                self._health_seen[r.replica_id] = verdict.reason
                # Emitted UNDER the lock so concurrent assessors can't
                # interleave edges out of order (the event stream's
                # last edge must agree with _health_seen); edges are
                # rare, so the held-lock sink write is cheap.
                self._event(
                    events.REPLICA_HEALTH,
                    replica=r.replica_id,
                    healthy=verdict.healthy,
                    reason=verdict.reason,
                )
        return verdict

    # -- rollout sessions (serve/rollout.py) -------------------------------

    def submit_rollout(
        self,
        sample: MeshSample,
        steps: int,
        *,
        deadline_ms: float | None = None,
        rollout_deadline_ms: float | None = None,
        on_step=None,
        name: str | None = None,
        tenant: str | None = None,
        trace_ctx=None,
    ) -> RolloutFuture:
        """Place one autoregressive rollout session. The FIRST step
        routes like any request (health gate + affinity/policy — one
        ``route`` event, tagged with the session id); steps 2..K stay
        on the owning replica (session affinity: the carry is resident
        there, and spilling a healthy session would forfeit it). When
        the owner fails mid-rollout (breaker open, NaN/dispatch error,
        worker death) the session is re-placed on a sibling FROM its
        last host-side snapshot and replays forward (``session_migrate``
        event) — zero lost sessions, at-least-once step semantics —
        unless ``session_migration`` is off or the migration budget is
        spent, in which case the future resolves with the failure. The
        future ALWAYS resolves."""
        sc = self._server_kwargs
        ms = (
            deadline_ms
            if deadline_ms is not None
            else sc["default_deadline_ms"]
        )
        if name is not None and any(
            r.server.has_session(name) for r in self._pool()
        ):
            # Two live sessions under one sid would shadow each other
            # in a residence table and fight over one store snapshot.
            raise ValueError(
                f"a session named {name!r} is already resident in the "
                "pool"
            )
        with self._lock:
            self._sessions_started += 1
            sid = name or f"r{self._sessions_started:05d}"
        session = RolloutSession(
            sid,
            sample,
            steps,
            snapshot_every=sc["session_snapshot_every"],
            step_deadline_ms=ms or None,
            rollout_deadline=(
                self._clock() + rollout_deadline_ms / 1e3
                if rollout_deadline_ms
                else None
            ),
            on_step=on_step,
            tenant=tenant,
        )
        session.named = name is not None
        session.migrate_cb = self._session_failed
        # The cluster's sampling decision rides the session object:
        # every step this host runs (including after a local migration)
        # adopts the same trace id, so resumed steps join the ORIGINAL
        # trace instead of starting fresh chains.
        session.trace_ctx = trace_ctx
        self._place_session(session, sample)
        return session.future

    def resume_rollout(
        self,
        name: str,
        *,
        deadline_ms: float | None = None,
        rollout_deadline_ms: float | None = None,
        on_step=None,
        trace_ctx=None,
    ) -> RolloutFuture:
        """Client-visible resume across restarts: load the named
        session's persisted final carry snapshot (written by the
        previous deployment's drain), rebuild it at its last
        snapshotted step, and place it like a fresh rollout — the
        remaining steps run on this pool, the restored prefix is in the
        result but not re-streamed. Raises ``KeyError`` when nothing is
        persisted under ``name``; a session already complete at its
        snapshot resolves immediately."""
        if self._session_store is None:
            raise RuntimeError("no session store configured")
        if any(r.server.has_session(name) for r in self._pool()):
            # A retry racing a live resume would run the trajectory
            # twice under one sid (same guard as submit_rollout).
            raise ValueError(
                f"a session named {name!r} is already resident in the "
                "pool"
            )
        state = self._session_store.load(name)
        if state is None:
            raise KeyError(f"no persisted session {name!r}")
        sc = self._server_kwargs
        ms = (
            deadline_ms
            if deadline_ms is not None
            else sc["default_deadline_ms"]
        )
        session = RolloutSession.from_state(
            state,
            snapshot_every=sc["session_snapshot_every"],
            step_deadline_ms=ms or None,
            rollout_deadline=(
                self._clock() + rollout_deadline_ms / 1e3
                if rollout_deadline_ms
                else None
            ),
            on_step=on_step,
        )
        if session.finished:
            session.resolve(True, "ok")
            return session.future
        with self._lock:
            self._sessions_started += 1
        session.migrate_cb = self._session_failed
        # A cross-host re-migration arrives here: the propagated ctx
        # re-attaches the resumed steps to the session's original
        # cluster trace (ISSUE 20's continuity requirement).
        session.trace_ctx = trace_ctx
        self._place_session(session, session.sample)
        return session.future

    def _place_session(self, session: RolloutSession, sample) -> None:
        """First-step placement shared by submit_rollout and
        resume_rollout: health + affinity pick the owner, one ``route``
        event tagged with the session id, residence taken there."""
        key, label = self._bucket_of(sample)
        replica, reason = self._place(key)
        with self._lock:
            self._submitted += 1
            rid = replica.replica_id
            self._routed[rid] = self._routed.get(rid, 0) + 1
            if reason == "spill":
                self._spills += 1
        self._note_route(reason)
        self._event(
            events.ROUTE,
            replica=rid,
            bucket=label,
            policy=self.route_policy,
            reason=reason,
            depth=replica.server.depth(),
            dtype=self._dtype,
            session=session.sid,
        )
        replica.server.submit_rollout(session=session)

    def _session_failed(
        self, session: RolloutSession, reason: str, detail: str,
        from_replica: int | None,
    ) -> None:
        """Migration callback, invoked by the failed owner's server
        (on its worker/drain thread) when a session step dies on a
        backend signal. Re-place the session from its snapshot on a
        sibling — or, with migration off / budget spent / no sibling
        left, resolve the future with the failure (a LOST session,
        counted loudly)."""
        # Assess the failed owner FIRST: a mid-rollout death/trip must
        # land its replica_health edge now, not at the next unrelated
        # placement — the event stream's story of the failure starts
        # with the owner going unhealthy.
        if from_replica is not None:
            for r in self._pool():
                if r.replica_id == from_replica:
                    self._assess(r, self._clock())
        give_up = (
            not self.session_migration
            or self._drained.is_set()
            or session.migrations >= self.max_session_migrations
        )
        target = None
        if not give_up:
            now = self._clock()
            replicas = [
                r for r in self._pool() if r.replica_id != from_replica
            ]
            healthy = [
                r for r in replicas if self._assess(r, now).healthy
            ]
            # Fallback candidates must at least have a LIVE worker: a
            # dead sibling would swallow the re-placed step into a
            # queue nobody drains and the session future would hang —
            # resolving as lost is the honest answer when the pool is
            # out of alive replicas. A retiring sibling is a LAST
            # resort (its drain still resolves honestly) behind any
            # non-retiring live worker.
            alive = [
                r
                for r in replicas
                if r.server.worker_alive() and not r.retiring
            ]
            alive = alive or [
                r for r in replicas if r.server.worker_alive()
            ]
            pool = healthy or alive
            if pool:
                with self._lock:
                    target = min(pool, key=self._load)
        if target is None:
            if session.resolve(False, reason, detail=detail):
                with self._lock:
                    self._sessions_lost += 1
                if self._metrics is not None:
                    self._metrics.counter(
                        "rollout_sessions_lost_total"
                    ).inc()
            return
        at_step = session.cursor
        replay_from = session.restore_from_snapshot()
        with self._lock:
            self._sessions_migrated += 1
        if self._metrics is not None:
            self._metrics.counter("router_migrations_total").inc()
        self._event(
            events.SESSION_MIGRATE,
            session=session.sid,
            from_replica=from_replica,
            to_replica=target.replica_id,
            at_step=at_step,
            replay_from=replay_from,
            reason=reason,
        )
        target.server.submit_rollout(session=session)

    # -- rolling hot-reload ------------------------------------------------

    def reload(self, *, deadline_ms: float = 0.0) -> int:
        """Rolling hot-reload across the pool: one replica at a time is
        marked warming (drained for NEW traffic; its old weights keep
        serving what it already holds), reloads on THIS caller's
        thread, and rejoins before the next one starts. A replica whose
        restore fails keeps its old weights and the rollout continues —
        the pool never loses more than one replica's worth of capacity,
        and never sheds a request because of the reload. Returns the
        number of replicas that reloaded ok.

        Each replica restores from the source INDEPENDENTLY (N reads
        per rollout, not one shared read): deliberate — a replica's
        restore failure/fallback stays its own (the chaos contract),
        and a checkpoint published mid-rollout reaches the replicas
        still to come instead of pinning the whole rollout to a
        pre-rollout snapshot. The extra reads cost restore I/O, not
        serving capacity (only the warming replica is drained)."""
        if self.reload_fn is None:
            raise RuntimeError("no reload source configured")
        with self._reload_lock:
            with self._lock:
                self._rollouts += 1
                rollout = self._rollouts
            ok_n = 0
            rollout_pool = self._pool()
            for step, r in enumerate(rollout_pool, 1):
                r.set_warming(True)
                self._assess(r, self._clock())  # emit the warming edge
                try:
                    # _reload_lock exists to serialize rollouts; holding
                    # it across each replica's reload IS the rolling-
                    # reload contract (one replica warming, the rest
                    # serving). Request traffic never takes this lock.
                    #: allowed_blocking — rolling reload serialized by design
                    ok = r.server.reload(deadline_ms=deadline_ms)
                finally:
                    r.set_warming(False)
                self._assess(r, self._clock())
                ok_n += bool(ok)
                self._event(
                    events.ROLLING_RELOAD,
                    replica=r.replica_id,
                    ok=ok,
                    step=step,
                    n_replicas=len(rollout_pool),
                    rollout=rollout,
                )
            return ok_n

    # -- drain / rollup ----------------------------------------------------

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Drain every replica, then emit ONE pool-level
        ``serve_summary`` with the per-replica rollup and the routing
        ledger. Idempotent (the event fires once)."""
        # Drain every replica CONCURRENTLY under one shared budget:
        # sequential drains would either multiply the SIGTERM grace
        # window by N or starve healthy siblings of their flush time
        # behind one wedged replica (drain(0) would emit spurious
        # drain_timeouts and strand their queued Futures). Replica
        # drains are independent — each touches only its own server.
        per: dict[int, dict] = {}
        pool = self._pool()

        def _drain_one(r):
            per[r.replica_id] = r.server.drain(timeout_s)

        threads = [
            threading.Thread(target=_drain_one, args=(r,), daemon=True)
            for r in pool
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # AFTER the drains: a drain flushes queued requests, whose
        # latencies must be in the pool percentiles too. The pool view
        # is the LOSSLESS merge of the per-replica log-bucketed
        # histograms (obs/metrics.py) — bucket counts add exactly, so
        # the pool p50/p99 carry the same estimate-error bound as each
        # replica's own (per-replica percentiles can never be averaged
        # into pool ones; merged populations can). Replicas retired by
        # remove_replica BEFORE this drain merge in from the retained
        # ledger — a membership change must not drop served history.
        with self._lock:
            # Ledger AND its histograms in one atomic snapshot
            # (remove_replica updates them under this same lock): a
            # half-visible removal would either drop the leaving
            # replica's latencies or count them twice.
            retired = dict(self._retired)
            retired_hist = self._retired_hist.copy()
            retired_step_hist = self._retired_step_hist.copy()
            retired_tenant_hists = {
                t: h.copy() for t, h in self._retired_tenant_hists.items()
            }
        retired_ids = set(retired)
        for rid, ret in retired.items():
            per[rid] = ret["summary"]
        # A remove_replica racing this drain can finish AFTER the pool
        # snapshot above was taken: the leaving replica is then in BOTH
        # the snapshot and the retired ledger — merge it from the
        # ledger only, or its histogram counts twice.
        live = [r for r in pool if r.replica_id not in retired_ids]
        pool_hist = LogHistogram()
        pool_hist.merge(retired_hist)
        for r in live:
            pool_hist.merge(r.server.latency_histogram())
        shed: dict[str, int] = {}
        for s in per.values():
            for reason, n in s["shed"].items():
                shed[reason] = shed.get(reason, 0) + n
        # Pool-level packing efficiency: merge the per-replica
        # pad-waste rollups by bucket (sum the token counters,
        # recompute the fractions) so the packed A/B reads ONE number
        # off the pool summary, replicated or not.
        pad_waste: dict[str, dict] = {}
        for s in per.values():
            for key, st in (s.get("pad_waste_by_bucket") or {}).items():
                agg = pad_waste.setdefault(
                    key,
                    {"dispatches": 0, "real_tokens": 0,
                     "capacity_tokens": 0},
                )
                for k in agg:
                    agg[k] += st[k]
        for st in pad_waste.values():
            cap = st["capacity_tokens"]
            st["fill_frac"] = st["real_tokens"] / cap if cap else None
            st["pad_waste_frac"] = (
                1.0 - st["real_tokens"] / cap if cap else None
            )
        # Pool-level tenant rollup: counts sum from the per-replica
        # summaries (retired ones included — their final summaries are
        # in `per`); percentiles merge the per-tenant histograms of the
        # LIVE replicas plus the retired-tenant ledger, the same
        # lossless log-bucket merge as the request latencies. Empty
        # (and therefore absent from the pool summary) unless some
        # request actually carried a tenant — the single-tenant path
        # stays byte-for-byte.
        tenants_roll: dict[str, dict] = {}
        for s in per.values():
            for t, st in (s.get("tenants") or {}).items():
                agg = tenants_roll.setdefault(
                    t, {"requests": 0, "completed": 0, "shed": {}}
                )
                agg["requests"] += st["requests"]
                agg["completed"] += st["completed"]
                for reason, n in st["shed"].items():
                    agg["shed"][reason] = agg["shed"].get(reason, 0) + n
        tenant_hists: dict[str, LogHistogram] = {
            t: h.copy() for t, h in retired_tenant_hists.items()
        }
        for r in live:
            for t, h in r.server.tenant_rollup()["hists"].items():
                tenant_hists.setdefault(t, LogHistogram()).merge(h)
        warm_by_id = {r.replica_id: r.warm_stats for r in pool}
        warm_by_id.update(
            {rid: ret["warm_stats"] for rid, ret in retired.items()}
        )
        # Pool-level rollout-session rollup: outcome counters are
        # router-truth (started/migrated/lost) plus the summed
        # per-replica terminals; the per-step latency percentiles merge
        # the per-replica step histograms, exactly like the request
        # ones.
        step_hist = LogHistogram()
        step_hist.merge(retired_step_hist)
        for r in live:
            step_hist.merge(r.server.step_latency_histogram())
        with self._lock:
            routed = dict(self._routed)
            spills = self._spills
            rollouts = self._rollouts
            submitted = self._submitted
            sessions_started = self._sessions_started
            sessions_migrated = self._sessions_migrated
            sessions_lost = self._sessions_lost
        summary = {
            "dtype": self._dtype,
            "requests": sum(s["requests"] for s in per.values()),
            "admitted": sum(s["admitted"] for s in per.values()),
            "completed": sum(s["completed"] for s in per.values()),
            "shed": shed,
            "dispatches": sum(s["dispatches"] for s in per.values()),
            "reloads": sum(s["reloads"] for s in per.values()),
            "breaker_trips": sum(s["breaker_trips"] for s in per.values()),
            # Pool-wide compiled-program count: affinity keeps this near
            # the single-server bound instead of replicas x buckets.
            "compiled_shapes": sum(
                s["compiled_shapes"] for s in per.values()
            ),
            "latency_p50_ms": pool_hist.percentile(0.50),
            "latency_p99_ms": pool_hist.percentile(0.99),
            **(
                {"pad_waste_by_bucket": dict(sorted(pad_waste.items()))}
                if pad_waste
                else {}
            ),
            "per_replica": {
                str(rid): {
                    "requests": s["requests"],
                    "completed": s["completed"],
                    "shed": s["shed"],
                    "dispatches": s["dispatches"],
                    "reloads": s["reloads"],
                    "breaker_trips": s["breaker_trips"],
                    "compiled_shapes": s["compiled_shapes"],
                    "latency_p50_ms": s["latency_p50_ms"],
                    "latency_p99_ms": s["latency_p99_ms"],
                    "routed": routed.get(rid, 0),
                    # Warm provenance (serve/aot.py): how this replica
                    # became serve-ready — cold compiles vs snapshot
                    # hydration, with the cache hit/miss breakdown.
                    "warmup_cache": warm_by_id.get(rid),
                    # Removed before this drain (scale-in / heal); its
                    # numbers are final as of its retirement.
                    **({"retired": True} if rid in retired_ids else {}),
                }
                for rid, s in sorted(per.items())
            },
            "routing": {
                "policy": self.route_policy,
                "replicas": len(pool),
                "removed": len(retired_ids),
                # Router-level submit count: equals the sum of the
                # per-replica `requests` unless callers also submitted
                # to replica servers directly.
                "submitted": submitted,
                "spills": spills,
                "rollouts": rollouts,
            },
        }
        if tenants_roll:
            summary["tenants"] = {
                t: {
                    **agg,
                    "latency_p50_ms": (
                        tenant_hists[t].percentile(0.50)
                        if t in tenant_hists
                        else None
                    ),
                    "latency_p99_ms": (
                        tenant_hists[t].percentile(0.99)
                        if t in tenant_hists
                        else None
                    ),
                }
                for t, agg in sorted(tenants_roll.items())
            }
        if self._tracer is not None:
            # Pool trace coverage: the replicas share ONE tracer, so
            # its counters already ARE the pool view (ISSUE 20 — same
            # honesty denominator as the per-replica summaries).
            summary["trace"] = self._tracer.coverage()
        if sessions_started:
            summary["sessions"] = {
                "started": sessions_started,
                "completed": sum(
                    (s.get("sessions") or {}).get("completed", 0)
                    for s in per.values()
                ),
                "drained": sum(
                    (s.get("sessions") or {}).get("drained", 0)
                    for s in per.values()
                ),
                "shed": sum(
                    (s.get("sessions") or {}).get("shed", 0)
                    for s in per.values()
                ),
                "migrated": sessions_migrated,
                "lost": sessions_lost,
                "steps": step_hist.count,
                "step_latency_p50_ms": step_hist.percentile(0.50),
                "step_latency_p99_ms": step_hist.percentile(0.99),
            }
        if self._catalog is not None:
            # Pool capacity model: the catalog's cost entries joined
            # with every replica's attributed traffic (retired replicas
            # included — traffic rows are never deleted). Emits the
            # capacity_snapshot event exactly once across repeated
            # drains (emit_snapshot is idempotent).
            model = self._catalog.emit_snapshot()
            summary["capacity_model"] = (
                model if model is not None else self._catalog.capacity_model()
            )
        if not self._drained.is_set():
            self._drained.set()
            self._event(events.SERVE_SUMMARY, **summary)
            if self.sink is not None:
                self.sink.flush()
        return summary

    def _event(self, event: str, **fields) -> None:
        if self.sink is not None:
            self.sink.log(event=event, **fields)
