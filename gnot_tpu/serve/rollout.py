"""Stateful autoregressive rollout sessions for the serving tier.

One-shot serving answers ``f(sample) -> field``; the NS2d trajectory
workload (PAPER.md's time-dependent family — ``data/datasets.py::
synth_ns2d`` parameterizes ``theta`` as time) is ``K`` CHAINED
dispatches per request: step ``k+1``'s input is derived from step
``k``'s prediction, and the carry state stays resident on the serving
replica between steps. This module holds the pieces both tiers share:

* ``advance_sample`` — THE canonical carry: ``theta`` advances by
  ``dt`` and the input function's value channels are refreshed from the
  predicted field, so every step genuinely depends on the previous
  step's output (a rollout is a trajectory, not K independent queries).
  Shapes never change across steps, so a session stays in ONE bucket —
  the whole rollout rides the bucket's one compiled program, and
  concurrent sessions at different step indices batch/pack together
  through the ordinary ``Batcher``/``PackPlan`` machinery.
* ``offline_rollout`` — the engine-only K-step loop (no serve stack):
  the parity reference the chaos A/B (``tools/rollout_ab.py``) holds
  served rollouts to, <= 1e-5 per step.
* ``RolloutSession`` — the session object: id, step cursor,
  replica-resident carry, per-step/whole-rollout deadline budgets, and
  the rolling host-side snapshot (the ``resilience/supervisor.py``
  last-good pattern applied to serving): every ``snapshot_every``
  completed steps the carry is copied out, and when the owning replica
  dies/open-breakers/wedges mid-rollout the router re-places the
  session on a sibling FROM the snapshot and replays forward —
  at-least-once step semantics, zero lost sessions. Replayed steps are
  deterministic (same carry -> same outputs), and re-delivery to the
  client is suppressed by a high-water mark.
* ``RolloutFuture`` — the submitted future, extended with streaming
  partial results: ``iter_steps()`` yields ``(step, output)`` as each
  step lands (an ``on_step`` callback is the push-style twin), and the
  future itself always resolves to a ``RolloutResult`` — completed,
  partial-with-``drained_at_step``-marker, or shed-with-reason; never
  a hang, on any path (the one-shot tier's contract, kept stateful).

Thread-safety: a session is mutated by the owning replica's worker
thread and read/re-placed by router threads (migration, drain) — all
mutable state is under the session's own lock (graftlint GL004
enforces the annotations).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue
import re
import threading
from concurrent.futures import Future
from typing import Callable, Iterator, Sequence

import numpy as np

from gnot_tpu.data.batch import MeshSample

#: Default trajectory time increment per rollout step (theta advance).
ROLLOUT_DT = 0.05

#: Terminal reasons a rollout future can resolve with, beyond the
#: one-shot REASONS a failing step passes through: "ok" (all K steps),
#: "drained" (partial, with the ``drained_at_step`` marker).
ROLLOUT_REASONS = ("ok", "drained")


def advance_sample(
    sample: MeshSample, output: np.ndarray, *, dt: float = ROLLOUT_DT
) -> MeshSample:
    """The canonical autoregressive carry: the next step's request,
    derived from this step's prediction.

    ``theta`` (time, per the NS2d parameterization) advances by ``dt``;
    the input function's trailing value channels are refreshed from the
    predicted field at the function mesh's points (first ``m`` rows —
    the synthetic generators emit function meshes as node-mesh
    prefixes). Coordinates and every shape are preserved EXACTLY, so
    the whole rollout stays in one bucket and one compiled program.
    All arrays are fresh copies — the previous step's sample (which may
    be a held snapshot) is never written in place."""
    out = np.asarray(output, dtype=np.float32)
    funcs = []
    for f in sample.funcs:
        f_new = np.array(f, dtype=np.float32)
        k = min(f_new.shape[1], out.shape[1])
        t = min(f_new.shape[0], out.shape[0])
        f_new[:t, f_new.shape[1] - k :] = out[:t, :k]
        funcs.append(f_new)
    theta = (np.asarray(sample.theta, dtype=np.float32) + np.float32(dt)).astype(
        np.float32
    )
    return MeshSample(
        coords=np.array(sample.coords, dtype=np.float32),
        y=np.array(sample.y, dtype=np.float32),
        theta=theta,
        funcs=tuple(funcs),
    )


def offline_rollout(
    engine,
    sample: MeshSample,
    steps: int,
    *,
    rows: int | None = None,
    advance: Callable = advance_sample,
    dt: float = ROLLOUT_DT,
) -> list[np.ndarray]:
    """The engine-only K-step reference loop (no serve stack): the
    trajectory a served rollout must match <= 1e-5 per step — including
    sessions that migrated mid-rollout (replay from the snapshot carry
    is exact)."""
    if steps < 1:
        raise ValueError(f"rollout needs steps >= 1, got {steps}")
    outs: list[np.ndarray] = []
    cur = sample
    for _ in range(steps):
        pn, pf = engine.bucket_key(cur)
        out = engine.infer([cur], pad_nodes=pn, pad_funcs=pf, rows=rows)[0]
        outs.append(out)
        cur = advance(cur, out, dt=dt)
    return outs


@dataclasses.dataclass
class RolloutResult:
    """What a rollout future resolves to — ALWAYS, on every path.

    ``ok`` means all ``steps`` completed; otherwise ``reason`` names the
    terminal condition ("drained" for a graceful drain mid-rollout —
    then ``drained_at_step`` marks where it stopped — or the failing
    step's one-shot reason: "shed_deadline", "shed_queue_full",
    "rejected_breaker_open", "error_nan_output", "error_dispatch",
    "error_replica_dead", "error_stale_session", ...). ``outputs``
    holds the per-step predictions actually committed (all ``steps`` of
    them when ok, the completed prefix otherwise)."""

    ok: bool
    reason: str
    session: str
    steps: int
    steps_completed: int
    outputs: list = dataclasses.field(default_factory=list)
    drained_at_step: int | None = None
    migrations: int = 0
    detail: str = ""


class RolloutFuture(Future):
    """A ``concurrent.futures.Future`` resolving to ``RolloutResult``,
    plus streaming partial results: each committed step is published to
    ``iter_steps()`` as it lands. The stream closes when the future
    resolves, so iteration always terminates."""

    def __init__(self):
        super().__init__()
        self._step_queue: queue.Queue = queue.Queue()

    def _publish(self, step: int, output: np.ndarray) -> None:
        self._step_queue.put((step, output))

    def _close_stream(self) -> None:
        self._step_queue.put(None)

    def iter_steps(self, timeout: float | None = None) -> Iterator[tuple]:
        """Yield ``(step, output)`` pairs (1-indexed, in order) as the
        rollout progresses; returns when the session reaches a terminal
        state. Replayed steps after a migration are NOT re-delivered
        (high-water deduplication in the session)."""
        while True:
            item = self._step_queue.get(timeout=timeout)
            if item is None:
                return
            yield item


class RolloutSession:
    """One in-flight autoregressive rollout: identity, cursor, the
    replica-resident carry, the rolling host-side snapshot, and the
    client-facing future/stream. Created by ``submit_rollout`` (router
    or standalone server); mutated by the owning replica's worker
    thread; read and re-placed by router threads on migration/drain."""

    def __init__(
        self,
        sid: str,
        sample: MeshSample,
        steps: int,
        *,
        snapshot_every: int = 1,
        step_deadline_ms: float | None = None,
        rollout_deadline: float | None = None,
        on_step: Callable | None = None,
        advance: Callable = advance_sample,
        dt: float = ROLLOUT_DT,
        tenant: str | None = None,
    ):
        if steps < 1:
            raise ValueError(f"rollout needs steps >= 1, got {steps}")
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}"
            )
        self.sid = sid
        self.steps = steps
        self.snapshot_every = snapshot_every
        self.step_deadline_ms = step_deadline_ms
        #: Absolute whole-rollout expiry on the serving clock (None =
        #: no budget); every step's deadline is clamped to it.
        self.rollout_deadline = rollout_deadline
        self.on_step = on_step
        self.advance = advance
        self.dt = dt
        #: The submitter's tenant identity (docs/serving.md
        #: "Multi-tenant isolation"), inherited by every step request
        #: the session enqueues — and carried through snapshot_state/
        #: from_state, so a migrated or resumed session keeps billing
        #: the SAME tenant's quota/WFQ share. None = untagged.
        self.tenant = tenant
        self.future = RolloutFuture()
        #: True for client-NAMED sessions (``submit_rollout(name=)``):
        #: only those persist to a ``SessionStore`` on drain — an
        #: auto-generated sid restarts from 1 in every process, so
        #: persisting it would let run 2's "r00001" overwrite (or
        #: delete) run 1's resumable snapshot.
        self.named = False
        #: Migration handler installed by the router
        #: (``fn(session, reason, detail, from_replica)``); None on a
        #: standalone server — step failures then resolve the future.
        self.migrate_cb: Callable | None = None
        #: Propagated cluster trace context (``obs/dtrace.TraceContext``)
        #: installed by the router on federated placements: every step
        #: request this session enqueues adopts the SAME cluster-made
        #: sampling decision, so steps resumed after a migration stay
        #: spans of the original trace. None = locally-placed session,
        #: whose steps run untraced (local spans belong to requests the
        #: local tracer sampled itself).
        self.trace_ctx = None
        self._lock = threading.Lock()
        self._sample = sample  #: guarded_by _lock
        self._cursor = 0  #: guarded_by _lock
        self._outputs: list = []  #: guarded_by _lock
        # The rolling last-good snapshot (supervisor pattern): taken at
        # creation (step 0 is always restorable) and every
        # snapshot_every completed steps thereafter.
        self._snapshot = {
            "cursor": 0, "sample": sample, "outputs": [],
        }  #: guarded_by _lock
        self._streamed = 0  #: guarded_by _lock
        self._migrations = 0  #: guarded_by _lock
        self._resolved = False  #: guarded_by _lock

    # -- step lifecycle (owning replica's worker thread) -------------------

    @property
    def sample(self) -> MeshSample:
        """The current carry — the next step's request payload."""
        with self._lock:
            return self._sample

    @property
    def cursor(self) -> int:
        """Completed steps (the next step to run is ``cursor + 1``)."""
        with self._lock:
            return self._cursor

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._cursor >= self.steps

    @property
    def migrations(self) -> int:
        with self._lock:
            return self._migrations

    def record_step(self, output: np.ndarray) -> int:
        """Commit one completed step: append the output, advance the
        carry. Returns the 1-indexed step just committed."""
        with self._lock:
            self._outputs.append(output)
            self._cursor += 1
            if self._cursor < self.steps:
                self._sample = self.advance(self._sample, output, dt=self.dt)
            return self._cursor

    def publish_step(self, step: int, output: np.ndarray) -> None:
        """Stream one committed step to the client (callback +
        iterator), exactly once per step index: replays after a
        migration re-commit steps but never re-deliver them."""
        with self._lock:
            if step <= self._streamed:
                return
            self._streamed = step
        if self.on_step is not None:
            self.on_step(self.sid, step, output)
        self.future._publish(step, output)

    def snapshot_due(self) -> bool:
        with self._lock:
            return (
                self._cursor < self.steps
                and self._cursor - self._snapshot["cursor"]
                >= self.snapshot_every
            )

    def take_snapshot(self) -> int:
        """Copy the carry (and the committed prefix) host-side — the
        state a migration replays from. Returns the snapshot cursor."""
        with self._lock:
            self._snapshot = {
                "cursor": self._cursor,
                "sample": self._sample,
                "outputs": list(self._outputs),
            }
            return self._cursor

    def snapshot_state(self) -> dict:
        """JSON/array-ready copy of the last SNAPSHOT (not the live
        cursor) — what the ``SessionStore`` persists: a restart resumes
        from exactly the state a migration would have replayed from."""
        with self._lock:
            snap = self._snapshot
            return {
                "sid": self.sid,
                "steps": self.steps,
                "cursor": snap["cursor"],
                "sample": snap["sample"],
                "outputs": list(snap["outputs"]),
                "dt": self.dt,
                "tenant": self.tenant,
            }

    @classmethod
    def from_state(
        cls,
        state: dict,
        *,
        snapshot_every: int = 1,
        step_deadline_ms: float | None = None,
        rollout_deadline: float | None = None,
        on_step: Callable | None = None,
        advance: Callable = advance_sample,
    ) -> "RolloutSession":
        """Rebuild a session from a persisted ``snapshot_state`` — the
        client-visible resume across server restarts. The restored
        prefix counts as already streamed (``publish_step`` will not
        re-deliver it); the next step to run is ``cursor + 1``."""
        s = cls(
            state["sid"],
            state["sample"],
            state["steps"],
            snapshot_every=snapshot_every,
            step_deadline_ms=step_deadline_ms,
            rollout_deadline=rollout_deadline,
            on_step=on_step,
            advance=advance,
            dt=state.get("dt", ROLLOUT_DT),
            tenant=state.get("tenant"),
        )
        s.named = True  # only named sessions are ever persisted
        with s._lock:
            s._cursor = int(state["cursor"])
            s._outputs = list(state["outputs"])
            s._snapshot = {
                "cursor": s._cursor,
                "sample": state["sample"],
                "outputs": list(state["outputs"]),
            }
            s._streamed = s._cursor
        return s

    # -- migration (router threads) ----------------------------------------

    def restore_from_snapshot(self) -> int:
        """Roll the session back to its last snapshot (cursor, carry,
        committed prefix) and count one migration. Returns the step the
        replay resumes from (the snapshot cursor). At-least-once: steps
        past the snapshot re-execute on the new owner; ``publish_step``
        suppresses their re-delivery."""
        with self._lock:
            self._cursor = self._snapshot["cursor"]
            self._sample = self._snapshot["sample"]
            self._outputs = list(self._snapshot["outputs"])
            self._migrations += 1
            return self._cursor

    # -- resolution (exactly once, any thread) -----------------------------

    def resolve(
        self,
        ok: bool,
        reason: str,
        *,
        drained_at_step: int | None = None,
        detail: str = "",
    ) -> bool:
        """Resolve the client future with a terminal ``RolloutResult``
        (idempotent — the first caller wins; late duplicates from a
        drain racing the worker are no-ops). Returns True when THIS
        call resolved it."""
        with self._lock:
            if self._resolved:
                return False
            self._resolved = True
            result = RolloutResult(
                ok=ok,
                reason=reason,
                session=self.sid,
                steps=self.steps,
                steps_completed=self._cursor,
                outputs=list(self._outputs),
                drained_at_step=drained_at_step,
                migrations=self._migrations,
                detail=detail,
            )
        self.future.set_result(result)
        self.future._close_stream()
        return True


class SessionStore:
    """On-disk persistence for rollout-session carry snapshots — the
    PR 13 stretch made client-visible: a drain (SIGTERM, restart,
    scale-in of the whole deployment) persists every open session's
    FINAL snapshot here, and a restarted server/router resumes a named
    session from its last snapshotted step (``resume_rollout``).

    One ``.npz`` per session: the carry sample's arrays, the committed
    output prefix, and a JSON meta record (sid, steps, cursor, dt).
    Writes are atomic (tmp + rename), so a crash mid-persist leaves the
    previous snapshot intact rather than a torn file. Thread-safe at
    the filesystem level (one writer per session — the draining owner).
    """

    def __init__(self, directory: str):
        if not directory:
            raise ValueError("SessionStore needs a directory")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        # Sanitized stem + a short digest of the RAW name: two distinct
        # sids that sanitize identically ("run:1" vs "run_1") must not
        # share a file — a save would silently overwrite the other
        # client's resumable snapshot.
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", name)
        digest = hashlib.sha1(name.encode()).hexdigest()[:8]
        return os.path.join(
            self.directory, f"{safe}-{digest}.session.npz"
        )

    def names(self) -> list[str]:
        """Persisted session names — the true sids from each file's
        meta record (filenames are sanitized + digest-suffixed, so the
        meta is the authority; unreadable files are skipped)."""
        out = []
        for fn in sorted(os.listdir(self.directory)):
            if not fn.endswith(".session.npz"):
                continue
            try:
                with np.load(
                    os.path.join(self.directory, fn), allow_pickle=False
                ) as z:
                    out.append(json.loads(str(z["meta"]))["sid"])
            except (OSError, KeyError, ValueError):
                continue
        return out

    def save(self, session: "RolloutSession") -> str:
        """Persist the session's last snapshot. Returns the path."""
        state = session.snapshot_state()
        sample: MeshSample = state["sample"]
        arrays = {
            "coords": np.asarray(sample.coords),
            "y": np.asarray(sample.y),
            "theta": np.asarray(sample.theta),
        }
        for i, f in enumerate(sample.funcs):
            arrays[f"func_{i}"] = np.asarray(f)
        for i, o in enumerate(state["outputs"]):
            arrays[f"out_{i}"] = np.asarray(o)
        meta = {
            "sid": state["sid"],
            "steps": state["steps"],
            "cursor": state["cursor"],
            "dt": state["dt"],
            "tenant": state.get("tenant"),
            "n_funcs": len(sample.funcs),
            "n_outputs": len(state["outputs"]),
        }
        path = self._path(state["sid"])
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, meta=json.dumps(meta), **arrays)
        os.replace(tmp, path)
        return path

    def load(self, name: str) -> dict | None:
        """The persisted ``snapshot_state`` for ``name`` (None when no
        snapshot exists) — feed to ``RolloutSession.from_state``."""
        path = self._path(name)
        if not os.path.exists(path):
            return None
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            sample = MeshSample(
                coords=z["coords"],
                y=z["y"],
                theta=z["theta"],
                funcs=tuple(
                    z[f"func_{i}"] for i in range(meta["n_funcs"])
                ),
            )
            outputs = [z[f"out_{i}"] for i in range(meta["n_outputs"])]
        return {
            "sid": meta["sid"],
            "steps": meta["steps"],
            "cursor": meta["cursor"],
            "dt": meta["dt"],
            "tenant": meta.get("tenant"),
            "sample": sample,
            "outputs": outputs,
        }

    def delete(self, name: str) -> None:
        """Drop a persisted snapshot (a resumed-and-completed session's
        snapshot is stale — the resume path cleans up after itself)."""
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            pass


def parity_check(
    served: Sequence[np.ndarray],
    reference: Sequence[np.ndarray],
    *,
    atol: float = 1e-5,
) -> float:
    """Max absolute per-step deviation of a served rollout from the
    offline reference (raises on step-count mismatch — a truncated
    trajectory is not 'close')."""
    if len(served) != len(reference):
        raise ValueError(
            f"served rollout has {len(served)} steps, reference "
            f"{len(reference)}"
        )
    worst = 0.0
    for got, want in zip(served, reference):
        worst = max(worst, float(np.max(np.abs(got - want))))
    return worst
