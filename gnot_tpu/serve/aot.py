"""Ahead-of-time compile pipeline + warm-replica snapshots.

Serving correctness already *depends* on warmup — a cold XLA compile
landing under a tight deadline sheds everything queued behind it — and
the replica tier multiplies the cost by N at every scale-out and
rolling reload. This module makes cold start a deploy-time artifact
instead of a first-request tax:

* **Enumerate** (``enumerate_programs``): the exact program family a
  deployment will serve — one program per bucket the representative
  traffic hits (at the serving row count), plus the one ``PackPlan``
  program under packed mode. The family is O(log L_max) by the
  bucketing contract, so enumerating it is cheap and complete.
* **Compile** (``aot_compile``): ``jit(...).lower(...).compile()`` each
  program at deploy time — lowered against the engine's REAL placed
  batch signature (mesh-slice sharding included), so the persistent
  compile cache entry it writes is the one a live dispatch would look
  up. Runs under ``utils.cache.warm_cache`` with the cache admission
  threshold at 0 so every serving program persists, and records
  per-program compile seconds + cache hit/miss.
* **Snapshot**: each compiled executable is additionally serialized
  (``jax.experimental.serialize_executable``) into ``snapshot_dir`` —
  the warm-replica snapshot. Hydrating one (``hydrate``) deserializes
  the executable and installs it in the engine's AOT table
  (``InferenceEngine.install_program``): a prewarmed replica's first
  request runs the executable DIRECTLY — no trace, no compile, no
  cache lookup. Snapshots are device-assignment-bound (the XLA
  executable is compiled for its replica's device slice), which is why
  the manifest is keyed per replica.
* **Manifest** (``save_manifest``/``load_manifest``): the deploy
  artifact — program keys, compile seconds, snapshot bytes, cache-dir
  occupancy — consumed by ``EngineReplica.prewarm_from`` /
  ``ReplicaRouter.prewarm_from`` and recorded into ``run.json``.

Snapshots use pickle (the upstream ``serialize_executable`` format):
they are local, same-machine deploy artifacts like the compile cache
itself — load them only from a directory you wrote.

CLI: ``tools/aot_prewarm.py`` drives this end to end; the cold-start
A/B lives in ``tools/coldstart_ab.py`` (docs/performance.md "Cold
start").
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import pickle
import time
from typing import Sequence

import numpy as np

from gnot_tpu.data.batch import (
    MeshSample,
    PackPlan,
    collate,
    pack_collate,
    pack_prefix,
)
from gnot_tpu.utils.cache import cache_dir_manifest, warm_cache

#: Manifest schema version (bump on incompatible changes; load_manifest
#: rejects unknown versions loudly instead of hydrating garbage).
#: v2: program identity is dtype-keyed — ``ProgramSpec.dtype``, the
#: ``@<tag>`` key suffix, and the manifest-level ``dtype`` a hydrating
#: engine must match wholesale. v1 manifests predate serving dtypes
#: and are refused (their f32 programs would silently hydrate into a
#: bf16 deployment at the same shapes).
MANIFEST_VERSION = 2


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One compiled serving program: a padded bucket (``kind="bucket"``,
    one program per ``(pad_nodes, pad_funcs)`` at ``rows`` dispatch
    rows) or THE packed program (``kind="packed"``, the ``PackPlan``'s
    fixed grid). ``dims`` carries the sample schema (coordinate /
    theta / function / target widths) so a dummy batch with the exact
    dispatch signature can be rebuilt in any process — the manifest
    round-trips without the original traffic."""

    key: str
    kind: str  # "bucket" | "packed"
    pad_nodes: int
    pad_funcs: int
    rows: int
    dims: dict
    plan: dict | None = None  # PackPlan fields when kind == "packed"
    # Serving compute dtype this program was lowered at
    # (models/precision.py). Part of program IDENTITY: the key carries
    # its tag, the dummy batch collates at it, and hydration refuses a
    # manifest whose dtype differs from the serving engine's — an f32
    # executable at a bf16 deployment's shapes is the wrong program,
    # not a warm one.
    dtype: str = "float32"

    def dummy_samples(self) -> list[MeshSample]:
        """Zero-filled sample(s) whose collated batch has this
        program's dispatch signature (values never matter — programs
        are shape-keyed)."""
        d = self.dims
        n = self.pad_nodes if self.kind == "bucket" else self.plan["chunk"]
        funcs = tuple(
            np.zeros((max(1, self.pad_funcs), d["func_dim"]), np.float32)
            for _ in range(d["n_funcs"])
        ) if d["n_funcs"] else ()
        return [
            MeshSample(
                coords=np.zeros((n, d["input_dim"]), np.float32),
                y=np.zeros((n, d["out_dim"]), np.float32),
                theta=np.zeros((d["theta_dim"],), np.float32),
                funcs=funcs,
            )
        ]

    def dummy_batch(self):
        """The collated (host-side) batch at this program's exact
        static shape AND dtype — what the engine lowers/dispatches
        (dispatch signatures are dtype-keyed, so the dummy must collate
        at the program's dtype or hydration would install keys no live
        dispatch ever matches)."""
        samples = self.dummy_samples()
        if self.kind == "packed":
            plan = PackPlan(**self.plan)
            placements = pack_prefix(
                [s.coords.shape[0] for s in samples], plan
            )
            return pack_collate(
                samples,
                placements,
                n_rows=plan.n_rows,
                row_len=plan.row_len,
                chunk=plan.chunk,
                n_slots=plan.n_slots,
                pad_funcs=plan.pad_funcs,
                dtype=self.dtype,
            )
        reqs = samples * self.rows
        return collate(
            reqs,
            bucket=False,
            pad_nodes=self.pad_nodes,
            pad_funcs=self.pad_funcs,
            dtype=self.dtype,
        )


def params_signature(params) -> str:
    """Structure fingerprint of a param tree (paths + shapes + dtypes,
    values excluded — snapshots take params as a runtime argument). A
    snapshot compiled for one model must not hydrate an engine serving
    another: the loaded executable would reject (or worse, misread)
    the foreign param tree at dispatch time, mid-traffic. Checked at
    ``hydrate``; a mismatch skips the snapshot and the engine stays on
    the ordinary jit path."""
    import hashlib

    import jax

    leaves = jax.tree_util.tree_leaves_with_path(params)
    desc = ";".join(
        f"{jax.tree_util.keystr(path)}:{np.shape(leaf)}:"
        f"{getattr(leaf, 'dtype', type(leaf).__name__)}"
        for path, leaf in sorted(leaves, key=lambda kv: str(kv[0]))
    )
    return hashlib.sha256(desc.encode()).hexdigest()[:16]


def sample_dims(sample: MeshSample) -> dict:
    """The schema widths of one representative sample (ProgramSpec.dims)."""
    return {
        "input_dim": int(sample.coords.shape[1]),
        "out_dim": int(sample.y.shape[1]),
        "theta_dim": int(np.atleast_1d(sample.theta).shape[0]),
        "n_funcs": len(sample.funcs),
        "func_dim": int(sample.funcs[0].shape[1]) if sample.funcs else 0,
    }


def enumerate_programs(
    engine,
    samples: Sequence[MeshSample],
    *,
    rows: int | None = None,
    pack_plan: PackPlan | None = None,
) -> list[ProgramSpec]:
    """The program family a deployment serving ``samples``-shaped
    traffic needs: one bucket program per distinct ``bucket_key`` in
    the representative set (the oversize-fallback path stays warm even
    under packed mode — mirroring ``EngineReplica.warm``), plus the one
    packed program when a plan is given."""
    if not samples:
        raise ValueError("enumerate_programs needs representative samples")
    from gnot_tpu.models.precision import DTYPE_TAGS

    rows = rows or engine.batch_size
    # Programs inherit the engine's serving dtype — the key carries the
    # tag, so an f32 and a bf16 deployment of the same traffic family
    # never share a program name (or a snapshot file).
    dtype = getattr(engine, "dtype", "float32")
    tag = DTYPE_TAGS[dtype]
    dims = sample_dims(samples[0])
    specs = []
    seen: set[tuple[int, int]] = set()
    for s in samples:
        key = engine.bucket_key(s)
        if key in seen:
            continue
        seen.add(key)
        pn, pf = key
        specs.append(
            ProgramSpec(
                key=f"bucket:{pn}x{pf}@{rows}@{tag}",
                kind="bucket",
                pad_nodes=pn,
                pad_funcs=pf,
                rows=rows,
                dims=dims,
                dtype=dtype,
            )
        )
    specs.sort(key=lambda sp: sp.key)
    if pack_plan is not None:
        specs.append(
            ProgramSpec(
                key=f"packed:{pack_plan.n_rows}x{pack_plan.row_len}@{tag}",
                kind="packed",
                pad_nodes=0,
                pad_funcs=pack_plan.pad_funcs,
                rows=pack_plan.n_rows,
                dims=dims,
                plan=dataclasses.asdict(pack_plan),
                dtype=dtype,
            )
        )
    return specs


def _snapshot_file(snapshot_dir: str, replica_id: int, key: str) -> str:
    safe = key.replace(":", "_").replace("@", "_")
    return os.path.join(snapshot_dir, f"r{replica_id}_{safe}.xsnap")


def aot_compile(
    engine,
    specs: Sequence[ProgramSpec],
    *,
    replica_id: int = 0,
    snapshot_dir: str | None = None,
) -> dict:
    """Compile every program in ``specs`` for ``engine`` ahead of time:
    ``lower()`` at the REAL placed dispatch signature, ``.compile()``
    into the persistent cache (admission threshold 0 — every serving
    program persists), and — with ``snapshot_dir`` — serialize each
    executable as a warm-replica snapshot. Returns the manifest block
    for this engine: per-program entries (key, compile seconds,
    snapshot file/bytes) plus the aggregated cache stats."""
    from jax.experimental import serialize_executable

    from gnot_tpu.obs.costs import extract_costs

    compiled: dict[str, object] = {}

    def thunk(spec):
        def run():
            placed = engine.place_batch(spec.dummy_batch())
            compiled[spec.key] = engine.lower_program(placed).compile()

        return run

    stats = warm_cache((spec.key, thunk(spec)) for spec in specs)
    by_key = {p["key"]: p["seconds"] for p in stats["programs"]}
    entries = []
    for spec in specs:
        entry = {
            **dataclasses.asdict(spec),
            "compile_s": by_key[spec.key],
            # XLA cost/memory analysis of the compiled executable
            # (obs/costs.py) — recorded AT COMPILE TIME so the program
            # catalog of a hydrating deployment has cost entries even
            # when the deserialized snapshot's own probes come back
            # thin. Fields the backend would not report are None with
            # an explicit `unavailable` list, never zero.
            "costs": extract_costs(compiled[spec.key]),
            "snapshot": None,
            "snapshot_bytes": None,
        }
        if snapshot_dir is not None:
            os.makedirs(snapshot_dir, exist_ok=True)
            path = _snapshot_file(snapshot_dir, replica_id, spec.key)
            blob = _snapshot_blob(engine, spec, compiled[spec.key])
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            entry["snapshot"] = os.path.basename(path)
            entry["snapshot_bytes"] = len(blob)
        entries.append(entry)
    return {
        "replica": replica_id,
        "params_sig": params_signature(engine.params),
        "programs": entries,
        "compile_s": stats["seconds"],
        "cache": {
            k: stats[k]
            for k in ("requests", "hits", "misses", "dir",
                      "entries_before", "entries_after")
        },
    }


#: Process-unique tags for snapshot recompiles (see _snapshot_blob).
_SNAP_TAGS = itertools.count()


def _snapshot_blob(engine, spec: ProgramSpec, compiled) -> bytes:
    """Serialize one executable as a warm-replica snapshot, VALIDATED
    by an in-process test load. On CPU jaxlib 0.4.x an executable whose
    program was ever LOADED in this process (a persistent-cache hit, a
    prior snapshot hydration) re-serializes without its jitted kernel
    symbols — deserialization then fails with "Symbols not found", and
    the kernel dedup is keyed by HLO module NAME, so even a fresh
    recompile of the same-named module stays thin. When validation
    catches that, the program is recompiled genuinely fresh: new jit
    object, persistent cache disabled, and a process-unique module
    name (``rename_forward``) the dedup cannot match. A deploy pass
    over a warm cache therefore still emits loadable snapshots."""
    import pickle as _pickle

    from jax.experimental import serialize_executable

    from gnot_tpu.utils.cache import compile_cache_disabled

    blob = _pickle.dumps(serialize_executable.serialize(compiled))
    try:
        serialize_executable.deserialize_and_load(*_pickle.loads(blob))
        return blob
    except Exception:  # noqa: BLE001 — fall through to the fresh compile
        pass
    tag = f"p{os.getpid()}_{next(_SNAP_TAGS)}"
    with compile_cache_disabled():
        placed = engine.place_batch(spec.dummy_batch())
        fresh = engine.lower_fresh(placed, tag=tag).compile()
    blob = _pickle.dumps(serialize_executable.serialize(fresh))
    # A snapshot that STILL fails to load is a deploy-time error — far
    # better than N replicas discovering it at scale-out.
    serialize_executable.deserialize_and_load(*_pickle.loads(blob))
    return blob


def hydrate(
    engine,
    programs: Sequence[dict],
    snapshot_dir: str,
    *,
    params_sig: str | None = None,
    dtype: str | None = None,
) -> dict:
    """Warm-replica hydration: deserialize each program's snapshot and
    install it in the engine's AOT table — no trace, no compile, no
    cache lookup on any later dispatch of that signature. Programs
    without a snapshot (or with an unreadable one) are SKIPPED and
    counted, not fatal: a missing snapshot degrades that one program to
    the ordinary jit-plus-persistent-cache path, exactly the cold
    behavior serving already survives. Returns ``{"installed",
    "skipped", "seconds", "keys"}``."""
    from jax.experimental import serialize_executable

    t0 = time.monotonic()
    if dtype is not None and dtype != getattr(engine, "dtype", "float32"):
        # Programs compiled at another serving dtype: refuse them ALL,
        # first. A bf16 deployment handed f32 snapshots must serve
        # cold, not serve the wrong-precision programs — params_sig
        # would also catch the cast weight mismatch, but the dtype
        # refusal is the named, deliberate contract (and covers
        # engines whose param trees happen to agree).
        return {
            "installed": 0,
            "skipped": len(list(programs)),
            "seconds": time.monotonic() - t0,
            "keys": [],
            "reason": "dtype_mismatch",
        }
    if params_sig is not None and params_sig != params_signature(
        engine.params
    ):
        # Snapshots from a different model/param layout: refuse them
        # ALL — the engine serves cold (jit + persistent cache), which
        # is slow but correct.
        return {
            "installed": 0,
            "skipped": len(list(programs)),
            "seconds": time.monotonic() - t0,
            "keys": [],
            "reason": "params_mismatch",
        }
    installed, skipped, keys, errors = 0, 0, [], []
    for entry in programs:
        name = entry.get("snapshot")
        path = os.path.join(snapshot_dir, name) if name else None
        try:
            if path is None:
                raise FileNotFoundError("no snapshot recorded")
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            loaded = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except Exception as err:  # noqa: BLE001 — degrade to the jit path
            skipped += 1
            errors.append(f"{entry.get('key')}: {type(err).__name__}: {err}")
            continue
        spec = ProgramSpec(
            **{
                k: entry[k]
                for k in ("key", "kind", "pad_nodes", "pad_funcs",
                          "rows", "dims", "plan")
            },
            dtype=entry.get("dtype", "float32"),
        )
        # Keyed on the PLACED signature, mirroring aot_compile's
        # lowering and _run_forward's lookup — an engine whose
        # device_put hook reshapes leaves (e.g. multi-process global
        # batch assembly) would otherwise install keys no dispatch
        # ever matches.
        signature = engine.signature_of(
            engine.place_batch(spec.dummy_batch())
        )
        engine.install_program(signature, loaded)
        installed += 1
        keys.append(spec.key)
        cat = getattr(engine, "catalog", None)
        if cat is not None:
            # Pre-record this program's costs at hydrate time so a
            # prewarmed tier's catalog is complete BEFORE traffic (the
            # engine's lazy capture would otherwise re-lower on first
            # dispatch — breaking prewarm's zero-compile contract).
            # Probe the deserialized executable; when its analysis
            # comes back thinner than the compile-time record shipped
            # in the manifest, prefer the manifest's.
            from gnot_tpu.obs.costs import extract_costs

            costs, source = extract_costs(loaded), "hydrate"
            mc = entry.get("costs")
            if mc is not None and len(mc.get("unavailable", ())) < len(
                costs.get("unavailable", ())
            ):
                costs, source = dict(mc), "manifest"
            cat.record(spec.key, costs, source=source)
    return {
        "installed": installed,
        "skipped": skipped,
        "seconds": time.monotonic() - t0,
        "keys": keys,
        **({"errors": errors} if errors else {}),
    }


def prewarm_deployment(
    engines,
    samples: Sequence[MeshSample],
    *,
    rows: int,
    pack_plan: PackPlan | None = None,
    snapshot_dir: str,
    manifest_path: str | None = None,
    sink=None,
    extra: dict | None = None,
) -> dict:
    """The deploy-time pass, end to end: enumerate the program family
    once, AOT-compile + snapshot it for EVERY engine of the target
    topology (``engines`` is ``[(replica_id, InferenceEngine), ...]`` —
    snapshots are device-bound, so each replica slice compiles its
    own), write the manifest, and emit one ``aot_prewarm`` event.
    Returns the manifest document (also written to ``manifest_path``
    when given)."""
    from gnot_tpu.obs import events

    engines = list(engines)
    if not engines:
        raise ValueError("prewarm_deployment needs at least one engine")
    specs = enumerate_programs(
        engines[0][1], samples, rows=rows, pack_plan=pack_plan
    )
    per_replica = {}
    for rid, engine in engines:
        per_replica[str(rid)] = aot_compile(
            engine, specs, replica_id=rid, snapshot_dir=snapshot_dir
        )
    blocks = per_replica.values()
    doc = {
        "version": MANIFEST_VERSION,
        "cache_dir": cache_dir_manifest(),
        "replicas": len(engines),
        "rows": rows,
        # The one serving dtype every program in this manifest was
        # lowered at — hydration matches it WHOLESALE against the
        # serving engine (hydrate's dtype refusal).
        "dtype": getattr(engines[0][1], "dtype", "float32"),
        "packed": pack_plan is not None,
        "snapshot_dir": os.path.abspath(snapshot_dir),
        "program_keys": [sp.key for sp in specs],
        "compile_s": sum(b["compile_s"] for b in blocks),
        "snapshot_bytes": sum(
            e["snapshot_bytes"] or 0
            for b in blocks
            for e in b["programs"]
        ),
        "cache": {
            "hits": _sum_opt(b["cache"]["hits"] for b in blocks),
            "misses": _sum_opt(b["cache"]["misses"] for b in blocks),
        },
        **(extra or {}),
        "per_replica": per_replica,
    }
    if manifest_path:
        save_manifest(manifest_path, doc)
    if sink is not None:
        sink.log(
            event=events.AOT_PREWARM,
            replicas=doc["replicas"],
            programs=len(specs) * len(engines),
            compile_s=doc["compile_s"],
            cache_dir=cache_dir_manifest().get("dir"),
            snapshot_dir=doc["snapshot_dir"],
            snapshot_bytes=doc["snapshot_bytes"],
            hits=doc["cache"]["hits"],
            misses=doc["cache"]["misses"],
            **({"manifest": manifest_path} if manifest_path else {}),
        )
    return doc


def _sum_opt(values) -> int | None:
    """Sum that degrades to None when any addend is None (the probe's
    private-API degradation contract)."""
    total = 0
    for v in values:
        if v is None:
            return None
        total += v
    return total


def hydrate_block(engine, manifest: dict, replica_id: int) -> dict:
    """Hydrate one engine from its manifest block — THE shared entry
    point for both ``EngineReplica.prewarm_from`` and the
    single-server ``--serve_prewarm`` path, so dtype/params-guard
    threading and skip accounting cannot drift between them."""
    block = manifest["per_replica"][str(replica_id)]
    return hydrate(
        engine,
        block["programs"],
        manifest["snapshot_dir"],
        params_sig=block.get("params_sig"),
        dtype=manifest.get("dtype", "float32"),
    )


def save_manifest(path: str, doc: dict) -> str:
    """Atomically write the deploy manifest (fills in the schema
    version and the cache-dir occupancy snapshot when absent)."""
    doc = {
        "version": MANIFEST_VERSION,
        "cache_dir": cache_dir_manifest(),
        **doc,
    }
    if d := os.path.dirname(path):
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return path


def load_manifest(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"manifest {path} has version {doc.get('version')!r}; this "
            f"build reads version {MANIFEST_VERSION} — re-run "
            "tools/aot_prewarm.py against the current tree"
        )
    return doc
