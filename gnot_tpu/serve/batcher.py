"""Dynamic request batching with per-bucket flush discipline.

Single-sample requests queue per BUCKET (the engine's ``bucket_key`` —
the static pad shape their dispatch must compile at) and a bucket
flushes when it holds ``max_batch`` requests or its oldest entry has
waited ``max_wait_ms``. Two invariants the chaos suite asserts:

* a batch NEVER spans two buckets — mixing a 64-point Darcy query with
  a 64k-point Heatsink3d query would pad the former to the latter's
  bucket and waste >99% of the dispatch FLOPs (ISSUE 3 motivation);
* every dispatch is shape-identical within its bucket (the server pads
  the sample count to a fixed row count), so the compiled-program
  count is bounded by the bucket count: O(log L_max), never O(traffic).

Pure data structure — no thread, no lock, no clock of its own (callers
pass ``now``); exactly ONE worker loop drives each instance. Under
replicated serving (serve/router.py) every replica's ``InferenceServer``
owns its own ``Batcher`` — queues never span replicas, so the router's
bucket-affinity decision is the only cross-replica coupling and this
structure stays single-threaded by construction. FIFO within a bucket, so
per-bucket latency is arrival-ordered — which also makes the server's
``queue_wait`` spans (obs/tracing.py: submit -> dispatch pop, the same
interval the deadline shed reports as ``waited_ms``) monotone within a
bucket: a request never overtakes an older batchmate, so a trace's
queue-wait outlier always indicts real queueing, not reordering.

Multi-tenant mode (``tenants=`` a ``policies.TenantPolicy``): each
bucket holds per-TENANT FIFO sub-queues drained by weighted fair
queueing — strict priority tiers first (every ``interactive``-class
tenant before any ``batch``-class one: brownout before blackout), then
deficit-round-robin by configured weight within a tier, FIFO within a
tenant. A flooding tenant therefore cannot starve siblings (each
non-empty sibling receives at least ``weight`` slots per DRR round),
the bucket invariants above survive unchanged (sub-queues never span
buckets), and ``pack_prefix``'s arrival-order contract holds WITHIN
each tenant (the packed take consumes the WFQ order, which is FIFO per
tenant). Age is per REQUEST across every sub-queue: the flush clock
reads the oldest arrival of the whole bucket, so ``max_wait_ms`` bounds
the queue wait of the lowest-weight tenant's head too — WFQ shapes
ORDER under contention, never starvation. ``tenants=None`` (the
default) leaves every path above byte-for-byte identical to the
single-FIFO batcher.
"""

from __future__ import annotations

from typing import Callable, Hashable, Iterable


class _TenantQueues:
    """One bucket's per-tenant FIFO sub-queues plus its WFQ ring (the
    tenant service order, rotated past the last-served tenant after
    each cut so remainder slots do not always favor the first tenant).
    Internal to ``Batcher``'s tenant mode."""

    __slots__ = ("queues", "ring")

    def __init__(self):
        self.queues: dict[Hashable, list] = {}  # tenant -> [(req, arrival)]
        self.ring: list = []  # tenant service order

    def size(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def oldest(self) -> float:
        """Oldest arrival across ALL sub-queues (each head is its
        queue's oldest — FIFO within tenant), i.e. the whole bucket's
        per-request age clock."""
        return min(q[0][1] for q in self.queues.values() if q)

    def add(self, tenant, request, now: float) -> None:
        q = self.queues.get(tenant)
        if q is None:
            q = self.queues[tenant] = []
            self.ring.append(tenant)
        q.append((request, now))

    def prune(self) -> None:
        for t in [t for t, q in self.queues.items() if not q]:
            del self.queues[t]
            self.ring.remove(t)


class Batcher:
    """Groups queued requests per bucket; flush on size or age.

    ``key_fn(request)`` maps a request to its bucket key (hashable).
    ``max_wait_ms`` bounds time-to-first-dispatch for a lonely request
    in an idle bucket — the latency/utilization dial.
    """

    def __init__(
        self,
        *,
        max_batch: int,
        max_wait_ms: float,
        key_fn: Callable[[object], Hashable],
        take_fn: Callable[[Hashable, list], int | None] | None = None,
        tenants=None,
        tenant_fn: Callable[[object], Hashable] | None = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.key_fn = key_fn
        # Optional per-bucket dispatch-capacity override (the packed
        # serve path): ``take_fn(key, requests) -> n | None`` returns
        # how many of the FIFO prefix fit one dispatch (a first-fit
        # packer for the packed bucket), or None for the default
        # max_batch discipline. A bucket whose prefix-take is smaller
        # than its queue is FULL (one whole dispatch is ready).
        self.take_fn = take_fn
        # Multi-tenant WFQ mode (docstring above): ``tenants`` supplies
        # weight(t)/priority(t); ``tenant_fn(request)`` names a
        # request's tenant (the server maps untagged requests to the
        # default tenant). None = single-FIFO mode, byte-for-byte the
        # pre-tenant batcher.
        self.tenants = tenants
        self.tenant_fn = tenant_fn or (lambda r: getattr(r, "tenant", None))
        # Per-bucket FIFO of (request, arrival) pairs — or, in tenant
        # mode, a ``_TenantQueues``. Ages are per-request either way,
        # so a leftover surviving a size-based flush keeps its true
        # arrival time and the max_wait bound holds for it too (a
        # bucket-level "oldest" stamp would reset its clock).
        self._pending: dict[Hashable, list | _TenantQueues] = {}

    def __len__(self) -> int:
        if self.tenants is not None:
            return sum(b.size() for b in self._pending.values())
        return sum(len(v) for v in self._pending.values())

    def add(self, request, now: float) -> None:
        if self.tenants is not None:
            key = self.key_fn(request)
            b = self._pending.get(key)
            if b is None:
                b = self._pending[key] = _TenantQueues()
            b.add(self.tenant_fn(request), request, now)
            return
        self._pending.setdefault(self.key_fn(request), []).append(
            (request, now)
        )

    def _take(self, key: Hashable, q: list) -> int | None:
        """Prefix-take for the next dispatch from ``q`` under
        ``take_fn`` (None = bucket uses the default max_batch
        discipline). Clamped to ``[1, len(q)]``: the key_fn routes only
        plan-fitting requests to a packed bucket, so a 0 from a
        degenerate packer must not wedge the queue forever."""
        if self.take_fn is None:
            return None
        n = self.take_fn(key, [r for r, _ in q])
        if n is None:
            return None
        return max(1, min(n, len(q)))

    def pop_ready(
        self, now: float, *, flush_all: bool = False
    ) -> list[tuple[Hashable, list]]:
        """Flushable ``(bucket_key, requests)`` batches: full buckets
        always; aged buckets (oldest waiting >= max_wait); everything
        when ``flush_all`` (drain). Each batch holds at most
        ``max_batch`` requests from ONE bucket — or, for a bucket with
        a ``take_fn`` capacity (the packed serve path), exactly the
        FIFO prefix the packer says fits one dispatch; such a bucket is
        FULL when its prefix-take is smaller than its queue (one whole
        dispatch is ready and the next arrival already spills). An
        overfull bucket yields several batches in arrival order — WFQ
        order in tenant mode (see module docstring)."""
        if self.tenants is not None:
            return self._pop_ready_wfq(now, flush_all)
        out: list[tuple[Hashable, list]] = []
        for key in list(self._pending):
            q = self._pending[key]
            take = self._take(key, q)
            if take is None:
                ready = (
                    flush_all
                    or len(q) >= self.max_batch
                    or now - q[0][1] >= self.max_wait_s
                )
                if not ready:
                    continue
                while q and (flush_all or len(q) >= self.max_batch):
                    out.append((key, [r for r, _ in q[: self.max_batch]]))
                    del q[: self.max_batch]
            else:
                ready = (
                    flush_all
                    or take < len(q)
                    or now - q[0][1] >= self.max_wait_s
                )
                if not ready:
                    continue
                while q and (flush_all or take < len(q)):
                    out.append((key, [r for r, _ in q[:take]]))
                    del q[:take]
                    if q:
                        take = self._take(key, q)
            if q and not flush_all and now - q[0][1] >= self.max_wait_s:
                # Aged flush of a partial bucket: take it all — the
                # oldest entry has already waited its budget. (In the
                # take_fn case the size loop above has already cut the
                # queue down to one whole dispatch: take == len(q).)
                out.append((key, [r for r, _ in q]))
                q.clear()
            if not q:
                del self._pending[key]
        return out

    # -- tenant-mode (WFQ) internals --------------------------------------

    def _wfq_order(self, b: _TenantQueues) -> list:
        """The bucket's full dispatch order as ``(tenant, request)``
        pairs WITHOUT mutating state: strict priority tiers
        (interactive before batch), deficit-round-robin by weight
        within a tier (quantum = weight, cost 1/request, deficit reset
        when a tenant's queue runs dry — no banking while idle), FIFO
        within a tenant. A cut of n commits exactly the first n of this
        sequence, so stopping early never reorders."""
        pol = self.tenants
        seq: list = []
        cursor = dict.fromkeys(b.ring, 0)
        for tier in ("interactive", "batch"):
            ring = [t for t in b.ring if pol.priority(t) == tier]
            deficit = dict.fromkeys(ring, 0.0)
            while any(cursor[t] < len(b.queues[t]) for t in ring):
                for t in ring:
                    q = b.queues[t]
                    if cursor[t] >= len(q):
                        deficit[t] = 0.0
                        continue
                    deficit[t] += pol.weight(t)
                    while cursor[t] < len(q) and deficit[t] >= 1.0:
                        seq.append((t, q[cursor[t]][0]))
                        cursor[t] += 1
                        deficit[t] -= 1.0
        return seq

    def _cut(self, b: _TenantQueues, seq: list, n: int) -> list:
        """Commit the first ``n`` emissions of ``seq``: pop each
        tenant's head in order (the sequence is FIFO per tenant, so the
        heads ARE the emitted requests), rotate the ring past the
        last-served tenant, prune emptied sub-queues."""
        batch = []
        for t, _ in seq[:n]:
            batch.append(b.queues[t].pop(0)[0])
        if n and len(b.ring) > 1:
            i = b.ring.index(seq[n - 1][0])
            b.ring = b.ring[i + 1:] + b.ring[: i + 1]
        b.prune()
        return batch

    def _pop_ready_wfq(
        self, now: float, flush_all: bool
    ) -> list[tuple[Hashable, list]]:
        out: list[tuple[Hashable, list]] = []
        for key in list(self._pending):
            b = self._pending[key]
            while b.size():
                seq = self._wfq_order(b)
                take = None
                if self.take_fn is not None:
                    n = self.take_fn(key, [r for _, r in seq])
                    if n is not None:
                        take = max(1, min(n, len(seq)))
                # Per-REQUEST age across every sub-queue: the oldest
                # head anywhere in the bucket starts the flush clock,
                # so max_wait_ms bounds the lowest-weight tenant's
                # queue wait too (not just the sub-queue WFQ happens to
                # favor).
                aged = now - b.oldest() >= self.max_wait_s
                if take is None:
                    if flush_all or len(seq) >= self.max_batch:
                        out.append(
                            (key, self._cut(b, seq, min(self.max_batch,
                                                        len(seq))))
                        )
                        continue
                    if aged:
                        # Aged flush of a partial bucket: take it all —
                        # the oldest entry (whatever its tenant) has
                        # already waited its budget.
                        out.append((key, self._cut(b, seq, len(seq))))
                    break
                else:
                    if flush_all or take < len(seq) or aged:
                        out.append((key, self._cut(b, seq, take)))
                        continue
                    break
            if not b.size():
                self._pending.pop(key, None)
        return out

    def next_flush_in(self, now: float) -> float | None:
        """Seconds until the next age-based flush (0 when one is
        already due), or None when empty — the worker's poll timeout,
        so an idle server blocks instead of spinning."""
        if not self._pending:
            return None
        if self.tenants is not None:
            due = min(b.oldest() for b in self._pending.values())
        else:
            due = min(q[0][1] for q in self._pending.values())
        return max(0.0, due + self.max_wait_s - now)

    def requests(self) -> Iterable:
        """All pending requests (shed/cancel sweeps during drain)."""
        if self.tenants is not None:
            for b in self._pending.values():
                for q in b.queues.values():
                    for r, _ in q:
                        yield r
            return
        for q in self._pending.values():
            for r, _ in q:
                yield r
