"""Dynamic request batching with per-bucket flush discipline.

Single-sample requests queue per BUCKET (the engine's ``bucket_key`` —
the static pad shape their dispatch must compile at) and a bucket
flushes when it holds ``max_batch`` requests or its oldest entry has
waited ``max_wait_ms``. Two invariants the chaos suite asserts:

* a batch NEVER spans two buckets — mixing a 64-point Darcy query with
  a 64k-point Heatsink3d query would pad the former to the latter's
  bucket and waste >99% of the dispatch FLOPs (ISSUE 3 motivation);
* every dispatch is shape-identical within its bucket (the server pads
  the sample count to a fixed row count), so the compiled-program
  count is bounded by the bucket count: O(log L_max), never O(traffic).

Pure data structure — no thread, no lock, no clock of its own (callers
pass ``now``); exactly ONE worker loop drives each instance. Under
replicated serving (serve/router.py) every replica's ``InferenceServer``
owns its own ``Batcher`` — queues never span replicas, so the router's
bucket-affinity decision is the only cross-replica coupling and this
structure stays single-threaded by construction. FIFO within a bucket, so
per-bucket latency is arrival-ordered — which also makes the server's
``queue_wait`` spans (obs/tracing.py: submit -> dispatch pop, the same
interval the deadline shed reports as ``waited_ms``) monotone within a
bucket: a request never overtakes an older batchmate, so a trace's
queue-wait outlier always indicts real queueing, not reordering.
"""

from __future__ import annotations

from typing import Callable, Hashable, Iterable


class Batcher:
    """Groups queued requests per bucket; flush on size or age.

    ``key_fn(request)`` maps a request to its bucket key (hashable).
    ``max_wait_ms`` bounds time-to-first-dispatch for a lonely request
    in an idle bucket — the latency/utilization dial.
    """

    def __init__(
        self,
        *,
        max_batch: int,
        max_wait_ms: float,
        key_fn: Callable[[object], Hashable],
        take_fn: Callable[[Hashable, list], int | None] | None = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.key_fn = key_fn
        # Optional per-bucket dispatch-capacity override (the packed
        # serve path): ``take_fn(key, requests) -> n | None`` returns
        # how many of the FIFO prefix fit one dispatch (a first-fit
        # packer for the packed bucket), or None for the default
        # max_batch discipline. A bucket whose prefix-take is smaller
        # than its queue is FULL (one whole dispatch is ready).
        self.take_fn = take_fn
        # Per-bucket FIFO of (request, arrival) pairs: ages are
        # per-request, so a leftover surviving a size-based flush keeps
        # its true arrival time and the max_wait bound holds for it too
        # (a bucket-level "oldest" stamp would reset its clock).
        self._pending: dict[Hashable, list] = {}

    def __len__(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def add(self, request, now: float) -> None:
        self._pending.setdefault(self.key_fn(request), []).append(
            (request, now)
        )

    def _take(self, key: Hashable, q: list) -> int | None:
        """Prefix-take for the next dispatch from ``q`` under
        ``take_fn`` (None = bucket uses the default max_batch
        discipline). Clamped to ``[1, len(q)]``: the key_fn routes only
        plan-fitting requests to a packed bucket, so a 0 from a
        degenerate packer must not wedge the queue forever."""
        if self.take_fn is None:
            return None
        n = self.take_fn(key, [r for r, _ in q])
        if n is None:
            return None
        return max(1, min(n, len(q)))

    def pop_ready(
        self, now: float, *, flush_all: bool = False
    ) -> list[tuple[Hashable, list]]:
        """Flushable ``(bucket_key, requests)`` batches: full buckets
        always; aged buckets (oldest waiting >= max_wait); everything
        when ``flush_all`` (drain). Each batch holds at most
        ``max_batch`` requests from ONE bucket — or, for a bucket with
        a ``take_fn`` capacity (the packed serve path), exactly the
        FIFO prefix the packer says fits one dispatch; such a bucket is
        FULL when its prefix-take is smaller than its queue (one whole
        dispatch is ready and the next arrival already spills). An
        overfull bucket yields several batches in arrival order."""
        out: list[tuple[Hashable, list]] = []
        for key in list(self._pending):
            q = self._pending[key]
            take = self._take(key, q)
            if take is None:
                ready = (
                    flush_all
                    or len(q) >= self.max_batch
                    or now - q[0][1] >= self.max_wait_s
                )
                if not ready:
                    continue
                while q and (flush_all or len(q) >= self.max_batch):
                    out.append((key, [r for r, _ in q[: self.max_batch]]))
                    del q[: self.max_batch]
            else:
                ready = (
                    flush_all
                    or take < len(q)
                    or now - q[0][1] >= self.max_wait_s
                )
                if not ready:
                    continue
                while q and (flush_all or take < len(q)):
                    out.append((key, [r for r, _ in q[:take]]))
                    del q[:take]
                    if q:
                        take = self._take(key, q)
            if q and not flush_all and now - q[0][1] >= self.max_wait_s:
                # Aged flush of a partial bucket: take it all — the
                # oldest entry has already waited its budget. (In the
                # take_fn case the size loop above has already cut the
                # queue down to one whole dispatch: take == len(q).)
                out.append((key, [r for r, _ in q]))
                q.clear()
            if not q:
                del self._pending[key]
        return out

    def next_flush_in(self, now: float) -> float | None:
        """Seconds until the next age-based flush (0 when one is
        already due), or None when empty — the worker's poll timeout,
        so an idle server blocks instead of spinning."""
        if not self._pending:
            return None
        due = min(q[0][1] for q in self._pending.values()) + self.max_wait_s
        return max(0.0, due - now)

    def requests(self) -> Iterable:
        """All pending requests (shed/cancel sweeps during drain)."""
        for q in self._pending.values():
            for r, _ in q:
                yield r
