"""Core GNOT layers: MLP and heterogeneous normalized linear attention.

Flax linen modules; all heavy math lives in ``gnot_tpu.ops.attention`` as
pure einsum functions. Parameter initialization matches
``torch.nn.Linear`` (kaiming-uniform weight with a=sqrt(5) + fan-in
uniform bias) so that *training from scratch* has the same dynamics as
the reference, not just weight-imported inference.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from gnot_tpu.ops.attention import (
    feature_softmax,
    merge_heads,
    normalized_linear_attention,
    packed_normalized_linear_attention,
    split_heads,
)
from gnot_tpu.ops.pallas_ffn import fits_vmem, fused_gated_ffn

Array = jax.Array

# torch.nn.Linear weight init: kaiming_uniform(a=sqrt(5)) == U(+-1/sqrt(fan_in))
# which is variance_scaling(1/3, fan_in, uniform).
torch_kernel_init = nn.initializers.variance_scaling(
    scale=1.0 / 3.0, mode="fan_in", distribution="uniform"
)


def torch_bias_init(fan_in: int):
    """torch.nn.Linear bias init: U(+-1/sqrt(fan_in))."""

    def init(key, shape, dtype=jnp.float32):
        bound = 1.0 / (fan_in**0.5)
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def torch_dense(features: int, fan_in: int, *, name: str | None = None, dtype=None):
    """A Dense layer with torch.nn.Linear-equivalent initialization."""
    return nn.Dense(
        features,
        kernel_init=torch_kernel_init,
        bias_init=torch_bias_init(fan_in),
        name=name,
        dtype=dtype,
    )


class Mlp(nn.Module):
    """GELU MLP matching the reference ``MLP`` (model.py:5-18).

    ``num_layers`` counts *hidden* blocks: the stack is
    ``Linear(in->hid), GELU, [Linear(hid->hid), GELU] x (num_layers-1),
    Linear(hid->out)`` — ``num_layers + 1`` Linears total, erf-GELU
    (torch ``nn.GELU()`` default), no final activation, no norm.
    """

    num_layers: int
    hidden_dim: int
    output_dim: int
    dtype: Any = None
    # "erf": torch nn.GELU default (parity). "tanh": the standard
    # approximation — ~2x cheaper on the TPU VPU (see config.gelu).
    gelu: str = "erf"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        gelu = functools.partial(jax.nn.gelu, approximate=self.gelu == "tanh")
        fan_in = x.shape[-1]
        for i in range(self.num_layers):
            x = torch_dense(
                self.hidden_dim, fan_in, name=f"dense_{i}", dtype=self.dtype
            )(x)
            x = gelu(x)
            fan_in = self.hidden_dim
        return torch_dense(
            self.output_dim, fan_in, name=f"dense_{self.num_layers}", dtype=self.dtype
        )(x)


def _stacked_dense(features: int, fan_in: int, *, name: str, dtype=None):
    """A Dense vmapped over a leading stack axis with per-slice params.

    Equivalent of a ``torch.nn.ModuleList`` of Linears, but the stacked
    parameter tensor ``[S, in, out]`` turns S separate GEMMs into one
    batched GEMM — the MXU-friendly layout.
    """
    vmapped = nn.vmap(
        nn.Dense,
        in_axes=0,
        out_axes=0,
        variable_axes={"params": 0},
        split_rngs={"params": True},
    )
    return vmapped(
        features,
        kernel_init=torch_kernel_init,
        bias_init=torch_bias_init(fan_in),
        name=name,
        dtype=dtype,
    )


class LinearAttention(nn.Module):
    """Heterogeneous normalized linear attention (model.py:33-107).

    Cross mode (``n_input_functions > 0``): per-input-function K/V
    projections (stacked, one batched GEMM), per-function attention
    outputs averaged. Self mode: K/V from the query sequence itself.

    Faithful quirks preserved from the reference:
      * q and k are softmaxed over the **feature** axis (model.py:59,72,93);
      * the residual adds the *softmaxed* q, not the raw input
        (model.py:86,104);
      * a single ``fc_out`` closes both branches (model.py:106).
    """

    n_embed: int
    n_head: int
    n_input_functions: int = 0
    dtype: Any = None
    # The reference merges heads by reshaping the PERMUTED [B,H,L,D]
    # tensor straight to [B,L,E] (model.py:81,83,103-104) — an
    # interleave that mixes heads AND sequence positions across output
    # rows, not a transpose-merge. parity=True replicates that exactly;
    # parity=False uses the correct [B,L,H*D] merge (required for
    # pad-invariance in masked mode, since the interleaved merge leaks
    # padded-row garbage into real rows).
    parity: bool = False

    def _merge(self, x: Array) -> Array:
        if self.parity:
            b, h, l, d = x.shape
            return x.reshape(b, l, h * d)
        return merge_heads(x)

    @nn.compact
    def __call__(
        self,
        query: Array,
        input_functions: Array | None = None,
        *,
        query_mask: Array | None = None,
        func_mask: Array | None = None,
        q_seg_oh: Array | None = None,
        kv_seg_oh: Array | None = None,
    ) -> Array:
        """``q_seg_oh``/``kv_seg_oh`` switch on the PACKED layout
        (ops.attention.packed_normalized_linear_attention): one-hot
        chunk->segment maps for the query rows and (cross mode) the
        slot-indexed input-function rows — ARRAYS, precomputed once per
        forward by the caller (segment_one_hot), so no static int
        crosses a remat boundary. Masked mode only — parity's
        interleaved head merge is packing-hostile by design.
        """
        packed = q_seg_oh is not None
        if packed and self.parity:
            raise ValueError("packed attention requires parity=False")
        e, h = self.n_embed, self.n_head
        q_proj = torch_dense(e, query.shape[-1], name="query", dtype=self.dtype)(query)

        if self.n_input_functions > 0:
            if input_functions is None:
                raise ValueError(
                    "cross-attention layer called without input functions"
                )
            # input_functions: [F, B, Lf, E]; stacked K/V -> one batched GEMM.
            fan_in = input_functions.shape[-1]
            k_proj = _stacked_dense(e, fan_in, name="key", dtype=self.dtype)(
                input_functions
            )
            v_proj = _stacked_dense(e, fan_in, name="value", dtype=self.dtype)(
                input_functions
            )
            q = feature_softmax(split_heads(q_proj, h))
            k = feature_softmax(jax.vmap(lambda t: split_heads(t, h))(k_proj))
            v = jax.vmap(lambda t: split_heads(t, h))(v_proj)
            mask_axis = None if func_mask is None else 0
            if packed:
                # kv_seg_oh (the slot-row -> segment map) is SHARED by
                # all F functions — the stacked funcs tensor is
                # slot-indexed.
                out = jax.vmap(
                    _packed_nla_positional,
                    in_axes=(None, 0, 0, mask_axis, None, None),
                )(q, k, v, func_mask, q_seg_oh, kv_seg_oh)  # [F, Bq, H, Lq, D]
            else:
                out = jax.vmap(_nla_positional, in_axes=(None, 0, 0, mask_axis))(
                    q, k, v, func_mask
                )  # [F, B, H, Lq, D]
            res = self._merge(q) + self._merge(jnp.mean(out, axis=0))
        else:
            k_proj = torch_dense(e, query.shape[-1], name="key", dtype=self.dtype)(
                query
            )
            v_proj = torch_dense(e, query.shape[-1], name="value", dtype=self.dtype)(
                query
            )
            q = feature_softmax(split_heads(q_proj, h))
            k = feature_softmax(split_heads(k_proj, h))
            v = split_heads(v_proj, h)
            if packed:
                out = packed_normalized_linear_attention(
                    q, k, v, q_seg_oh=q_seg_oh, kv_seg_oh=q_seg_oh,
                    kv_mask=query_mask,
                )
            else:
                out = normalized_linear_attention(q, k, v, kv_mask=query_mask)
            res = self._merge(q) + self._merge(out)

        return torch_dense(e, e, name="fc_out", dtype=self.dtype)(res)


# vmap of normalized_linear_attention needs mask passed positionally; wrap.
def _nla_positional(q, k, v, mask):
    return normalized_linear_attention(q, k, v, kv_mask=mask)


def _packed_nla_positional(q, k, v, mask, q_seg_oh, kv_seg_oh):
    return packed_normalized_linear_attention(
        q, k, v, q_seg_oh=q_seg_oh, kv_seg_oh=kv_seg_oh, kv_mask=mask
    )


def gate_stats(scores: Array, mask: Array | None) -> dict[str, Array]:
    """Gate-health scalars for one layer's geometry-gating ``scores``
    ``[B, L, E]``: per-expert load fractions (masked token mean — a
    collapsed gate shows one expert's load -> 1) and the mean per-token
    gate entropy in nats (uniform gating -> log E, collapse -> 0).
    Pure f32 reductions; ``mask=None`` (parity mode) averages every
    token, matching parity's pads-are-real semantics."""
    s = scores.astype(jnp.float32)
    ent = -jnp.sum(s * jnp.log(jnp.clip(s, 1e-20)), axis=-1)  # [B, L]
    if mask is None:
        return {"gate_load": jnp.mean(s, axis=(0, 1)), "gate_entropy": jnp.mean(ent)}
    m = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(m), 1.0)
    load = jnp.einsum("ble,bl->e", s, m) / denom
    return {"gate_load": load, "gate_entropy": jnp.sum(ent * m) / denom}


class GatedExpertFfn(nn.Module):
    """Dense soft mixture-of-experts FFN (model.py:123-124,128-131).

    Every expert runs on every token (no routing / capacity factor — this
    is a *soft* mixture); outputs are combined with the geometry-gating
    ``scores``. The E expert MLPs are stacked so each Linear becomes one
    batched ``[E, ...]`` GEMM on the MXU instead of an E-way Python loop.

    ``ffn_impl='pallas'`` runs the whole expert stack tile-resident in
    VMEM (ops/pallas_ffn.py) — no ``[E, B, L, hidden]`` HBM slabs
    between layers — when the weight set fits the VMEM budget;
    otherwise it falls back to the XLA path.
    """

    n_expert: int
    num_layers: int
    hidden_dim: int
    output_dim: int
    dtype: Any = None
    ffn_impl: str = "xla"
    gelu: str = "erf"

    @nn.compact
    def __call__(self, x: Array, scores: Array) -> Array:
        experts = nn.vmap(
            Mlp,
            in_axes=None,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            axis_size=self.n_expert,
        )(
            self.num_layers, self.hidden_dim, self.output_dim, self.dtype,
            self.gelu, name="experts",
        )

        if self.ffn_impl == "pallas" and not self.is_initializing():
            p = self.variables["params"]["experts"]
            kernels = [
                p[f"dense_{i}"]["kernel"] for i in range(self.num_layers + 1)
            ]
            biases = [p[f"dense_{i}"]["bias"] for i in range(self.num_layers + 1)]
            if fits_vmem(kernels, biases):
                return fused_gated_ffn(x, scores, kernels, biases, gelu=self.gelu)

        out = experts(x)  # [E, B, L, D]
        # scores: [B, L, E]; gate-weighted sum over experts (model.py:130).
        return jnp.einsum("ebld,ble->bld", out, scores.astype(out.dtype))
