"""GNOT — General Neural Operator Transformer (arXiv 2302.14376).

TPU-native Flax implementation with the exact semantics of the reference
(``/root/reference/model.py:118-172``), including its deliberate quirks:

* geometry gating is computed on the **raw coordinates only** (before the
  theta concat), softmaxed over experts, and reused by every block
  (model.py:148,155-156,169);
* there is **no LayerNorm anywhere** (a divergence from the GNOT paper
  that the reference makes and we preserve for parity);
* the residual inside attention adds the softmaxed q (see layers.py).

Two operating modes (``ModelConfig.attention_mode``):
* ``"parity"`` — unmasked padding, numerics faithful to the reference
  (padding pollutes attention; results depend on batch composition);
* ``"masked"`` — ragged structure carried as 0/1 masks folded into the
  attention reductions and losses; results are pad-length invariant.
  This is the default and the mode all performance numbers use.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from gnot_tpu.config import ModelConfig
from gnot_tpu.models.layers import GatedExpertFfn, LinearAttention, Mlp, gate_stats

Array = jax.Array


class HNABlock(nn.Module):
    """One Heterogeneous Normalized Attention encoder layer
    (reference model.py:118-139): cross-attention -> gated expert FFN ->
    residual, then self-attention -> gated expert FFN -> residual."""

    n_attn_hidden_dim: int
    n_mlp_num_layers: int
    n_mlp_hidden_dim: int
    n_input_hidden_dim: int
    n_expert: int
    n_head: int
    n_input_functions: int = 0
    dtype: Any = None
    parity: bool = False
    ffn_impl: str = "xla"
    gelu: str = "erf"

    @nn.compact
    def __call__(
        self,
        scores: Array,
        query: Array,
        input_functions: Array | None = None,
        *,
        node_mask: Array | None = None,
        func_mask: Array | None = None,
        node_seg_oh: Array | None = None,
        func_seg_oh: Array | None = None,
    ) -> Array:
        # Gate telemetry side-channel: per-layer expert load fractions +
        # entropy, sown into the "intermediates" collection. Free unless
        # the caller applies with mutable=["intermediates"] (the
        # telemetry train step, obs/telemetry.py); sown per BLOCK even
        # though the reference shares one gate across layers, so a
        # future per-layer gate keeps the same record schema.
        for k, v in gate_stats(scores, node_mask).items():
            self.sow("intermediates", k, v)
        cross = LinearAttention(
            self.n_attn_hidden_dim,
            self.n_head,
            self.n_input_functions,
            dtype=self.dtype,
            parity=self.parity,
            name="cross_attention",
        )(
            query, input_functions, query_mask=node_mask, func_mask=func_mask,
            q_seg_oh=node_seg_oh, kv_seg_oh=func_seg_oh,
        )
        ffn1 = GatedExpertFfn(
            self.n_expert,
            self.n_mlp_num_layers,
            self.n_mlp_hidden_dim,
            self.n_mlp_hidden_dim,
            dtype=self.dtype,
            ffn_impl=self.ffn_impl,
            gelu=self.gelu,
            name="ffn1",
        )(cross, scores)
        query = query + ffn1

        self_out = LinearAttention(
            self.n_attn_hidden_dim,
            self.n_head,
            0,
            dtype=self.dtype,
            parity=self.parity,
            name="self_attention",
        )(query, query_mask=node_mask, q_seg_oh=node_seg_oh)
        ffn2 = GatedExpertFfn(
            self.n_expert,
            self.n_mlp_num_layers,
            self.n_mlp_hidden_dim,
            self.n_mlp_hidden_dim,
            dtype=self.dtype,
            ffn_impl=self.ffn_impl,
            gelu=self.gelu,
            name="ffn2",
        )(self_out, scores)
        return query + ffn2


# --- Shared module factories + pure math ---------------------------------
#
# Single source of truth for every submodule's hyperparameters and the
# pre/post-block math. GNOT.__call__ composes them inline (compact, so
# the `name=`s place params at the reference-mapped tree paths); the
# pipeline-parallel forward (parallel/pipeline.py) applies the very same
# factories standalone against the corresponding param subtrees — the
# two paths cannot drift apart.


def model_dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype) if cfg.dtype != "float32" else None


def precision_scope(cfg: ModelConfig):
    """Parity mode exists to reproduce the torch oracle; on TPU the
    default matmul precision accumulates bf16 passes and costs ~1e-4 of
    agreement by itself (docs/performance.md, hardware parity note).
    Pin full-f32 contractions so the mode means the same thing on every
    backend (no-op on CPU). THE one scope every parity-capable forward
    enters: GNOT.__call__, pipeline.stacked_forward, and
    pipeline.pipelined_forward."""
    import contextlib

    if cfg.attention_mode == "parity":
        return jax.default_matmul_precision("highest")
    return contextlib.nullcontext()


def gating_module(cfg: ModelConfig) -> Mlp:
    """Geometry gating MLP (model.py:148)."""
    return Mlp(
        cfg.n_mlp_num_layers,
        cfg.n_mlp_hidden_dim,
        cfg.n_expert,
        dtype=model_dtype(cfg),
        gelu=cfg.gelu,
        name="gating",
    )


def gating_scores(gating_out: Array) -> Array:
    """Softmax over experts in f32, computed once (model.py:155-156)."""
    return jax.nn.softmax(gating_out.astype(jnp.float32), axis=-1)


def query_features(coords: Array, theta: Array) -> Array:
    """theta broadcast along L, concat to coords (model.py:158-159)."""
    theta_b = jnp.broadcast_to(
        theta[:, None, :], (coords.shape[0], coords.shape[1], theta.shape[-1])
    )
    return jnp.concatenate([coords, theta_b], axis=-1)


def packed_query_features(coords: Array, theta: Array, node_seg: Array) -> Array:
    """Packed layout: theta is PER-SAMPLE ``[S, T]``; each token gathers
    its segment's theta (pad tokens clip to slot 0 — they are excluded
    from attention sums and the loss, so their value is inert)."""
    tok_seg = jnp.repeat(node_seg, coords.shape[1] // node_seg.shape[1], axis=1)
    th = jnp.take(theta, jnp.clip(tok_seg, 0, theta.shape[0] - 1), axis=0)
    return jnp.concatenate([coords, th.astype(coords.dtype)], axis=-1)


def x_embed_module(cfg: ModelConfig) -> Mlp:
    """Query embedding MLP (model.py:146,161)."""
    return Mlp(
        cfg.n_mlp_num_layers,
        cfg.n_input_hidden_dim,
        cfg.n_input_hidden_dim,
        dtype=model_dtype(cfg),
        gelu=cfg.gelu,
        name="x_embed",
    )


def func_embed_module(cfg: ModelConfig):
    """Per-input-function embedding MLPs (model.py:149,164-166),
    stacked over the function axis."""
    return nn.vmap(
        Mlp,
        in_axes=0,
        out_axes=0,
        variable_axes={"params": 0},
        split_rngs={"params": True},
    )(
        cfg.n_mlp_num_layers,
        cfg.n_mlp_hidden_dim,
        cfg.n_input_hidden_dim,
        model_dtype(cfg),
        cfg.gelu,
        name="input_func_mlps",
    )


def block_module(
    cfg: ModelConfig,
    has_funcs: bool,
    *,
    name: str | None = None,
    remat: bool = False,
) -> HNABlock:
    cls = nn.remat(HNABlock) if remat else HNABlock
    return cls(
        cfg.n_attn_hidden_dim,
        cfg.n_mlp_num_layers,
        cfg.n_mlp_hidden_dim,
        cfg.n_input_hidden_dim,
        cfg.n_expert,
        cfg.n_head,
        cfg.n_input_functions if has_funcs else 0,
        dtype=model_dtype(cfg),
        parity=cfg.attention_mode == "parity",
        ffn_impl=cfg.ffn_impl,
        gelu=cfg.gelu,
        name=name,
    )


def out_module(cfg: ModelConfig) -> Mlp:
    """Output projection MLP (model.py:152,171). ALWAYS f32
    (``dtype=None`` + the f32 input cast in ``finalize_input``): the
    head feeds RelL2 directly, so the precision policy
    (models/precision.py) keeps it out of the reduced-precision block
    stack. No-op for f32 configs, where ``model_dtype`` is None
    anyway."""
    return Mlp(
        cfg.n_mlp_num_layers,
        cfg.n_mlp_hidden_dim,
        cfg.out_dim,
        dtype=None,
        gelu=cfg.gelu,
        name="out_mlp",
    )


def finalize_input(query: Array) -> Array:
    """The encoder->head boundary: whatever dtype the block stack
    computed in, the output head reads f32 (a same-dtype cast XLA
    elides for f32 configs)."""
    return query.astype(jnp.float32)


def finalize_output(out: Array) -> Array:
    return out.astype(jnp.float32)


class GNOT(nn.Module):
    """Full GNOT model (reference model.py:142-172)."""

    config: ModelConfig

    @nn.compact
    def __call__(
        self,
        coords: Array,
        theta: Array,
        input_functions: Array | None = None,
        *,
        node_mask: Array | None = None,
        func_mask: Array | None = None,
        node_seg: Array | None = None,
        func_seg: Array | None = None,
        n_seg: int = 0,
    ) -> Array:
        """``node_seg``/``func_seg``/``n_seg`` select the PACKED layout
        ("pack, don't pad" — docs/performance.md): rows carry multiple
        samples as chunk-aligned segments, ``theta`` is per-sample
        ``[S, T]``, and attention/losses stay exactly per-sample via
        segment Grams. Masked mode only."""
        if node_seg is not None and self.config.attention_mode == "parity":
            raise ValueError(
                "packed layout requires attention_mode='masked' (parity "
                "reproduces the reference's per-batch padding pollution, "
                "which has no packed equivalent)"
            )
        if self.config.attention_mode == "parity":
            node_mask = func_mask = None
        with precision_scope(self.config):
            return self._gnot_forward(
                coords, theta, input_functions,
                node_mask=node_mask, func_mask=func_mask,
                node_seg=node_seg, func_seg=func_seg, n_seg=n_seg,
            )

    def _gnot_forward(
        self,
        coords: Array,
        theta: Array,
        input_functions: Array | None,
        *,
        node_mask: Array | None,
        func_mask: Array | None,
        node_seg: Array | None = None,
        func_seg: Array | None = None,
        n_seg: int = 0,
    ) -> Array:
        cfg = self.config

        # Geometry gating on raw coordinates, computed once (model.py:155-156).
        scores = gating_scores(gating_module(cfg)(coords))

        # Query embedding: theta broadcast along L, concat to coords
        # (model.py:158-161); packed rows gather per-token theta instead.
        if node_seg is not None:
            feats = packed_query_features(coords, theta, node_seg)
        else:
            feats = query_features(coords, theta)
        query = x_embed_module(cfg)(feats)

        if cfg.n_input_functions > 0 and input_functions is not None:
            funcs = func_embed_module(cfg)(input_functions)  # [F, B, Lf, D]
        else:
            funcs = None

        # One-hot segment maps, computed ONCE and threaded as arrays:
        # inside the blocks no static int remains, so the packed layout
        # composes with nn.remat (which traces every call argument).
        if node_seg is not None:
            from gnot_tpu.ops.attention import segment_one_hot

            node_seg_oh = segment_one_hot(node_seg, n_seg)
            func_seg_oh = (
                segment_one_hot(func_seg, n_seg) if func_seg is not None else None
            )
        else:
            node_seg_oh = func_seg_oh = None

        for i in range(cfg.n_attn_layers):
            query = block_module(
                cfg,
                funcs is not None,
                name=f"block_{i}",
                remat=cfg.remat,
            )(
                scores, query, funcs, node_mask=node_mask, func_mask=func_mask,
                node_seg_oh=node_seg_oh, func_seg_oh=func_seg_oh,
            )

        return finalize_output(out_module(cfg)(finalize_input(query)))
