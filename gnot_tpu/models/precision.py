"""Serving precision policy: WHERE reduced precision is safe, in code.

GNOT's linear attention is matmul-dominated — the ideal bf16 target on
matrix hardware — but its softmax-normalized queries are exactly the
normalization-sensitive structure Cao's Fourier/Galerkin analysis
(arXiv 2105.14995) warns about: the output is ``alpha * q @ (k^T v)``
with ``alpha = 1 / <q, k_sum>``, so any precision loss in the
normalizer multiplies EVERY output channel. Flipping one dtype flag is
therefore not a policy; this module is. It pins, as data the rest of
the stack threads through:

* **compute dtype** — the per-block matmul/activation dtype (the knob
  ``serve.dtype`` flips; flax modules receive it as their ``dtype``);
* **f32 accumulation** — attention einsums contract with an explicit
  ``preferred_element_type=float32`` so Gram/k_sum reductions never
  accumulate in bf16 (``ops/attention.py`` reads the input dtype and
  applies this; on TPU the MXU accumulates f32 natively, so this costs
  nothing there);
* **f32 normalizer** — ``<q, k_sum>`` and the ``1/x`` that follows are
  computed in f32 ALWAYS (never the compute dtype); the mutation test
  in tests/test_lowprec.py demonstrates what a bf16 normalizer does to
  parity;
* **f32 output head** — the final MLP feeds the RelL2 metric directly;
  it runs on f32 inputs with f32 params (``models/gnot.py::out_module``
  forces it when the block stack computes in bf16).

Params stay f32 AT REST everywhere (training state, checkpoints, hot
reload): the serving engine casts a bf16 copy at publish time
(``InferenceEngine.swap_params`` -> :func:`cast_params`), so
train/serve weight sharing and reload are untouched by the serving
dtype.

``int8`` weight-only for the FFN experts is the designed-for next step
behind the same policy object (``weights_dtype`` is separate from
``compute_dtype`` for exactly that reason); it is not wired yet.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: The serving dtypes the stack accepts end-to-end. Every program name,
#: manifest and event uses the SHORT tag (program identity must be
#: dtype-keyed but also stable and readable).
SERVE_DTYPES = ("float32", "bfloat16")
DTYPE_TAGS = {"float32": "f32", "bfloat16": "bf16"}


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One serving precision mode, as explicit per-site dtypes.

    ``accum_dtype``, ``normalizer_dtype`` and ``head_dtype`` are
    float32 by POLICY — ``__post_init__`` refuses anything else, so a
    future dtype cannot silently widen into the RelL2-critical sites.
    """

    compute_dtype: str = "float32"  # per-block matmuls + activations
    weights_dtype: str = "float32"  # published (serving) weight copy
    accum_dtype: str = "float32"  # attention einsum accumulation
    normalizer_dtype: str = "float32"  # <q, k_sum> and 1/x
    head_dtype: str = "float32"  # output MLP (RelL2-critical)

    def __post_init__(self) -> None:
        if self.compute_dtype not in SERVE_DTYPES:
            raise ValueError(
                f"unknown serve dtype {self.compute_dtype!r}; one of "
                f"{SERVE_DTYPES}"
            )
        for site in ("accum_dtype", "normalizer_dtype", "head_dtype"):
            if getattr(self, site) != "float32":
                raise ValueError(
                    f"{site} must stay float32 (the precision policy's "
                    "point — see models/precision.py docstring); got "
                    f"{getattr(self, site)!r}"
                )

    @property
    def tag(self) -> str:
        """Short dtype tag for program keys / manifests ("f32"/"bf16")."""
        return DTYPE_TAGS[self.compute_dtype]

    def table(self) -> list[tuple[str, str, str]]:
        """(site, dtype, why) rows — the docs/performance.md policy
        table renders from this so docs cannot drift from code."""
        return [
            ("block matmuls + activations", self.compute_dtype,
             "the throughput knob; matmul-dominated, bf16-safe"),
            ("published weight copy", self.weights_dtype,
             "cast once at publish; params stay f32 at rest"),
            ("attention einsum accumulation", self.accum_dtype,
             "Gram/k_sum reductions; bf16 accumulation loses the "
             "normalization property"),
            ("attention normalizer <q,k_sum>, 1/x", self.normalizer_dtype,
             "multiplies every output channel (2105.14995)"),
            ("output head MLP", self.head_dtype,
             "feeds RelL2 directly"),
        ]


def policy_for(dtype: str) -> PrecisionPolicy:
    """The serving policy for a ``serve.dtype`` value."""
    if dtype not in SERVE_DTYPES:
        raise ValueError(
            f"unknown serve dtype {dtype!r}; one of {SERVE_DTYPES}"
        )
    return PrecisionPolicy(compute_dtype=dtype, weights_dtype=dtype)


def np_dtype(dtype: str):
    """The numpy dtype object for a serve dtype (bfloat16 rides
    ml_dtypes, which jax already depends on — no new dependency)."""
    if dtype == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float32)


def cast_params(params, dtype: str):
    """A ``dtype`` copy of a param tree for publish: float leaves cast
    (f32 -> bf16 halves the published weight bytes), non-float leaves
    pass through untouched. Identity (the SAME tree object) for
    float32 — the f32 serving path stays byte-identical."""
    if dtype == "float32":
        return params
    import jax

    target = np_dtype(dtype)

    def cast(leaf):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and np.issubdtype(np.dtype(dt), np.floating):
            return leaf.astype(target)
        return leaf

    return jax.tree.map(cast, params)


def serve_model(model, dtype: str):
    """The model to SERVE at ``dtype``: the same architecture with the
    policy's compute dtype threaded per-block (flax ``dtype`` — params
    keep their own dtype; computation casts). Identity for float32 or
    when the model already computes at ``dtype``."""
    if dtype == "float32" or model.config.dtype == dtype:
        return model
    return type(model)(dataclasses.replace(model.config, dtype=dtype))
