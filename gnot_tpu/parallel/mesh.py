"""Device mesh + sharding layout: the distributed backend.

The reference has no parallelism or communication backend at all
(SURVEY.md §2 rows 9-10: single ``cuda:{id}`` device, no
torch.distributed). The TPU-native equivalent is declarative: pick a
mesh, annotate shardings, and let XLA GSPMD insert the collectives
(psum/all-gather/reduce-scatter) over ICI — nothing hand-built.

Axes of the mesh:

* ``data`` — batch sharding (DP). Gradient reduction becomes an
  implicit psum emitted by XLA.
* ``seq``  — sequence/context parallelism (SP) over mesh points. GNOT's
  linear attention shards trivially over sequence: ``k_sum`` and
  ``k^T v`` are segment-sums over L, so each shard contributes a partial
  sum and XLA inserts one psum per attention (SURVEY.md §5 long-context
  note). This is what makes Heatsink3d-scale point clouds fit.
* ``model`` — tensor parallelism (TP): attention projections are
  head-sharded (the embed axis factors as [head, head_dim] with head
  leading), expert-FFN hidden layers are column/row-sharded.
* ``expert`` — expert parallelism (EP) over the stacked soft-MoE
  expert axis. GNOT's mixture is dense (every expert runs on every
  token, no routing — reference model.py:128-130), so there is no
  all-to-all dispatch/combine as in routed MoE; each shard runs its
  experts on the full token stream and the gate-weighted combine
  (a contraction over E) becomes one psum.
* ``pipe`` — pipeline parallelism (PP) over the attention-block stack.
  Not a GSPMD axis: the pipeline is an explicit shard_map microbatch
  schedule (parallel/pipeline.py); ``make_sharded_train_step``
  dispatches there when the mesh carries ``pipe > 1``.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gnot_tpu.config import MeshConfig
from gnot_tpu.data.batch import MeshBatch

AXES = ("data", "seq", "model", "expert", "pipe")


def make_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    seq, model, expert, pipe = cfg.seq, cfg.model, cfg.expert, cfg.pipe
    rest = seq * model * expert * pipe
    data = cfg.data if cfg.data > 0 else n // rest
    if data * rest != n:
        raise ValueError(
            f"mesh {data}x{seq}x{model}x{expert}x{pipe} "
            f"(data x seq x model x expert x pipe) does not cover {n} devices"
        )
    if pipe > 1 and (seq > 1 or expert > 1):
        raise ValueError(
            "pipe > 1 composes with the data and model axes only (the "
            "pipeline is a partially-manual shard_map: data/pipe are "
            "mapped, model stays a GSPMD auto axis); set seq=expert=1"
        )
    arr = np.asarray(devices).reshape(data, seq, model, expert, pipe)
    return Mesh(arr, AXES)


def batch_pspecs() -> MeshBatch:
    """PartitionSpecs for a MeshBatch: batch over ``data``, mesh-point
    and function-point axes over ``seq``."""
    return MeshBatch(
        coords=P("data", "seq", None),
        theta=P("data", None),
        y=P("data", "seq", None),
        node_mask=P("data", "seq"),
        funcs=P(None, "data", "seq", None),
        func_mask=P(None, "data", "seq"),
    )


def packed_batch_pspecs():
    """PartitionSpecs for a PackedBatch: ROWS shard over ``data``; the
    slot-indexed pieces (theta, the input-function slot rows, the
    slot->segment map) replicate — segments are global ids, so the
    per-segment Gram scatter (a contraction over the sharded row axis)
    lowers to one GSPMD psum per attention, and each device gathers
    from the full replicated segment table. seq is not composed with
    packing (a segment would straddle the seq shards)."""
    from gnot_tpu.data.batch import PackedBatch

    return PackedBatch(
        coords=P("data", None, None),
        theta=P(),
        y=P("data", None, None),
        node_mask=P("data", None),
        node_seg=P("data", None),
        funcs=P(None, None, None, None),
        func_mask=P(None, None, None),
        func_seg=P(),
        n_seg=0,  # static field — not a pytree leaf, value unused here
    )


def _base_pspecs(batch):
    """Spec tree matching ``batch``'s type (MeshBatch or PackedBatch).
    For PackedBatch the static ``n_seg`` is copied over so the spec
    tree's treedef (which includes static fields) matches the batch's."""
    from gnot_tpu.data.batch import PackedBatch

    if isinstance(batch, PackedBatch):
        return packed_batch_pspecs().replace(n_seg=batch.n_seg)
    return batch_pspecs()


def stacked_batch_pspecs(base=None):
    """PartitionSpecs for a K-step stacked batch (leading step axis
    unsharded — the scan iterates it)."""
    return jax.tree.map(
        lambda spec: P(*((None,) + tuple(spec))),
        batch_pspecs() if base is None else base,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_shardings(mesh: Mesh, batch, specs=None):
    specs = _base_pspecs(batch) if specs is None else specs
    return jax.tree.map(
        lambda spec, leaf: NamedSharding(mesh, spec) if leaf is not None else None,
        specs,
        batch,
        is_leaf=lambda x: x is None or isinstance(x, P),
    )


def shard_batch(mesh: Mesh, batch, *, stacked: bool = False):
    """Host->device transfer with the batch layout applied
    (``stacked=True`` for a K-step stacked batch)."""
    specs = stacked_batch_pspecs(_base_pspecs(batch)) if stacked else None
    return jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh),
        batch,
        batch_shardings(mesh, batch, specs),
    )


def _param_pspec(path: str, leaf) -> P:
    """Name-based TP rules for the GNOT param tree.

    The embed axis E of every attention projection factors as
    [n_head, head_dim] with head leading (split_heads), so sharding E
    over ``model`` is head-parallelism. fc_out is row-parallel (its
    input axis carries E), producing the usual column->row TP pair with
    one psum at the block output. Expert-FFN hidden layers are
    column-sharded on the way in, row-sharded on the way out.

    ``blocks/`` paths are the STACKED layout (scan_layers /
    checkpoint-restored pipeline trees): a leading layer axis sits in
    front of the ordinary block param shape — same rules, spec
    prefixed with an unsharded layer dim.
    """
    if "blocks/" in path:
        inner = _param_pspec_at(path, np.ndim(leaf) - 1)
        return P(*((None,) + tuple(inner)))
    return _param_pspec_at(path, np.ndim(leaf))


def _param_pspec_at(path: str, ndim: int) -> P:
    is_kernel = path.endswith("kernel")
    if re.search(r"(query|key|value)/kernel$", path):
        return P(*([None] * (ndim - 1) + ["model"]))  # column (head) parallel
    if re.search(r"(query|key|value)/bias$", path):
        return P(*([None] * (ndim - 1) + ["model"]))
    if re.search(r"fc_out/kernel$", path):
        return P("model", None)  # row parallel -> psum
    if "experts/" in path:
        # Stacked expert MLPs [E, in, out]: the stack axis is EP, the
        # hidden axis TP. The gated combine contracts over E, so EP's
        # only collective is one psum at each FFN output.
        if is_kernel and "dense_0" in path:
            return P("expert", None, "model")
        if is_kernel:
            return P("expert", "model", None)
        if "dense_0" in path and ndim == 2:
            return P("expert", "model")
        return P(*(["expert"] + [None] * (ndim - 1)))
    if "input_func_mlps/" in path:
        # Stacked per-input-function MLPs [F, in, out]: the stack axis
        # is the (semantic) function axis — never sharded; hidden is TP.
        if is_kernel and "dense_0" in path:
            return P(None, None, "model")
        if is_kernel:
            return P(None, "model", None)
        if "dense_0" in path and ndim == 2:
            return P(None, "model")
        return P(*([None] * ndim))
    return P(*([None] * ndim))  # everything else replicated


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path
    )


def param_shardings(mesh: Mesh, params) -> Any:
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _param_pspec(_path_str(path), leaf)),
        params,
    )


def state_shardings(mesh: Mesh, state) -> Any:
    """Shardings for a full TrainState: optimizer moments follow their
    parameters (their tree paths end with the same param path), scalars
    replicate."""

    def rule(path, leaf):
        p = _path_str(path)
        if np.ndim(leaf) == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _param_pspec(p, leaf))

    return jax.tree_util.tree_map_with_path(rule, state)


def shard_state(mesh: Mesh, state):
    return jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh), state, state_shardings(mesh, state)
    )


def _validate_gspmd(model, mesh: Mesh) -> None:
    """Config-validity guards shared by every GSPMD step builder —
    clear ValueErrors at build time instead of opaque XLA partitioning
    failures mid-compile."""
    if mesh.shape.get("expert", 1) > 1 and (
        model.config.n_expert % mesh.shape["expert"]
    ):
        raise ValueError(
            f"n_expert={model.config.n_expert} must be divisible by the "
            f"mesh expert axis ({mesh.shape['expert']})"
        )
    if getattr(model.config, "ffn_impl", "xla") == "pallas":
        raise ValueError(
            "ffn_impl='pallas' is single-device/DP only (no shard_map "
            "form yet); use ffn_impl='xla' on a mesh"
        )


def make_sharded_train_step(
    model, optim_cfg, loss_name: str, mesh: Mesh, state, microbatches: int = 0,
    loss_fn=None,
):
    """jit the train step with explicit in/out shardings over the mesh.

    All communication (DP gradient psum, SP partial-sum psums inside the
    linear attention, TP collectives around the sharded GEMMs) is
    emitted by XLA from these annotations. A mesh with ``pipe > 1``
    dispatches to the explicit shard_map pipeline schedule instead
    (parallel/pipeline.py; ``microbatches`` applies there only).
    """
    from gnot_tpu.train.trainer import train_step_body

    if mesh.shape.get("pipe", 1) > 1:
        if loss_fn is not None:
            raise ValueError(
                "loss_fn overrides do not reach the pipeline path (it "
                "builds its own pipelined forward); use pipe == 1"
            )
        from gnot_tpu.parallel import pipeline

        return pipeline.make_pipelined_train_step(
            model, optim_cfg, loss_name, mesh, state, microbatches
        )
    _validate_gspmd(model, mesh)
    body = train_step_body(model, optim_cfg, loss_name, loss_fn=loss_fn)

    def step(state, batch: MeshBatch, lr):
        return body(state, (batch, lr))

    st_sh = state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step,
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),
        donate_argnums=(0,),
    )


def _reject_pipe_multi(mesh: Mesh) -> None:
    if mesh.shape.get("pipe", 1) > 1:
        raise ValueError(
            "steps_per_dispatch > 1 does not compose with the pipeline "
            "mesh path; use single-step dispatch with pipe > 1"
        )


def make_sharded_multi_train_step(
    model, optim_cfg, loss_name: str, mesh: Mesh, state, loss_fn=None
):
    """K-step scanned train step over the mesh (see
    trainer.make_multi_train_step): one dispatch, one program, all
    GSPMD collectives inside the scan body."""
    from gnot_tpu.train.trainer import train_step_body

    _reject_pipe_multi(mesh)
    _validate_gspmd(model, mesh)
    body = train_step_body(model, optim_cfg, loss_name, loss_fn=loss_fn)

    def multi_step(state, batches, lrs):
        return jax.lax.scan(body, state, (batches, lrs))

    st_sh = state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        multi_step,
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),
        donate_argnums=(0,),
    )


def make_sharded_eval_step(
    model, loss_name: str, mesh: Mesh, state, microbatches: int = 0, loss_fn=None,
    per_sample: bool = False,
):
    """jit the eval (loss-only) step over the mesh; the scalar metric
    comes back replicated. ``per_sample=True`` returns the replicated
    ``[B]`` per-graph metric vector instead (the ragged-tail eval path;
    a passed ``loss_fn`` must then itself be per-sample)."""
    from gnot_tpu.train.trainer import eval_step_body

    if mesh.shape.get("pipe", 1) > 1:
        if loss_fn is not None:
            raise ValueError(
                "loss_fn overrides do not reach the pipeline path (it "
                "builds its own pipelined forward); use pipe == 1"
            )
        from gnot_tpu.parallel import pipeline

        return pipeline.make_pipelined_eval_step(
            model, loss_name, mesh, state, microbatches, per_sample=per_sample
        )

    _validate_gspmd(model, mesh)
    p_sh = state_shardings(mesh, state).params
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        eval_step_body(model, loss_name, loss_fn=loss_fn, per_sample=per_sample),
        in_shardings=(p_sh, None),
        out_shardings=replicated,
    )


def make_sharded_multi_eval_step(model, loss_name: str, mesh: Mesh, state, loss_fn=None):
    """K eval losses over K stacked batches in one sharded dispatch."""
    from gnot_tpu.train.trainer import eval_step_body

    _reject_pipe_multi(mesh)
    _validate_gspmd(model, mesh)
    body = eval_step_body(model, loss_name, loss_fn=loss_fn)
    p_sh = state_shardings(mesh, state).params
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        lambda params, batches: jax.lax.map(lambda b: body(params, b), batches),
        in_shardings=(p_sh, None),
        out_shardings=replicated,
    )
