"""Device mesh + sharding layout: the distributed backend.

The reference has no parallelism or communication backend at all
(SURVEY.md §2 rows 9-10: single ``cuda:{id}`` device, no
torch.distributed). The TPU-native equivalent is declarative: pick a
mesh, annotate shardings, and let XLA GSPMD insert the collectives
(psum/all-gather/reduce-scatter) over ICI — nothing hand-built.

Axes of the mesh:

* ``data`` — batch sharding (DP). Gradient reduction becomes an
  implicit psum emitted by XLA.
* ``seq``  — sequence/context parallelism (SP) over mesh points. GNOT's
  linear attention shards trivially over sequence: ``k_sum`` and
  ``k^T v`` are segment-sums over L, so each shard contributes a partial
  sum and XLA inserts one psum per attention (SURVEY.md §5 long-context
  note). This is what makes Heatsink3d-scale point clouds fit.
* ``model`` — tensor parallelism (TP): attention projections are
  head-sharded (the embed axis factors as [head, head_dim] with head
  leading), expert-FFN hidden layers are column/row-sharded.

Soft-MoE note: GNOT's mixture is dense (every expert runs on every
token, no routing — reference model.py:128-130), so classic expert
parallelism with all-to-all does not apply; the expert dimension is a
batched GEMM that TP shards instead.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gnot_tpu.config import MeshConfig
from gnot_tpu.data.batch import MeshBatch

AXES = ("data", "seq", "model")


def make_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    seq, model = cfg.seq, cfg.model
    data = cfg.data if cfg.data > 0 else n // (seq * model)
    if data * seq * model != n:
        raise ValueError(
            f"mesh {data}x{seq}x{model} does not cover {n} devices"
        )
    arr = np.asarray(devices).reshape(data, seq, model)
    return Mesh(arr, AXES)


def batch_pspecs() -> MeshBatch:
    """PartitionSpecs for a MeshBatch: batch over ``data``, mesh-point
    and function-point axes over ``seq``."""
    return MeshBatch(
        coords=P("data", "seq", None),
        theta=P("data", None),
        y=P("data", "seq", None),
        node_mask=P("data", "seq"),
        funcs=P(None, "data", "seq", None),
        func_mask=P(None, "data", "seq"),
    )


def batch_shardings(mesh: Mesh, batch: MeshBatch) -> MeshBatch:
    specs = batch_pspecs()
    return jax.tree.map(
        lambda spec, leaf: NamedSharding(mesh, spec) if leaf is not None else None,
        specs,
        batch,
        is_leaf=lambda x: x is None or isinstance(x, P),
    )


def shard_batch(mesh: Mesh, batch: MeshBatch) -> MeshBatch:
    """Host->device transfer with the batch layout applied."""
    return jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh),
        batch,
        batch_shardings(mesh, batch),
    )


def _param_pspec(path: str, leaf) -> P:
    """Name-based TP rules for the GNOT param tree.

    The embed axis E of every attention projection factors as
    [n_head, head_dim] with head leading (split_heads), so sharding E
    over ``model`` is head-parallelism. fc_out is row-parallel (its
    input axis carries E), producing the usual column->row TP pair with
    one psum at the block output. Expert-FFN hidden layers are
    column-sharded on the way in, row-sharded on the way out.
    """
    ndim = np.ndim(leaf)
    is_kernel = path.endswith("kernel")
    if re.search(r"(query|key|value)/kernel$", path):
        return P(*([None] * (ndim - 1) + ["model"]))  # column (head) parallel
    if re.search(r"(query|key|value)/bias$", path):
        return P(*([None] * (ndim - 1) + ["model"]))
    if re.search(r"fc_out/kernel$", path):
        return P("model", None)  # row parallel -> psum
    if "experts/" in path or "input_func_mlps/" in path:
        # Stacked MLPs [S, in, out]: shard the hidden axis.
        if is_kernel and "dense_0" in path:
            return P(None, None, "model")
        if is_kernel:
            return P(None, "model", None)
        if "dense_0" in path and ndim == 2:
            return P(None, "model")
        return P(*([None] * ndim))
    return P(*([None] * ndim))  # everything else replicated


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path
    )


def param_shardings(mesh: Mesh, params) -> Any:
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _param_pspec(_path_str(path), leaf)),
        params,
    )


def state_shardings(mesh: Mesh, state) -> Any:
    """Shardings for a full TrainState: optimizer moments follow their
    parameters (their tree paths end with the same param path), scalars
    replicate."""

    def rule(path, leaf):
        p = _path_str(path)
        if np.ndim(leaf) == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _param_pspec(p, leaf))

    return jax.tree_util.tree_map_with_path(rule, state)


def shard_state(mesh: Mesh, state):
    return jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh), state, state_shardings(mesh, state)
    )


def make_sharded_train_step(model, optim_cfg, loss_name: str, mesh: Mesh, state):
    """jit the train step with explicit in/out shardings over the mesh.

    All communication (DP gradient psum, SP partial-sum psums inside the
    linear attention, TP collectives around the sharded GEMMs) is
    emitted by XLA from these annotations.
    """
    import optax

    from gnot_tpu.train.trainer import TrainState, batch_loss, make_optimizer

    if getattr(model.config, "ffn_impl", "xla") == "pallas":
        raise ValueError(
            "ffn_impl='pallas' is single-device/DP only (no shard_map "
            "form yet); use ffn_impl='xla' on a mesh"
        )
    if getattr(model.config, "attention_impl", "xla") == "pallas":
        # pallas_call is not GSPMD-partitionable, but the model can run
        # it distributed through shard_map when built with this mesh
        # (GNOT(cfg, mesh=mesh) -> ops/pallas_attention.fused_nla_sp).
        if getattr(model, "mesh", None) is not mesh:
            raise ValueError(
                "attention_impl='pallas' on a mesh requires the model to "
                "be constructed with that mesh (GNOT(cfg, mesh=mesh)) so "
                "attention dispatches through shard_map; or use "
                "attention_impl='xla'"
            )

    def step(state: TrainState, batch: MeshBatch, lr):
        loss, grads = jax.value_and_grad(
            lambda p: batch_loss(model, p, batch, loss_name)
        )(state.params)
        tx = make_optimizer(optim_cfg, lr)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    st_sh = state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step,
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),
        donate_argnums=(0,),
    )


def make_sharded_eval_step(model, loss_name: str, mesh: Mesh, state):
    """jit the eval (loss-only) step over the mesh; the scalar metric
    comes back replicated."""
    from gnot_tpu.train.trainer import batch_loss

    p_sh = state_shardings(mesh, state).params
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        lambda params, batch: batch_loss(model, params, batch, loss_name),
        in_shardings=(p_sh, None),
        out_shardings=replicated,
    )
