"""Pipeline parallelism: a shard_map microbatch pipeline over the
attention-block stack.

The reference has no parallelism of any kind (SURVEY.md §2 rows 9-10);
this is part of the TPU-native scale-out surface, alongside the GSPMD
axes in ``parallel/mesh.py``. Unlike DP/SP/TP/EP — which are sharding
*annotations* that XLA GSPMD turns into collectives — a pipeline is a
*schedule*, so it is written explicitly with ``jax.shard_map``:

* the per-block parameter trees are stacked along a leading layer axis
  and that axis is sharded over the mesh ``pipe`` axis — each device
  holds ``n_attn_layers / pipe`` consecutive blocks (one stage);
* the (embedded) batch is split into M microbatches; the classic
  ``M + S - 1``-tick schedule runs: at tick t, stage s processes
  microbatch ``t - s`` and hands its output to stage ``s+1`` with a
  single ``ppermute`` hop over ICI. Only the running query activation
  travels; scores / input functions / masks are read locally by
  microbatch index. The pipeline bubble is the usual
  ``(S-1) / (M+S-1)`` fraction of ticks;
* the embedding head (gating + query/function embeds) and the output
  MLP run outside the pipeline as plain GSPMD-sharded compute (they
  are a few percent of FLOPs).

Everything is differentiable (``ppermute`` transposes to the inverse
permute inside ``lax.scan``), so the same schedule serves forward and
backward; the backward pass replays the ring in reverse.

The pipeline composes with the ``data`` axis (each data shard runs its
own pipeline over the same stage devices) and with the ``model`` axis:
the shard_map maps ``data``/``pipe`` manually while ``model`` stays an
XLA GSPMD *auto* axis, so tensor parallelism inside a stage is the
ordinary sharding-annotation kind (state_shardings puts heads / FFN
hidden over ``model``; GSPMD inserts the psums). Requires
``seq == expert == 1``, ``ffn_impl == 'xla'``, and
``n_attn_layers % pipe == 0``.

Parameter layout: pipeline states store the block stack under
``params["blocks"]`` (leading layer axis, pipe-sharded) instead of the
standard ``block_i`` subtrees; ``stack_params`` / ``unstack_params``
convert. All other entries (gating, x_embed, input_func_mlps, out_mlp)
are identical to the standard layout, and the module math is the exact
GNOT forward (models/gnot.py) — the tests assert the pipelined step
matches the single-device step to float tolerance.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gnot_tpu.config import ModelConfig, OptimConfig
from gnot_tpu.data.batch import MeshBatch

Array = jax.Array


# ---------------------------------------------------------------------------
# Parameter layout


def stack_params(params: dict, n_layers: int) -> dict:
    """Standard GNOT param tree -> pipeline layout: the ``block_i``
    subtrees become one ``blocks`` tree with a leading layer axis."""
    out = {k: v for k, v in params.items() if not k.startswith("block_")}
    blocks = [params[f"block_{i}"] for i in range(n_layers)]
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return out


def unstack_params(params: dict, n_layers: int) -> dict:
    """Pipeline layout -> standard GNOT param tree (for predict /
    checkpoint interop / torch export)."""
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(n_layers):
        out[f"block_{i}"] = jax.tree.map(lambda x, i=i: x[i], params["blocks"])
    return out


def convert_state_layout(state, n_layers: int, to: str):
    """Convert a full TrainState between the standard ``block_i`` layout
    and the stacked ``blocks`` layout — INCLUDING the optimizer moments,
    whose trees mirror the params — so a checkpoint written by a
    ``--scan_layers`` / ``--mesh_pipe`` run can be resumed by a standard
    run and vice versa. Operates on host/device values (pipe-sharded
    states should be ``jax.device_get`` first). No-op if already in the
    target layout."""
    if to not in ("stacked", "standard"):
        raise ValueError(f"unknown layout {to!r}")

    def rule(node):
        if isinstance(node, dict):
            if to == "stacked" and "block_0" in node:
                return stack_params(node, n_layers)
            if to == "standard" and "blocks" in node:
                return unstack_params(node, n_layers)
        return None

    from gnot_tpu.train.trainer import map_state_containers

    return map_state_containers(state, rule)


# ---------------------------------------------------------------------------
# Model pieces: standalone applications of the SAME module factories
# GNOT.__call__ composes (models/gnot.py) against the corresponding
# param subtrees — hyperparameters and math cannot drift between the
# standard and pipelined forwards.


def _embed(cfg: ModelConfig, params: dict, coords, theta, input_functions):
    """Gating scores + query embedding + input-function embeddings —
    the pre-pipeline part of GNOT.__call__."""
    from gnot_tpu.models import gnot

    scores = gnot.gating_scores(
        gnot.gating_module(cfg).apply({"params": params["gating"]}, coords)
    )
    query = gnot.x_embed_module(cfg).apply(
        {"params": params["x_embed"]}, gnot.query_features(coords, theta)
    )
    if cfg.n_input_functions > 0 and input_functions is not None:
        funcs = gnot.func_embed_module(cfg).apply(
            {"params": params["input_func_mlps"]}, input_functions
        )
    else:
        funcs = None
    return scores, query, funcs


def _head(cfg: ModelConfig, params: dict, query):
    from gnot_tpu.models import gnot

    return gnot.finalize_output(
        gnot.out_module(cfg).apply(
            {"params": params["out_mlp"]}, gnot.finalize_input(query)
        )
    )


# ---------------------------------------------------------------------------
# The pipeline schedule


def _split_micro(x, m: int, batch_axis: int):
    """[..., B, ...] -> [M, ..., B/M, ...]: carve the batch axis into M
    microbatches and move the microbatch index to the front."""
    if x is None:
        return None
    shape = list(x.shape)
    b = shape[batch_axis]
    if b % m:
        raise ValueError(
            f"local batch {b} must be divisible by microbatches={m}"
        )
    new = shape[:batch_axis] + [m, b // m] + shape[batch_axis + 1 :]
    return jnp.moveaxis(x.reshape(new), batch_axis, 0)


def _scan_blocks(cfg, block, stacked, scores, query, funcs, node_mask, func_mask):
    """lax.scan of one block module over stacked per-layer params — THE
    one block-application loop (the pipeline's per-stage compute and the
    scan_layers forward both call this, so remat policy and block
    wiring cannot drift between them)."""

    def body(q, layer_p):
        apply = lambda qq: block.apply(
            {"params": layer_p}, scores, qq, funcs,
            node_mask=node_mask, func_mask=func_mask,
        )
        if cfg.remat:
            apply = jax.checkpoint(apply)
        return apply(q), None

    q, _ = jax.lax.scan(body, query, stacked)
    return q


def _pipe_blocks(
    cfg: ModelConfig,
    mesh: Mesh,
    n_micro: int,
    stacked,
    scores,
    query,
    funcs,
    node_mask,
    func_mask,
):
    """Run the block stack as an S-stage, M-microbatch pipeline.

    Inputs are globally shaped; the shard_map carves the batch over
    ``data`` and the layer axis of ``stacked`` over ``pipe``.
    """
    from gnot_tpu.models import gnot

    s_pipe = mesh.shape["pipe"]
    block = gnot.block_module(cfg, funcs is not None)

    def local_fn(stacked_local, scores, query, funcs, node_mask, func_mask):
        m = n_micro
        t_total = m + s_pipe - 1
        s_idx = jax.lax.axis_index("pipe")

        scores_m = _split_micro(scores, m, 0)
        query_m = _split_micro(query, m, 0)
        funcs_m = _split_micro(funcs, m, 1)
        nm_m = _split_micro(node_mask, m, 0)
        fm_m = _split_micro(func_mask, m, 1)

        def run_stage(sc, q, f, nm, fm):
            return _scan_blocks(cfg, block, stacked_local, sc, q, f, nm, fm)

        def tick(carry, t):
            q_state, outputs = carry
            # Microbatch resident at stage s this tick (clipped during
            # warmup/drain; those lanes compute garbage that is never
            # collected).
            idx = jnp.clip(t - s_idx, 0, m - 1)
            sc = scores_m[idx]
            f = None if funcs_m is None else funcs_m[idx]
            nm = None if nm_m is None else nm_m[idx]
            fm = None if fm_m is None else fm_m[idx]
            # Stage 0 ingests a fresh microbatch; later stages take the
            # previous stage's handoff.
            q_in = jnp.where(s_idx == 0, query_m[jnp.clip(t, 0, m - 1)], q_state)
            q_out = run_stage(sc, q_in, f, nm, fm)

            out_idx = t - (s_pipe - 1)
            valid = (s_idx == s_pipe - 1) & (out_idx >= 0)
            upd = jax.lax.dynamic_update_index_in_dim(
                outputs, q_out, jnp.clip(out_idx, 0, m - 1), 0
            )
            outputs = jnp.where(valid, upd, outputs)

            # One ICI hop: stage s -> s+1 (the wraparound into stage 0
            # is discarded — stage 0 always re-ingests).
            q_next = jax.lax.ppermute(
                q_out, "pipe", [(i, (i + 1) % s_pipe) for i in range(s_pipe)]
            )
            return (q_next, outputs), None

        q0 = query_m[0]
        outputs0 = jnp.zeros_like(query_m)
        (_, outputs), _ = jax.lax.scan(
            tick, (q0, outputs0), jnp.arange(t_total)
        )
        # Collected outputs live on the last stage only; make them
        # pipe-replicated (one broadcast — everything else in the
        # schedule moved exactly one microbatch activation per tick).
        outputs = jax.lax.psum(
            jnp.where(s_idx == s_pipe - 1, outputs, jnp.zeros_like(outputs)),
            "pipe",
        )
        return outputs.reshape(query.shape)

    in_specs = [
        jax.tree.map(lambda _: P("pipe"), stacked),
        P("data", None, None),  # scores [B, L, E]
        P("data", None, None),  # query  [B, L, D]
        None if funcs is None else P(None, "data", None, None),
        None if node_mask is None else P("data", None),
        None if func_mask is None else P(None, "data", None),
    ]
    # Partially-manual shard_map: data/pipe are MAPPED (the schedule is
    # explicit), every other mesh axis stays an XLA GSPMD "auto" axis —
    # in particular ``model``, so tensor parallelism inside a stage is
    # the ordinary sharding-annotation kind (state_shardings puts heads
    # / FFN hidden over model and GSPMD inserts the psums).
    from gnot_tpu.ops.collectives import shard_map

    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P("data", None, None),
        axis_names={"data", "pipe"},
        check_vma=False,
    )
    return mapped(stacked, scores, query, funcs, node_mask, func_mask)


def stacked_forward(cfg: ModelConfig, params: dict, batch: MeshBatch):
    """Full GNOT forward with the block stack as ONE ``lax.scan`` over
    stacked per-layer params (the pipeline parameter layout, no mesh
    schedule): XLA traces and compiles a single block regardless of
    ``n_attn_layers`` — the compile-time lever for deep configs
    (``ModelConfig.scan_layers``). Same math as GNOT.__call__ (the
    block module comes from the same factory); works standalone or
    under a GSPMD-sharded jit (mesh._param_pspec knows the stacked
    ``blocks/`` layout)."""
    from gnot_tpu.models import gnot

    node_mask, func_mask = batch.node_mask, batch.func_mask
    if cfg.attention_mode == "parity":
        node_mask = func_mask = None
    with gnot.precision_scope(cfg):
        scores, query, funcs = _embed(
            cfg, params, batch.coords, batch.theta, batch.funcs
        )
        block = gnot.block_module(cfg, funcs is not None)
        query = _scan_blocks(
            cfg, block, params["blocks"], scores, query, funcs, node_mask, func_mask
        )
        return _head(cfg, params, query)


def init_stacked_state(model, optim_cfg: OptimConfig, sample_batch, seed: int):
    """Stacked-layout TrainState for ``scan_layers`` (no mesh; GSPMD
    callers shard it afterwards with mesh.shard_state, whose param
    rules understand the ``blocks`` stack)."""
    from gnot_tpu.train.trainer import TrainState, init_state, make_optimizer

    base = init_state(model, optim_cfg, sample_batch, seed)
    params = stack_params(base.params, model.config.n_attn_layers)
    tx = make_optimizer(optim_cfg, optim_cfg.lr)
    return TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )


def pipelined_forward(
    cfg: ModelConfig, mesh: Mesh, n_micro: int, params: dict, batch: MeshBatch
):
    """Full GNOT forward with the block stack pipelined (params in
    pipeline layout)."""
    from gnot_tpu.models import gnot

    node_mask, func_mask = batch.node_mask, batch.func_mask
    if cfg.attention_mode == "parity":
        node_mask = func_mask = None
    with gnot.precision_scope(cfg):
        scores, query, funcs = _embed(
            cfg, params, batch.coords, batch.theta, batch.funcs
        )
        query = _pipe_blocks(
            cfg, mesh, n_micro, params["blocks"], scores, query, funcs,
            node_mask, func_mask,
        )
        return _head(cfg, params, query)


# ---------------------------------------------------------------------------
# Train / eval steps and state layout


def _validate(cfg: ModelConfig, mesh: Mesh):
    s = mesh.shape["pipe"]
    if cfg.attention_impl != "xla" or cfg.ffn_impl != "xla":
        raise ValueError(
            "pipeline parallelism supports the xla attention/ffn impls only"
        )
    if cfg.n_attn_layers % s:
        raise ValueError(
            f"n_attn_layers={cfg.n_attn_layers} must be divisible by the "
            f"mesh pipe axis ({s})"
        )
    if any(mesh.shape[a] > 1 for a in ("seq", "expert")):
        raise ValueError(
            "pipe > 1 composes with data and model only; seq == expert == 1"
        )


def validate_local_batch(
    mesh: Mesh, per_host_batch_size: int, microbatches: int, n_process: int = 1
):
    """Fail at startup (not mid-epoch) if a per-host batch can't split
    into this host's data shards x microbatches. The mesh ``data`` axis
    is GLOBAL (hosts x per-host on hybrid meshes), so the per-host data
    degree is ``data / n_process``."""
    micro = resolve_microbatches(mesh, microbatches)
    local_data = max(1, mesh.shape["data"] // max(1, n_process))
    per_shard = per_host_batch_size // local_data
    if per_host_batch_size % local_data or per_shard % micro:
        raise ValueError(
            f"batch_size={per_host_batch_size} (per host) must split into "
            f"the per-host data axis ({local_data}) x microbatches ({micro})"
        )


def resolve_microbatches(mesh: Mesh, microbatches: int) -> int:
    """0 (the documented auto value) -> one microbatch per stage
    (bubble = (S-1)/(2S-1)); negatives are rejected rather than silently
    coerced."""
    if microbatches < 0:
        raise ValueError(f"microbatches must be >= 0, got {microbatches}")
    return microbatches if microbatches > 0 else mesh.shape["pipe"]


def state_shardings(mesh: Mesh, state) -> Any:
    """Pipeline-layout state: the ``blocks`` stack (and its optimizer
    moments, whose paths mirror the params) shards its layer axis over
    ``pipe`` and its inner block axes by the standard TP rules (heads /
    FFN hidden over ``model`` — mesh._param_pspec_at, the ONE copy of
    those rules); everything outside the stack takes the plain GSPMD
    rules (mesh._param_pspec), so embeds/head TP compose too."""
    from gnot_tpu.parallel.mesh import _param_pspec, _param_pspec_at, _path_str

    def rule(path, leaf):
        if np.ndim(leaf) == 0:
            return NamedSharding(mesh, P())
        p = _path_str(path)
        keys = p.split("/")
        if "blocks" in keys:
            sub = p[p.index("blocks/") + len("blocks/"):] if "blocks/" in p else ""
            inner = _param_pspec_at(sub, np.ndim(leaf) - 1)
            return NamedSharding(mesh, P(*(("pipe",) + tuple(inner))))
        return NamedSharding(mesh, P(*_param_pspec(p, leaf)))

    return jax.tree_util.tree_map_with_path(rule, state)


def init_pipeline_state(model, optim_cfg: OptimConfig, sample_batch, seed: int, mesh: Mesh):
    """Build a pipeline-layout TrainState, sharded over the mesh.

    The optimizer state is initialized fresh on the stacked tree (it is
    all zeros + a counter at step 0, so this is identical to stacking a
    standard init)."""
    # Validate up front so e.g. n_attn_layers % pipe != 0 surfaces as the
    # intended ValueError here, not as an uneven-sharding device_put error.
    _validate(model.config, mesh)
    state = init_stacked_state(model, optim_cfg, sample_batch, seed)
    return jax.tree.map(
        lambda leaf, sh: jax.device_put(leaf, sh), state, state_shardings(mesh, state)
    )


def make_pipelined_train_step(
    model, optim_cfg: OptimConfig, loss_name: str, mesh: Mesh, state, microbatches: int = 0
):
    """jit'd train step whose forward pipelines the block stack. The
    ``state`` must be in pipeline layout (init_pipeline_state)."""
    from gnot_tpu.ops.segment import LOSSES
    from gnot_tpu.train.trainer import train_step_body

    if "blocks" not in state.params:
        raise ValueError(
            "pipeline train step needs a pipeline-layout state "
            "(init_pipeline_state), not the standard block_i layout"
        )
    n_micro = resolve_microbatches(mesh, microbatches)
    _validate(model.config, mesh)
    cfg = model.config

    # The shared step math with the shard_map pipeline substituted as
    # the forward.
    body = train_step_body(
        model,
        optim_cfg,
        loss_name,
        loss_fn=lambda params, batch: LOSSES[loss_name](
            pipelined_forward(cfg, mesh, n_micro, params, batch),
            batch.y,
            batch.node_mask,
        ),
    )

    def step(state, batch: MeshBatch, lr):
        return body(state, (batch, lr))

    st_sh = state_shardings(mesh, state)
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step,
        in_shardings=(st_sh, None, replicated),
        out_shardings=(st_sh, replicated),
        donate_argnums=(0,),
    )


def make_pipelined_eval_step(
    model, loss_name: str, mesh: Mesh, state, microbatches: int = 0,
    per_sample: bool = False,
):
    from gnot_tpu.ops.segment import LOSSES, PER_SAMPLE_LOSSES

    if "blocks" not in state.params:
        raise ValueError(
            "pipeline eval step needs a pipeline-layout state "
            "(init_pipeline_state), not the standard block_i layout"
        )
    n_micro = resolve_microbatches(mesh, microbatches)
    _validate(model.config, mesh)
    cfg = model.config
    p_sh = state_shardings(mesh, state).params
    replicated = NamedSharding(mesh, P())
    table = PER_SAMPLE_LOSSES if per_sample else LOSSES

    def eval_fn(params, batch: MeshBatch):
        preds = pipelined_forward(cfg, mesh, n_micro, params, batch)
        return table[loss_name](preds, batch.y, batch.node_mask)

    return jax.jit(eval_fn, in_shardings=(p_sh, None), out_shardings=replicated)
