"""Multi-host (multi-process / DCN) scale-out.

The reference is strictly single-process, single-device — no
NCCL/MPI/torch.distributed anywhere (SURVEY.md §2 rows 9-10, §5). The
TPU-native scale-out story is JAX's multi-controller runtime: one
process per host, ``jax.distributed.initialize`` for the coordination
service, and ONE global mesh spanning every chip; jitted code is
identical to single-host — XLA routes collectives over ICI inside a
slice and DCN across slices.

Layout policy (the scaling-book recipe): put **data parallelism on the
DCN axis** — the only cross-host collective is then the gradient psum,
once per step, which DCN bandwidth handles — and keep SP/TP, whose
collectives are per-layer, inside the ICI domain. ``make_hybrid_mesh``
encodes exactly that: the leading ``data`` axis is (hosts x local-data),
``seq``/``model`` never cross a host boundary.

Data feeding is per-host: each process loads only its shard of the
samples (``shard_samples``) and assembles globally-sharded device arrays
from process-local batches (``global_batch``) via
``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding

from gnot_tpu.config import MeshConfig
from gnot_tpu.data.batch import MeshBatch
from gnot_tpu.parallel.mesh import AXES, batch_pspecs, make_mesh


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-controller runtime.

    With no arguments, attempts ``jax.distributed.initialize()``'s
    environment auto-detection (TPU pods, SLURM, Open MPI); if the
    process is not part of a managed multi-process job the attempt
    fails and this degrades to a single-process no-op, so drivers can
    call it unconditionally. If the environment LOOKS like a managed
    multi-process job (SLURM/Open MPI/TPU-pod env vars present) the
    failure re-raises instead: silently degrading there would launch p
    duplicate single-process trainings racing on the same checkpoint
    and metrics paths."""
    if _already_initialized():
        return  # a driver (or test harness) brought the runtime up itself
    if (
        coordinator_address is None
        and num_processes is None
        and process_id is None
    ):
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError) as exc:
            managed = _managed_job_hint()
            if managed:
                raise RuntimeError(
                    f"jax.distributed auto-detection failed but the "
                    f"environment advertises a multi-process job "
                    f"({managed}); refusing to degrade to p independent "
                    f"single-process runs"
                ) from exc
            import logging

            logging.getLogger(__name__).warning(
                "jax.distributed.initialize() auto-detection failed (%s); "
                "continuing single-process",
                exc,
            )
            return
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _already_initialized() -> bool:
    """Whether the jax.distributed runtime is already up (a driver may
    legitimately initialize it before calling into this framework)."""
    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:  # older jax without the public predicate
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None


def _managed_job_hint() -> str | None:
    """Name the env evidence of a multi-process job, or None."""
    import os

    ntasks = os.environ.get("SLURM_NTASKS")
    if ntasks and int(ntasks) > 1:
        return f"SLURM_NTASKS={ntasks}"
    world = os.environ.get("OMPI_COMM_WORLD_SIZE")
    if world and int(world) > 1:
        return f"OMPI_COMM_WORLD_SIZE={world}"
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES")
    if hosts and "," in hosts:
        return f"TPU_WORKER_HOSTNAMES={hosts}"
    return None


def make_hybrid_mesh(cfg: MeshConfig) -> Mesh:
    """Global ``data x seq x model`` mesh over all hosts.

    ``cfg.data`` is the TOTAL data-parallel degree (same meaning as
    ``make_mesh`` / ``--mesh_data``), factored as hosts x per-host; the
    host factor rides DCN, seq/model stay inside each host's ICI
    domain. Single-process runs degenerate to ``make_mesh``."""
    n_proc = jax.process_count()
    if n_proc == 1:
        return make_mesh(cfg)
    from jax.experimental import mesh_utils

    local = jax.local_device_count()
    rest = cfg.seq * cfg.model * cfg.expert * cfg.pipe
    if local % rest:
        raise ValueError(
            f"seq*model*expert*pipe={rest} must divide the {local} "
            "local devices (SP/TP/EP/PP must not cross hosts)"
        )
    if cfg.data > 0:
        if cfg.data % n_proc:
            raise ValueError(
                f"total data degree {cfg.data} must be divisible by the "
                f"{n_proc} processes"
            )
        ici_data = cfg.data // n_proc
    else:
        ici_data = local // rest
    if ici_data * rest != local:
        raise ValueError(
            f"per-host mesh {ici_data}x{cfg.seq}x{cfg.model}x{cfg.expert}"
            f"x{cfg.pipe} does not cover {local} local devices"
        )
    slices = {getattr(d, "slice_index", None) for d in jax.devices()}
    if slices != {None} and len(slices) > 1:
        # Real multi-slice topology: the hybrid builder knows the
        # ICI/DCN layout. DCN granularity is SLICES (a slice may span
        # several processes), so the data axis factors as
        # n_slices x per-slice. Its errors are informative — let them
        # raise.
        n_slices = len(slices)
        total_data = ici_data * n_proc
        if total_data % n_slices:
            raise ValueError(
                f"total data degree {total_data} must be divisible by "
                f"the {n_slices} slices"
            )
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(
                total_data // n_slices, cfg.seq, cfg.model, cfg.expert, cfg.pipe,
            ),
            dcn_mesh_shape=(n_slices, 1, 1, 1, 1),
        )
    else:
        # Devices that don't advertise DCN slices (CPU fleets,
        # single-slice topologies) reject the hybrid builder. Build the
        # same layout by hand: host-major data axis, each host's local
        # block shaped (local_data, seq, model) so seq/model never
        # leave a host.
        import numpy as np

        by_proc: dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        blocks = [
            np.asarray(sorted(v, key=lambda d: d.id)).reshape(
                ici_data, cfg.seq, cfg.model, cfg.expert, cfg.pipe
            )
            for _, v in sorted(by_proc.items())
        ]
        devices = np.concatenate(blocks, axis=0)
    return Mesh(devices, AXES)


def shard_samples(
    samples: Sequence,
    *,
    process_index: int | None = None,
    process_count: int | None = None,
) -> list:
    """This host's strided shard of the dataset (every host must call
    with the same ``samples`` order — seed the shuffle identically)."""
    i = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return list(samples)[i::n]


def per_host_gauge(value: float) -> "np.ndarray":
    """Allgather one host-local float into a ``[process_count]`` f32
    array in process order — the straggler gauge primitive (each host
    contributes its step/epoch wall-time; process 0 logs the max-min
    skew). COLLECTIVE: every process must call it together, whether or
    not it owns a metrics sink. Single-process returns ``[value]``."""
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray([value], np.float32)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(np.asarray(value, np.float32))
    )


def sync_flag(value: bool) -> bool:
    """All-reduce OR of one host-local boolean across processes — the
    preemption-coordination primitive (resilience/preemption.py): a
    SIGTERM landing on ONE host must stop EVERY host on the same step,
    or the survivors hang in the next collective. COLLECTIVE: every
    process must call it together (the SPMD dispatch loop guarantees
    the cadence). Single-process returns ``value`` without
    communicating."""
    if jax.process_count() == 1:
        return bool(value)
    return bool(per_host_gauge(float(bool(value))).max() > 0)


def all_agree(token: str) -> bool:
    """Whether every process holds the same ``token`` — the checkpoint
    fallback walk's guard (train/checkpoint.py): the collective orbax
    restore deadlocks if hosts attempt DIFFERENT candidate directories
    (per-host transient I/O can desynchronize the walk), so each
    candidate is agreed on before the restore and a divergence fails
    loudly instead of hanging the pod. COLLECTIVE: every process must
    call it together. Single-process returns True."""
    if jax.process_count() == 1:
        return True
    import hashlib

    import numpy as np
    from jax.experimental import multihost_utils

    digest = np.frombuffer(
        hashlib.md5(token.encode()).digest(), np.uint8
    ).copy()
    gathered = np.asarray(multihost_utils.process_allgather(digest))
    return bool((gathered == gathered.reshape(-1, 16)[0]).all())


def global_batch(
    mesh: Mesh, local_batch: MeshBatch, *, stacked: bool = False
) -> MeshBatch:
    """Assemble a globally-sharded MeshBatch from this process's local
    batch (the batch axis concatenates across hosts in process order).
    ``stacked=True`` for K-step stacked batches (leading step axis)."""
    from gnot_tpu.parallel.mesh import stacked_batch_pspecs

    specs = stacked_batch_pspecs() if stacked else batch_pspecs()

    def put(spec, leaf):
        if leaf is None:
            return None
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), leaf
        )

    return jax.tree.map(
        put,
        specs,
        local_batch,
        is_leaf=lambda x: x is None or not isinstance(x, MeshBatch),
    )
