"""The recovery supervisor: rolling on-device snapshots + the
rollback → checkpoint-restore → abort ladder.

Failure model: a single bad step (NaN gradients from an overflow, a
corrupt batch, a flaky chip) poisons the params, and every later step
is garbage — training past it burns chips. Before this subsystem the
first detected NaN hard-aborted the run (trainer.py's watchdog). The
supervisor instead keeps a last-known-good copy of the TrainState on
device (refreshed every ``train.snapshot_every`` steps, after a
finiteness check of every loss since the previous snapshot — so a
snapshot is never taken from poisoned state), and on detection:

1. **rollback** — restore the snapshot, quarantine the offending
   dispatch (it is skipped on replay; the loader's seeded order makes
   the replay deterministic), re-run the ≤ K lost steps. Bounded by
   ``train.max_rollbacks`` per run.
2. **checkpoint restore** — budget exhausted (or no clean snapshot):
   restore ``latest``/``best`` via the hardened Checkpointer walk and
   re-enter the epoch loop at the restored epoch. Used at most once.
3. **abort** — the current behavior: localize the op via checkify when
   a batch is in hand, write the ``non_finite_loss`` event, raise.

Detection is the cheapest sufficient signal: one ``device_get`` of the
K loss scalars per snapshot window (the same cadence discipline as the
telemetry buffer — no per-step syncs). The telemetry NaN watchdog and
``--debug_checks``, when enabled, feed the same ladder through
``NonFiniteLossError``. Multi-host runs need no extra coordination:
losses are replicated, so every host detects the same step and rolls
back identically (SPMD all the way down).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


class NonFiniteLossError(FloatingPointError):
    """A detected non-finite training loss, carrying enough context to
    recover: the step/epoch, the dispatch ordinal within the epoch (when
    known), and the offending host batch (when retained). Subclasses
    FloatingPointError so pre-recovery callers and tests that catch the
    hard abort keep working unchanged."""

    def __init__(
        self, message: str, *, step: int, epoch: int,
        ordinal: int | None = None, batch: Any = None,
    ):
        super().__init__(message)
        self.step = step
        self.epoch = epoch
        self.ordinal = ordinal
        self.batch = batch


class PreemptionRequested(Exception):
    """Raised at a step boundary when a stop was requested (SIGTERM/
    SIGINT or injected); the trainer saves ``latest`` and exits
    resume-ready."""

    def __init__(self, epoch: int, step: int):
        super().__init__(f"preemption requested at epoch {epoch}, step {step}")
        self.epoch = epoch
        self.step = step


class RestoreEscalation(Exception):
    """Internal: the ladder escalates past device rollback; the outer
    epoch loop restores from checkpoint (or aborts)."""

    def __init__(self, cause: NonFiniteLossError):
        super().__init__(str(cause))
        self.cause = cause


@dataclasses.dataclass
class _Snapshot:
    state: Any  # device copy of the TrainState
    ordinal: int  # dispatches completed in this epoch at snapshot time
    host_step: int
    n_losses: int
    points: int


def _copy_state(state):
    """Device-side copy: the live state's buffers get donated away by
    the next dispatch, so the snapshot must own distinct buffers (the
    copy is async — no host sync here)."""
    return jax.tree.map(jnp.copy, state)


class RecoverySupervisor:
    def __init__(self, *, snapshot_every: int = 50, max_rollbacks: int = 3):
        # Bounds validated at config construction (TrainConfig
        # __post_init__) — the one place every in-repo path goes through.
        self.snapshot_every = max(1, snapshot_every)
        self.max_rollbacks = max_rollbacks
        self.rollbacks_used = 0
        self.restore_used = False
        self._snap: _Snapshot | None = None
        self._dispatch_log: list[tuple[int, int, int]] = []  # (ordinal, start, end)
        self._checked = 0  # losses verified finite so far this epoch
        self._last_snap_step = 0

    # -- epoch lifecycle ---------------------------------------------------

    def begin_epoch(self, state, *, host_step: int) -> None:
        """Snapshot the epoch-entry state (always a legal rollback
        target) and reset the per-epoch dispatch log."""
        self._snap = _Snapshot(
            state=_copy_state(state), ordinal=0, host_step=host_step,
            n_losses=0, points=0,
        )
        self._dispatch_log = []
        self._checked = 0
        self._last_snap_step = host_step

    def after_dispatch(
        self, state, *, ordinal: int, start_step: int, end_step: int,
        losses: list, points: int, epoch: int,
    ) -> None:
        """Record the dispatch; at each ``snapshot_every`` boundary,
        verify every loss since the last check is finite (one
        device_get of ≤ K scalars) and refresh the snapshot. Raises
        NonFiniteLossError on the first bad loss — BEFORE snapshotting,
        so the held snapshot is always pre-poisoning."""
        self._dispatch_log.append((ordinal, start_step, end_step))
        if end_step - self._last_snap_step < self.snapshot_every:
            return
        self.check_losses(losses, epoch=epoch)
        self._snap = _Snapshot(
            state=_copy_state(state), ordinal=ordinal + 1,
            host_step=end_step, n_losses=len(losses), points=points,
        )
        self._last_snap_step = end_step

    def check_losses(self, losses: list, *, epoch: int) -> None:
        """Finiteness-check the unchecked tail of the epoch's per-
        dispatch losses (the trainer also calls this at epoch end so a
        NaN in the final partial window cannot reach eval)."""
        tail = losses[self._checked :]
        if not tail:
            return
        fetched = jax.device_get(tail)
        for i, loss in enumerate(fetched):
            arr = np.atleast_1d(np.asarray(loss))
            bad = ~np.isfinite(arr)
            if bad.any():
                ordinal, start, _ = self._dispatch_log[self._checked + i]
                step = start + int(np.argmax(bad)) + 1
                raise NonFiniteLossError(
                    f"non-finite train loss at epoch {epoch}, step {step}",
                    step=step, epoch=epoch, ordinal=ordinal,
                )
        self._checked = len(losses)

    def ordinal_for_step(self, step: int) -> int | None:
        """Map a step number (e.g. from the telemetry watchdog) to its
        dispatch ordinal in the current epoch's log."""
        for ordinal, start, end in self._dispatch_log:
            if start < step <= end:
                return ordinal
        return None

    # -- the ladder --------------------------------------------------------

    def plan(self, err: NonFiniteLossError) -> str:
        """Choose the next rung for this failure: ``"rollback"`` while
        snapshot + budget allow, else ``"restore"`` once, else
        ``"abort"``."""
        if self._snap is not None and self.rollbacks_used < self.max_rollbacks:
            return "rollback"
        if not self.restore_used:
            self.restore_used = True
            return "restore"
        return "abort"

    def last_good_state(self):
        """A copy of the last-known-good snapshot state (or None) — the
        preemption save's fallback when the final telemetry drain
        reveals a NaN buried in the un-drained window: checkpointing
        the live (possibly poisoned) state would strand the resume."""
        return None if self._snap is None else _copy_state(self._snap.state)

    def rollback(self) -> _Snapshot:
        """Consume one budget unit and hand back a COPY of the
        snapshot (the returned state's buffers will be donated by the
        replayed steps; the held snapshot must survive a second
        rollback). Truncates the dispatch log/checked counter to the
        snapshot point."""
        assert self._snap is not None
        self.rollbacks_used += 1
        snap = self._snap
        self._dispatch_log = [
            d for d in self._dispatch_log if d[0] < snap.ordinal
        ]
        self._checked = min(self._checked, snap.n_losses)
        self._last_snap_step = snap.host_step
        return dataclasses.replace(snap, state=_copy_state(snap.state))
