"""Fault injection + automatic recovery (ROADMAP north star: a
production-scale TPU stack survives the failures TPU fleets actually
have — preemptions, NaN steps, corrupted or flaky checkpoint storage —
instead of stranding hours of pod time on the first one).

Four pieces, composing with the obs/ subsystem (every recovery action
becomes a sink event — docs/robustness.md and docs/observability.md):

* ``faults`` — a deterministic fault-injection framework driven by the
  ``train.inject_fault`` config spec (``--inject_fault``): NaN
  gradients or bad data samples at step k, SIGTERM at step k,
  transient checkpoint-I/O errors, corrupted/truncated checkpoint
  directories, clean stop after epoch N — plus the serve-side kinds
  (``serve.inject_fault``: slow requests, NaN outputs, reload-racing
  corruption) consumed by ``gnot_tpu/serve`` (docs/serving.md). Every
  recovery path is thereby testable on CPU (tests/test_resilience.py
  and tests/test_serve.py, the chaos suites).
* ``supervisor`` — the recovery ladder wired into ``Trainer.fit``: a
  rolling last-good on-device snapshot every ``train.snapshot_every``
  steps; a watchdog-detected non-finite loss rolls back to it,
  quarantines the offending dispatch, and continues — under a bounded
  budget (``train.max_rollbacks``) that escalates to checkpoint
  restore and then to the hard abort with the localized-op report.
* ``preemption`` — graceful SIGTERM/SIGINT handling: stop at the next
  step boundary (coordinated across hosts so multi-host runs stop on
  the same step), save ``latest``, flush the sink, exit resume-ready.
* ``retry`` — exponential backoff + jitter around checkpoint and
  dataset I/O.
"""

from gnot_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    InjectedIOError,
    corrupt_checkpoint,
    corrupt_published,
    parse_fault_spec,
)
from gnot_tpu.resilience.preemption import PreemptionHandler  # noqa: F401
from gnot_tpu.resilience.retry import RetryPolicy, retry_io  # noqa: F401
from gnot_tpu.resilience.supervisor import (  # noqa: F401
    NonFiniteLossError,
    PreemptionRequested,
    RecoverySupervisor,
)
