"""Exponential-backoff retries for transient I/O.

Checkpoint storage on TPU fleets is remote (GCS/NFS) and flakes:
transient 5xx/ESTALE-class errors on save or restore must not kill an
hours-long run when a 1-second retry would succeed. The policy is the
standard full-jitter exponential backoff (delay_i = uniform(0, min(cap,
base * 2**i))) — jitter decorrelates the retry storms of many hosts
hitting the same flaky filesystem together.

Defaults (documented in docs/robustness.md): 3 retries, base 0.5 s,
cap 8 s. Deterministic callers (tests) pass ``sleep=lambda s: None``.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Iterable

logger = logging.getLogger(__name__)

#: Exceptions treated as transient by default: filesystem/network-class
#: errors. ValueError/TypeError (corrupt content) are NOT transient —
#: retrying a truncated checkpoint re-reads the same bad bytes; the
#: fallback walk (checkpoint.py) handles those instead.
TRANSIENT_ERRORS: tuple[type[BaseException], ...] = (OSError, IOError)

#: OSError subclasses that are PERMANENT: retrying a missing path or a
#: permission denial re-reads the same answer, so these surface
#: immediately (a typo'd --train_data path must not sit through the
#: full backoff schedule behind 'transient — retrying' warnings, and a
#: truncated checkpoint dir must advance the fallback walk, not stall
#: it).
PERMANENT_ERRORS: tuple[type[BaseException], ...] = (
    FileNotFoundError,
    PermissionError,
    NotADirectoryError,
    IsADirectoryError,
)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    attempts: int = 4  # total tries: 1 initial + 3 retries
    base_delay_s: float = 0.5
    max_delay_s: float = 8.0

    def delays(self) -> Iterable[float]:
        for i in range(max(0, self.attempts - 1)):
            yield random.uniform(
                0.0, min(self.max_delay_s, self.base_delay_s * (2.0**i))
            )


def retry_io(
    fn: Callable,
    *,
    policy: RetryPolicy | None = None,
    transient: tuple[type[BaseException], ...] = TRANSIENT_ERRORS,
    describe: str = "io",
    on_retry: Callable[[int, BaseException], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    deadline: float | None = None,
    clock: Callable[[], float] = time.monotonic,
):
    """Call ``fn()``; on a transient error, back off and retry up to
    ``policy.attempts`` total tries. The final failure re-raises the
    LAST error (the one a human debugs). ``on_retry(attempt, exc)``
    fires before each sleep — the trainer routes it to the sink as an
    ``io_retry`` event so flaky storage is visible, not silent.

    ``deadline`` is an ABSOLUTE ``clock()`` time bounding the whole
    retry loop: backoff sleeps are clamped to the remaining budget and
    a retry is never attempted past it, so a caller with its own
    deadline (a serving request, a hot reload mid-traffic) can wrap
    flaky I/O without the retries outliving it. A deadline already
    expired when the next retry would start re-raises the last error
    immediately.
    """
    policy = policy or RetryPolicy()
    delays = list(policy.delays())
    for attempt in range(policy.attempts):
        try:
            return fn()
        except transient as exc:
            if isinstance(exc, PERMANENT_ERRORS):
                raise
            if attempt >= policy.attempts - 1:
                raise
            delay = delays[attempt]
            if deadline is not None:
                remaining = deadline - clock()
                if remaining <= 0:
                    logger.warning(
                        "%s error and the caller's deadline has passed "
                        "(attempt %d/%d): %s — giving up without retrying",
                        describe, attempt + 1, policy.attempts, exc,
                    )
                    raise
                delay = min(delay, remaining)
            logger.warning(
                "transient %s error (attempt %d/%d): %s — retrying",
                describe, attempt + 1, policy.attempts, exc,
            )
            if on_retry is not None:
                on_retry(attempt + 1, exc)
            sleep(delay)
