"""Deterministic fault injection, driven by ``train.inject_fault``.

Every recovery path in the trainer exists because a real failure mode
exists on TPU fleets; every one of them must therefore be reproducible
on a CPU dev box or the recovery code rots untested. The spec grammar
is a comma-separated list of ``kind@arg`` entries:

* ``nan_grad@STEP`` — poison the batch dispatched as global step STEP
  so its loss/gradients are NaN (the "one bad step" pathology: an
  overflow, a poisoned collective, a flaky chip).
* ``bad_sample@STEP`` — NaN the batch's *inputs* at step STEP (a
  corrupt record that slipped through the data pipeline).
* ``sigterm@STEP`` — deliver a real SIGTERM to this process before
  dispatching step STEP (a TPU-VM preemption notice mid-epoch).
* ``ckpt_io@N`` — arm N transient ``InjectedIOError``s against
  checkpoint save/restore I/O (flaky remote filesystem).
* ``corrupt_ckpt@EPOCH`` — after the ``latest`` save of epoch EPOCH
  commits, truncate its directory on disk (torn write / partial
  upload), so a later restore must fall back.
* ``stop_epoch@N`` — stop cleanly after N epochs (the former
  ``--stop_after_epoch`` fault, now one mechanism with the rest; the
  flag remains as an alias).

Serve-side kinds (``serve.inject_fault``, consumed by
``gnot_tpu/serve/`` — docs/serving.md):

* ``slow_request@N`` — stall the dispatch carrying the Nth admitted
  request until that request's deadline has passed (a straggling
  device / head-of-line blocking), so deadline shedding is exercised
  deterministically.
* ``nan_output@N`` — poison the outputs of the Nth serving dispatch
  with NaN (sick chip / corrupted weights), the circuit breaker's
  trip condition.
* ``reload_corrupt@N`` — truncate the published ``latest`` checkpoint
  directory immediately before the Nth hot reload reads it, so the
  reload must survive via the restore fallback chain.

Rollout-serving kinds (stateful sessions, ``serve/rollout.py`` —
``STEP`` is the server's 1-indexed rollout-step admission ordinal, the
count of session steps that server has accepted):

* ``replica_kill@STEP`` — the replica dies just before dispatching its
  STEPth rollout step (worker exits; every in-system request fails
  ``error_replica_dead``): the mid-rollout replica loss whose sessions
  the router must migrate from their snapshots.
* ``stale_session@STEP`` — the session carry behind the STEPth rollout
  step is lost/stale at dispatch (``error_stale_session``): resident
  state evicted under it (host OOM, a buggy eviction) — restore from
  snapshot, don't serve a wrong trajectory.
* ``rollout_nan@STEP`` — NaN-poison the outputs of the dispatch
  carrying the STEPth rollout step (a sick chip mid-trajectory): feeds
  the breaker like ``nan_output``, and the victim session must replay,
  not keep a poisoned carry.

Federation kinds (multi-host control plane, ``serve/federation.py`` —
docs/distributed.md; one injector per HOST or per LINK, like the
router's per-replica injector map):

* ``host_kill@N`` — the host dies abruptly just before handling its
  Nth inbound control message (agent stops responding mid-protocol,
  local pool torn down hard): the host-loss shape whose resident
  sessions the cluster must re-migrate from persisted snapshots.
* ``net_partition@N`` — the link partitions (frames silently dropped
  BOTH ways) starting at its Nth outbound frame; the host stays
  healthy behind it. Healing is scripted by the harness
  (``heal_partition()``) so the detector's suspect→heal path is
  exercised deterministically.
* ``msg_drop@N`` — the link's Nth outbound frame is dropped (single
  lost datagram-shaped loss; retries/the next heartbeat must absorb
  it without a false death).
* ``msg_delay@MS`` — one frame (the first consulted after arming) is
  delayed by MS milliseconds before delivery: the slow-network shape
  the suspicion DWELL exists for — a slow host is drained around,
  never declared dead off one late ack.

Steps are 1-indexed global update counts (the trainer's ``host_step``
after the dispatch), matching the step numbers in metrics records;
serve ordinals are 1-indexed admission/dispatch/reload counts;
federation ordinals are 1-indexed per-host message / per-link frame
counts (``msg_delay``'s argument is milliseconds, not an ordinal).
Step- and epoch-keyed faults fire once; ``ckpt_io`` decrements its
budget per injected error.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import signal

import numpy as np

logger = logging.getLogger(__name__)

#: THE central registry of injectable fault kinds (the event-kind
#: counterpart is gnot_tpu/obs/events.py). graftlint's GL005 enforces
#: that every entry here is documented in docs/robustness.md.
FAULT_KINDS = (
    "nan_grad",
    "bad_sample",
    "sigterm",
    "ckpt_io",
    "corrupt_ckpt",
    "stop_epoch",
    # serve-side (gnot_tpu/serve/, docs/serving.md)
    "slow_request",
    "nan_output",
    "reload_corrupt",
    # rollout-serving (stateful sessions, serve/rollout.py)
    "replica_kill",
    "stale_session",
    "rollout_nan",
    # federation (multi-host control plane, serve/federation.py,
    # docs/distributed.md)
    "host_kill",
    "net_partition",
    "msg_drop",
    "msg_delay",
)

KINDS = FAULT_KINDS  # legacy alias


class InjectedIOError(OSError):
    """A deliberately injected transient I/O failure (subclass of
    OSError so the retry machinery treats it exactly like the real
    thing)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str  # one of FAULT_KINDS
    at: int  # step / epoch / error budget, per kind


def parse_fault_spec(spec: str) -> list[FaultSpec]:
    """Parse ``"kind@N,kind@N"`` into FaultSpecs; raises ValueError
    naming the bad entry and the grammar, not an unpack error."""
    out: list[FaultSpec] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        kind, sep, arg = entry.partition("@")
        if not sep or kind not in FAULT_KINDS or not arg.lstrip("-").isdigit():
            raise ValueError(
                f"bad fault spec entry {entry!r}: want kind@N with kind in "
                f"{FAULT_KINDS} and integer N (got spec {spec!r})"
            )
        at = int(arg)
        if at < 1:
            raise ValueError(f"fault spec entry {entry!r}: N must be >= 1")
        out.append(FaultSpec(kind, at))
    return out


class FaultInjector:
    """Holds the parsed plan; the trainer/checkpointer consult it at
    the few hookable boundaries (pre-dispatch, checkpoint I/O,
    post-save, epoch end). Single-fire bookkeeping lives here so the
    call sites stay branch-free when no fault is armed."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = list(specs)
        self._fired: set[tuple[str, int]] = set()
        self._io_budget = sum(s.at for s in specs if s.kind == "ckpt_io")

    @classmethod
    def from_config(cls, train_cfg) -> "FaultInjector | None":
        """Build from TrainConfig: the ``inject_fault`` spec plus the
        legacy ``stop_after_epoch`` alias (mapped to ``stop_epoch@N``
        so resume tests and chaos tests share one mechanism). Returns
        None when nothing is armed (the common case — the trainer then
        skips every hook)."""
        specs = parse_fault_spec(getattr(train_cfg, "inject_fault", "") or "")
        stop = getattr(train_cfg, "stop_after_epoch", 0)
        if stop and not any(s.kind == "stop_epoch" for s in specs):
            specs.append(FaultSpec("stop_epoch", stop))
        return cls(specs) if specs else None

    @classmethod
    def from_spec(cls, spec: str) -> "FaultInjector | None":
        """Build straight from a ``kind@N,...`` spec string (the serving
        engine's entry point — no TrainConfig in the loop). Returns
        None when the spec is empty."""
        specs = parse_fault_spec(spec or "")
        return cls(specs) if specs else None

    def _take(self, kind: str, at: int) -> bool:
        """True exactly once per (kind, at) armed in the plan."""
        key = (kind, at)
        if key in self._fired:
            return False
        if any(s.kind == kind and s.at == at for s in self.specs):
            self._fired.add(key)
            return True
        return False

    # -- trainer hooks -----------------------------------------------------

    def poison_batch(self, batch, step: int):
        """Apply any batch-level fault armed for global step ``step``
        (the 1-indexed step this batch will be dispatched as). Returns
        the (possibly poisoned) batch — a copy; loader-owned arrays are
        never written in place."""
        if self._take("nan_grad", step):
            logger.warning("fault injection: NaN targets at step %d", step)
            return batch.replace(y=np.full_like(np.asarray(batch.y), np.nan))
        if self._take("bad_sample", step):
            logger.warning("fault injection: bad sample (NaN coords) at step %d", step)
            return batch.replace(
                coords=np.full_like(np.asarray(batch.coords), np.nan)
            )
        return batch

    def maybe_sigterm(self, step: int) -> None:
        """Deliver a real SIGTERM to this process before step ``step``
        dispatches — exercising the actual signal path, not a mock."""
        if self._take("sigterm", step):
            logger.warning("fault injection: SIGTERM before step %d", step)
            os.kill(os.getpid(), signal.SIGTERM)

    def stop_after_epoch(self, epoch: int) -> bool:
        """Clean stop once ``epoch + 1`` epochs have completed (the
        former ``--stop_after_epoch`` semantics)."""
        return any(
            s.kind == "stop_epoch" and epoch + 1 >= s.at for s in self.specs
        )

    # -- serving hooks (gnot_tpu/serve/) -----------------------------------

    def maybe_slow_request(self, ordinal: int) -> bool:
        """True once when the ``ordinal``-th admitted request has a
        ``slow_request`` fault armed: the server stalls that request's
        dispatch past its deadline (deterministic deadline shedding)."""
        if self._take("slow_request", ordinal):
            logger.warning(
                "fault injection: slow request at admission #%d", ordinal
            )
            return True
        return False

    def maybe_nan_output(self, dispatch: int) -> bool:
        """True once when the ``dispatch``-th serving forward has a
        ``nan_output`` fault armed: the server poisons that dispatch's
        outputs with NaN (the circuit breaker's trip condition)."""
        if self._take("nan_output", dispatch):
            logger.warning(
                "fault injection: NaN outputs on serving dispatch #%d",
                dispatch,
            )
            return True
        return False

    def maybe_replica_kill(self, rollout_step: int) -> bool:
        """True once when the server's ``rollout_step``-th session step
        has a ``replica_kill`` armed: the worker dies before the
        dispatch (every in-system request fails ``error_replica_dead``
        and the worker thread exits — the router's ``dead`` health
        signal)."""
        if self._take("replica_kill", rollout_step):
            logger.warning(
                "fault injection: replica kill at rollout step #%d",
                rollout_step,
            )
            return True
        return False

    def maybe_stale_session(self, rollout_step: int) -> bool:
        """True once when the ``rollout_step``-th session step has a
        ``stale_session`` armed: the resident carry behind that step is
        lost — the step fails ``error_stale_session`` and the session
        must restore from its snapshot."""
        if self._take("stale_session", rollout_step):
            logger.warning(
                "fault injection: stale session carry at rollout step #%d",
                rollout_step,
            )
            return True
        return False

    def maybe_rollout_nan(self, rollout_step: int) -> bool:
        """True once when the ``rollout_step``-th session step has a
        ``rollout_nan`` armed: the dispatch carrying it gets NaN
        outputs (breaker food; the victim session replays from its
        snapshot instead of committing a poisoned carry)."""
        if self._take("rollout_nan", rollout_step):
            logger.warning(
                "fault injection: NaN outputs at rollout step #%d",
                rollout_step,
            )
            return True
        return False

    # -- federation hooks (gnot_tpu/serve/federation.py) -------------------

    def maybe_host_kill(self, msg_ordinal: int) -> bool:
        """True once when the host's ``msg_ordinal``-th inbound control
        message has a ``host_kill`` armed: the HostAgent dies abruptly
        before handling it (stops responding mid-protocol, pool torn
        down hard) — the cluster's failure detector must notice via
        lease silence and re-migrate the resident sessions."""
        if self._take("host_kill", msg_ordinal):
            logger.warning(
                "fault injection: host kill before inbound message #%d",
                msg_ordinal,
            )
            return True
        return False

    def maybe_net_partition(self, frame_ordinal: int) -> bool:
        """True once when the link's ``frame_ordinal``-th outbound frame
        has a ``net_partition`` armed: the link enters a partitioned
        state (frames dropped BOTH ways) until the harness heals it."""
        if self._take("net_partition", frame_ordinal):
            logger.warning(
                "fault injection: network partition at frame #%d",
                frame_ordinal,
            )
            return True
        return False

    def maybe_msg_drop(self, frame_ordinal: int) -> bool:
        """True once when the link's ``frame_ordinal``-th outbound frame
        has a ``msg_drop`` armed: that single frame is dropped (lost
        datagram shape; the next heartbeat/retry must absorb it)."""
        if self._take("msg_drop", frame_ordinal):
            logger.warning(
                "fault injection: dropping frame #%d", frame_ordinal
            )
            return True
        return False

    def maybe_msg_delay(self) -> int:
        """Milliseconds to delay the next frame by (0 = none): fires
        once per armed ``msg_delay@MS`` spec — the argument is the
        DELAY, not an ordinal, so the first consultation after arming
        takes it. Models the slow-network ack the suspicion dwell must
        tolerate without declaring death."""
        for s in self.specs:
            if s.kind == "msg_delay" and self._take("msg_delay", s.at):
                logger.warning(
                    "fault injection: delaying frame by %d ms", s.at
                )
                return s.at
        return 0

    def maybe_reload_corrupt(self, reload_ordinal: int, directory: str) -> bool:
        """``reload_corrupt@N``: before the Nth hot reload restores,
        truncate the published ``latest`` checkpoint under
        ``directory`` (torn write racing the reload) — the reload must
        survive via the restore fallback chain."""
        if not self._take("reload_corrupt", reload_ordinal):
            return False
        logger.warning(
            "fault injection: corrupting published 'latest' under %s "
            "before reload #%d", directory, reload_ordinal,
        )
        corrupt_published(directory, "latest")
        return True

    # -- checkpoint hooks --------------------------------------------------

    def maybe_io_error(self, op: str) -> None:
        """Raise one InjectedIOError per armed ``ckpt_io`` budget unit
        (the Checkpointer calls this at the top of each save/restore
        I/O attempt, inside the retry wrapper)."""
        if self._io_budget > 0:
            self._io_budget -= 1
            logger.warning(
                "fault injection: transient I/O error on %s (%d left)",
                op, self._io_budget,
            )
            raise InjectedIOError(f"injected transient failure during {op}")

    def post_save(self, name: str, directory: str, epoch: int) -> None:
        """``corrupt_ckpt@EPOCH``: truncate the just-committed ``latest``
        directory of that epoch (files vanish, sidecar still points at
        it — the torn-write shape restore fallback must survive)."""
        if name == "latest" and self._take("corrupt_ckpt", epoch):
            logger.warning(
                "fault injection: truncating checkpoint dir %s", directory
            )
            corrupt_checkpoint(directory, mode="truncate")


def corrupt_checkpoint(path: str, *, mode: str = "truncate") -> None:
    """Corrupt a committed orbax checkpoint directory in one of the
    shapes real storage produces (shared by the injector and the chaos
    tests):

    * ``truncate`` — delete roughly half the files under the directory
      (partial upload / torn write); the dir exists but orbax restore
      fails on it.
    * ``remove`` — delete the directory outright (sidecar now dangles).
    """
    if mode == "remove":
        shutil.rmtree(path, ignore_errors=True)
        return
    if mode != "truncate":
        raise ValueError(f"unknown corruption mode {mode!r}")
    victims = []
    for root, _, files in os.walk(path):
        victims.extend(os.path.join(root, f) for f in sorted(files))
    if not victims:
        raise FileNotFoundError(f"no files to corrupt under {path}")
    # Deterministic: drop every other file plus the orbax metadata (the
    # restore-breaking piece), and truncate the survivors' first file.
    for f in victims[:: 2]:
        os.remove(f)
    for f in victims:
        if os.path.exists(f) and os.path.basename(f).startswith("_"):
            os.remove(f)
    survivors = [f for f in victims if os.path.exists(f)]
    if survivors:
        with open(survivors[0], "wb") as fh:
            fh.write(b"\0")


def corrupt_published(directory: str, name: str = "latest") -> None:
    """Truncate the checkpoint directory the ``<name>.json`` sidecar
    currently names (the serve-side ``reload_corrupt`` shape: a torn
    write landing between a save and the reload that reads it). No-op
    when no sidecar/directory exists — the reload then simply walks its
    normal fallback chain."""
    meta_path = os.path.join(directory, f"{name}.json")
    try:
        with open(meta_path) as f:
            target = json.load(f).get("dir", name)
    except (OSError, json.JSONDecodeError):
        return
    full = os.path.join(directory, target)
    if os.path.isdir(full):
        corrupt_checkpoint(full, mode="truncate")


def dangle_sidecar(directory: str, name: str) -> None:
    """Point ``<name>.json`` at a directory that does not exist (the
    crash-window shape: sidecar committed, dir later lost)."""
    meta_path = os.path.join(directory, f"{name}.json")
    with open(meta_path) as f:
        meta = json.load(f)
    gone = meta.get("dir", name)
    shutil.rmtree(os.path.join(directory, gone), ignore_errors=True)
