"""Graceful preemption: SIGTERM/SIGINT → stop at the next step boundary.

TPU VMs are preemptible: the fleet sends SIGTERM and gives the process
a short grace window. Today's alternative — dying mid-step — strands
everything since the last periodic checkpoint. The handler here only
*requests* a stop (signal handlers must not run orbax saves or
collectives); the trainer checks the flag at step boundaries, saves
``latest``, flushes the sink, and exits resume-ready.

Multi-host coordination: a preemption notice can land on ONE host of a
pod. If that host stopped unilaterally the others would hang in the
next collective, so the step-boundary check all-reduces the flag
(``multihost.sync_flag`` — a tiny allgather every
``preempt_sync_every`` dispatches) and every host stops on the same
step. Single-process runs skip the collective entirely.

A second SIGINT restores Python's default KeyboardInterrupt behavior —
"Ctrl-C twice" stays the emergency exit, ahead of any save.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger(__name__)


class PreemptionHandler:
    """Context manager installing SIGTERM/SIGINT handlers that set a
    flag read by the training loop.

    Signal handlers are process-global and main-thread-only; entering
    from a non-main thread (embedding apps, test runners) degrades to
    a no-op handler whose flag simply never fires — the run behaves as
    before this subsystem existed. Previous handlers are restored on
    exit, so in-process drivers (tests calling ``main()``) do not leak
    handler state across runs.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, *, sync_every: int = 1):
        self.sync_every = max(1, int(sync_every))
        self._requested = threading.Event()
        self._previous: dict[int, object] = {}
        self._installed = False
        self._sigint_count = 0
        self._checks = 0

    # -- signal side -------------------------------------------------------

    def _handle(self, signum, frame) -> None:
        if signum == signal.SIGINT:
            self._sigint_count += 1
            if self._sigint_count > 1:
                raise KeyboardInterrupt  # second Ctrl-C: bail NOW
        logger.warning(
            "%s received: stopping at the next step boundary "
            "(checkpoint + metrics flush, then exit resume-ready)",
            signal.Signals(signum).name,
        )
        self._requested.set()

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handle)
            self._installed = True
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._installed:
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous.clear()
            self._installed = False

    # -- trainer side ------------------------------------------------------

    def request_stop(self) -> None:
        """Programmatic stop request (same path as the signals)."""
        self._requested.set()

    @property
    def triggered(self) -> bool:
        return self._requested.is_set()

    def should_stop(self, *, multiprocess: bool = False) -> bool:
        """Step-boundary check. Single-process: the local flag. Multi-
        process: every ``sync_every``-th call all-reduces the flag so
        all hosts agree on the stop step — COLLECTIVE on those calls
        (every process must call with the same cadence, which the SPMD
        dispatch loop guarantees); other calls return False without
        communicating, so a local flag waits (bounded) for the next
        sync point rather than desynchronizing the pod."""
        if not multiprocess:
            return self.triggered
        self._checks += 1
        if self._checks % self.sync_every:
            return False
        from gnot_tpu.parallel import multihost

        return multihost.sync_flag(self.triggered)
