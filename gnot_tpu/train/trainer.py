"""Training loop: jitted AdamW steps, per-epoch eval, best-checkpoint.

Reproduces the reference regime (``/root/reference/main.py:50-153``):
AdamW at torch defaults, OneCycle schedule (with the per-epoch stepping
bug in parity mode, see schedule.py), rel-L2 train objective and eval
metric, per-epoch console lines in the reference's exact format, and
best-eval checkpoint selection.

TPU-native differences: the whole update is one ``jit``-compiled,
donate-argnum'd function (no per-step ``.item()`` sync — losses are
fetched as device arrays and resolved at epoch end); batches stay
padded/masked on device; the learning rate enters the compiled step as a
scalar argument so schedule changes never trigger recompiles.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import time
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from gnot_tpu.config import Config, ModelConfig, OptimConfig
from gnot_tpu.data.batch import Loader, MeshBatch
from gnot_tpu.models.gnot import GNOT
from gnot_tpu.obs import events
from gnot_tpu.ops.segment import LOSSES, PER_SAMPLE_LOSSES
from gnot_tpu.train.schedule import make_lr_fn
from gnot_tpu.utils import profiling


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array  # int32 update counter


def make_optimizer(cfg: OptimConfig, learning_rate) -> optax.GradientTransformation:
    """AdamW with torch defaults made explicit (SURVEY.md §7 hard parts:
    optax and torch defaults differ — wd=0.01, eps=1e-8 are torch's).

    ``grad_accum > 1`` wraps the transform in ``optax.MultiSteps``: k
    micro-batch gradients are averaged before each real update, so the
    effective batch is k x batch_size at constant device memory."""
    tx = optax.adamw(
        learning_rate=learning_rate,
        b1=cfg.b1,
        b2=cfg.b2,
        eps=cfg.eps,
        weight_decay=cfg.weight_decay,
    )
    if cfg.grad_clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    if cfg.grad_accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.grad_accum)
    return tx


def apply_batch(model: GNOT, params, batch) -> jax.Array:
    """The one forward invocation (shared by loss, init and inference
    paths); a PackedBatch routes through the packed segment layout."""
    from gnot_tpu.data.batch import PackedBatch

    if isinstance(batch, PackedBatch):
        return model.apply(
            {"params": params},
            batch.coords,
            batch.theta,
            batch.funcs,
            node_mask=batch.node_mask,
            func_mask=batch.func_mask,
            node_seg=batch.node_seg,
            func_seg=batch.func_seg,
            n_seg=batch.n_seg,
        )
    return model.apply(
        {"params": params},
        batch.coords,
        batch.theta,
        batch.funcs,
        node_mask=batch.node_mask,
        func_mask=batch.func_mask,
    )


def packed_loss_fn(model: GNOT, loss_name: str) -> Callable:
    """loss_fn for the packed layout: packed forward + per-segment
    pooled loss (mean over the samples present in the dispatch)."""
    from gnot_tpu.ops.segment import PACKED_LOSSES

    def loss_fn(params, batch):
        preds = apply_batch(model, params, batch)
        return PACKED_LOSSES[loss_name](
            preds, batch.y, batch.node_mask, batch.node_seg, batch.n_seg
        )

    return loss_fn


def batch_loss(model: GNOT, params, batch: MeshBatch, loss_name: str) -> jax.Array:
    """Forward + per-graph pooled loss. The loss is always masked — the
    reference unpads before pooling (main.py:89), so padding never enters
    the loss even in parity mode."""
    preds = apply_batch(model, params, batch)
    return LOSSES[loss_name](preds, batch.y, batch.node_mask)


def train_step_body(
    model: GNOT,
    optim_cfg: OptimConfig,
    loss_name: str,
    *,
    loss_fn: Callable | None = None,
    instrument: Callable | None = None,
    loss_has_aux: bool = False,
):
    """THE training-step math — the one copy every step builder wraps
    (single-device, GSPMD-sharded, K-step scanned, and pipelined), so
    'numerically identical across dispatch modes' holds by construction.
    Shaped as a scan body: ``body(state, (batch, lr))``. The LR is a
    traced scalar: optax.adamw is pure, so building the transform inside
    the compiled step is free and recompile-safe. ``loss_fn(params,
    batch)`` overrides the forward (the pipeline path substitutes its
    shard_map forward); default is the standard ``batch_loss``.

    ``instrument(aux, grads, updates, params, batch) -> dict`` is the
    telemetry side-output hook (obs/telemetry.py): when set, the body
    returns ``(state, (loss, telem))`` instead of ``(state, loss)`` —
    the telemetry is computed INSIDE the compiled step (device
    reductions over values the backward pass already materialized), so
    enabling it adds no host syncs and does not change the update math.
    ``loss_has_aux=True`` marks a loss_fn returning ``(loss, aux)``
    (the intermediates-capturing telemetry forward)."""
    if loss_fn is None:
        loss_fn = lambda p, batch: batch_loss(model, p, batch, loss_name)

    def body(state: TrainState, xs):
        batch, lr = xs
        out, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch), has_aux=loss_has_aux
        )(state.params)
        loss, aux = out if loss_has_aux else (out, None)
        tx = make_optimizer(optim_cfg, lr)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        if instrument is None:
            return new_state, loss
        return new_state, (loss, instrument(aux, grads, updates, params, batch))

    return body


def make_train_step(
    model: GNOT, optim_cfg: OptimConfig, loss_name: str, *, loss_fn=None
) -> Callable:
    body = train_step_body(model, optim_cfg, loss_name, loss_fn=loss_fn)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, batch: MeshBatch, lr: jax.Array):
        return body(state, (batch, lr))

    return train_step


def make_multi_train_step(
    model: GNOT, optim_cfg: OptimConfig, loss_name: str, *, loss_fn=None
) -> Callable:
    """K training steps over K different batches as ONE compiled
    program: ``lax.scan`` over a MeshBatch whose leaves carry a leading
    step axis, with a ``[K]`` array of per-step learning rates. One
    host->device dispatch per K steps — the lever when dispatch latency
    (remote tunnels, tiny models) rivals step compute. Numerically
    identical to K ``make_train_step`` calls."""
    body = train_step_body(model, optim_cfg, loss_name, loss_fn=loss_fn)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def multi_step(state: TrainState, batches: MeshBatch, lrs: jax.Array):
        return jax.lax.scan(body, state, (batches, lrs))

    return multi_step


def stack_batches(batches: list[MeshBatch]) -> MeshBatch:
    """Stack same-shape host batches along a new leading step axis."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def eval_step_body(
    model: GNOT, loss_name: str, *, loss_fn=None, per_sample: bool = False
) -> Callable:
    """THE eval math — the one copy the single-device and sharded,
    single- and multi-batch eval builders all wrap. ``loss_fn(params,
    batch)`` overrides the forward (scan_layers substitutes the stacked
    forward). ``per_sample=True`` returns the ``[B]`` per-graph metric
    vector instead of the batch scalar (the distributed ragged-tail
    eval slices the real rows out on the host)."""
    if loss_fn is not None:
        return loss_fn
    table = PER_SAMPLE_LOSSES if per_sample else LOSSES

    def body(params, batch: MeshBatch):
        preds = apply_batch(model, params, batch)
        return table[loss_name](preds, batch.y, batch.node_mask)

    return body


def make_eval_step(model: GNOT, loss_name: str, *, loss_fn=None) -> Callable:
    return jax.jit(eval_step_body(model, loss_name, loss_fn=loss_fn))


def make_multi_eval_step(model: GNOT, loss_name: str, *, loss_fn=None) -> Callable:
    """K eval losses over K stacked batches in one dispatch (the eval
    counterpart of make_multi_train_step)."""
    body = eval_step_body(model, loss_name, loss_fn=loss_fn)

    @jax.jit
    def multi_eval(params, batches: MeshBatch):
        return jax.lax.map(lambda b: body(params, b), batches)

    return multi_eval


def stacked_loss_fn(model_cfg, loss_name: str, *, per_sample: bool = False) -> Callable:
    """loss_fn for the scan_layers (stacked-block) forward."""
    from gnot_tpu.parallel.pipeline import stacked_forward

    table = PER_SAMPLE_LOSSES if per_sample else LOSSES

    def loss_fn(params, batch: MeshBatch):
        preds = stacked_forward(model_cfg, params, batch)
        return table[loss_name](preds, batch.y, batch.node_mask)

    return loss_fn


def group_batches(batches, k: int):
    """Group same-shape batches into runs of k for one-dispatch
    execution: yields ``("group", [b1..bk])`` for full groups and
    ``("single", b)`` for shape-change flushes and remainders. THE one
    grouping discipline — the train and eval loops both iterate this,
    so their dispatch sequences stay in lockstep across hosts (a
    divergence would be a cross-host hang, not an error). ``k < 2``
    degenerates to all-singles (the plain one-step dispatch path)."""
    if k < 2:
        for b in batches:
            yield "single", b
        return
    pending, key = [], None
    for b in batches:
        bk = tuple(np.shape(l) for l in jax.tree.leaves(b))
        if pending and bk != key:
            # Bucket-shape change: the open group can't stack further.
            for p in pending:
                yield "single", p
            pending = []
        pending.append(b)
        key = bk
        if len(pending) == k:
            yield "group", pending
            pending = []
    for p in pending:  # remainder
        yield "single", p


def init_params(model: GNOT, sample_batch, seed: int):
    from gnot_tpu.data.batch import PackedBatch

    kwargs = dict(
        node_mask=sample_batch.node_mask, func_mask=sample_batch.func_mask
    )
    if isinstance(sample_batch, PackedBatch):
        kwargs.update(
            node_seg=sample_batch.node_seg,
            func_seg=sample_batch.func_seg,
            n_seg=sample_batch.n_seg,
        )
    return model.init(
        jax.random.key(seed),
        sample_batch.coords,
        sample_batch.theta,
        sample_batch.funcs,
        **kwargs,
    )["params"]


def init_state(model: GNOT, optim_cfg: OptimConfig, sample_batch: MeshBatch, seed: int) -> TrainState:
    params = init_params(model, sample_batch, seed)
    tx = make_optimizer(optim_cfg, optim_cfg.lr)
    return TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )


def init_flat_state(
    model: GNOT, optim_cfg: OptimConfig, sample_batch: MeshBatch, seed: int
):
    """Flat [P]-vector state layout (``optim.flat_params``): params and
    AdamW moments are ONE ravelled buffer each, so the optimizer update
    compiles to a few whole-buffer ops instead of ~2 per param leaf
    (the measured ~2 us/op launch overhead — docs/performance.md "Where
    the other 55% goes"). Returns ``(state, unravel)``; ``unravel`` maps
    the flat vector back to the param tree (exact — pure
    slices/reshapes, so gradients through it are a concat of the leaf
    gradients and the math is unchanged)."""
    from jax.flatten_util import ravel_pytree

    params = init_params(model, sample_batch, seed)
    flat, unravel = ravel_pytree(params)
    tx = make_optimizer(optim_cfg, optim_cfg.lr)
    return (
        TrainState(
            params=flat, opt_state=tx.init(flat), step=jnp.zeros((), jnp.int32)
        ),
        unravel,
    )


def map_state_containers(state: TrainState, rule: Callable) -> TrainState:
    """Rebuild a TrainState's ``params``/``opt_state`` by recursing
    through their containers (dicts, optax NamedTuple states,
    tuples/lists) and applying ``rule`` at every node: the first
    non-None result replaces that subtree, anything else passes
    through. THE one traversal both layout converters share
    (``convert_flat_state`` here, ``pipeline.convert_state_layout``) —
    optimizer moments mirror the param tree, so a layout change is
    always "find the param-shaped subtrees wherever optax nested them
    and rewrite each"."""
    import dataclasses

    def convert(node):
        out = rule(node)
        if out is not None:
            return out
        if isinstance(node, dict):
            return {k: convert(v) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(convert(v) for v in node))
        if isinstance(node, (tuple, list)):
            return type(node)(convert(v) for v in node)
        return node

    return dataclasses.replace(
        state, params=convert(state.params), opt_state=convert(state.opt_state)
    )


def convert_flat_state(state: TrainState, params_template, to: str) -> TrainState:
    """Convert a full TrainState between the flat ``[P]``-vector layout
    and the standard tree layout — INCLUDING the optimizer moments
    (and MultiSteps accumulators), whose trees mirror the params — so a
    checkpoint written by a ``--flat_params`` run can be resumed by a
    standard run and vice versa (the flat counterpart of
    ``pipeline.convert_state_layout``). ``params_template`` is a params
    tree with the target structure/shapes (e.g. ``init_params(...)`` or
    a restored tree). Operates on host/device values; no-op leaves pass
    through."""
    from jax.flatten_util import ravel_pytree

    if to not in ("flat", "tree"):
        raise ValueError(f"unknown layout {to!r} (want 'flat' or 'tree')")
    flat_t, unravel = ravel_pytree(params_template)
    size = flat_t.size
    pstruct = jax.tree_util.tree_structure(params_template)

    def rule(node):
        if (
            to == "tree"
            and hasattr(node, "ndim")
            and node.ndim == 1
            and node.size == size
        ):
            return unravel(node)
        if (
            to == "flat"
            and isinstance(node, dict)
            and jax.tree_util.tree_structure(node) == pstruct
        ):
            return ravel_pytree(node)[0]
        return None

    return map_state_containers(state, rule)


def flat_loss_fn(
    model: GNOT, unravel, loss_name: str, *, per_sample: bool = False
) -> Callable:
    """loss_fn for the flat [P]-vector layout: unravel, then the
    standard forward + pooled loss."""
    table = PER_SAMPLE_LOSSES if per_sample else LOSSES

    def loss_fn(p, batch: MeshBatch):
        preds = apply_batch(model, unravel(p), batch)
        return table[loss_name](preds, batch.y, batch.node_mask)

    return loss_fn


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


class Trainer:
    """Orchestrates one train/eval run (reference main.py:55-153)."""

    def __init__(
        self,
        config: Config,
        model_cfg: ModelConfig,
        train_samples,
        test_samples,
        *,
        metrics_sink=None,
        checkpointer=None,
        tracer=None,
        metrics_registry=None,
    ):
        self.config = config
        # Live metrics plane (obs/metrics.py): when a registry is
        # attached, the telemetry drain feeds the train_step_time_ms
        # windowed histogram and the slow-step counter — the same
        # series/publisher machinery the serving tier streams through.
        self._metrics_registry = metrics_registry
        # obs.tracing.Tracer (--trace_path) or None = tracing off. All
        # trainer spans are host-side (around dispatch, never inside
        # the compiled step — GL002 enforces that); one trace per
        # epoch, head-sampled at trace_sample_rate.
        self._tracer = tracer
        self.mesh = None
        self._eval_tail = 0  # real samples in a repeat-padded tail eval batch
        if config.train.telemetry and config.train.distributed and config.mesh.pipe > 1:
            # BEFORE any mesh/pipeline setup so the error names the real
            # conflict, not a downstream pipeline validation.
            from gnot_tpu.obs.telemetry import PIPE_ERROR

            raise ValueError(PIPE_ERROR)
        if config.data.packed:
            # Validate BEFORE any mesh/pad setup so the error names the
            # real conflict, not a downstream divisibility check.
            if config.train.distributed:
                if jax.process_count() > 1:
                    raise ValueError(
                        "packed + multi-process not composed yet (the "
                        "cross-host packed global-batch assembly is not "
                        "built); packed meshes are single-process"
                    )
                if config.mesh.seq > 1 or config.mesh.pipe > 1:
                    raise ValueError(
                        "packed composes with the data/model/expert mesh "
                        "axes only: a seq shard would straddle packed "
                        "segments, and the pipeline forward does not "
                        "thread segment ids; set mesh seq=pipe=1"
                    )
            if model_cfg.attention_mode == "parity":
                raise ValueError(
                    "packed mode requires attention_mode='masked' "
                    "(parity reproduces the reference's per-batch "
                    "padding pollution, which has no packed equivalent)"
                )
            if model_cfg.scan_layers:
                raise ValueError(
                    "packed + scan_layers not composed yet; pick one"
                )
            if config.optim.flat_params:
                raise ValueError(
                    "packed + flat_params not composed yet; pick one"
                )
        drop_remainder = config.data.drop_remainder
        pad_nodes = config.data.pad_nodes
        pad_funcs = config.data.pad_funcs
        if config.train.distributed and config.data.packed:
            # Packed dispatches already have ONE static shape (R rows x
            # row_len); none of the pad-fixing / remainder / tail
            # machinery below applies — the only mesh requirement is
            # that the row count splits over the data axis, enforced by
            # the loader's row_multiple.
            from gnot_tpu.parallel import multihost

            self.mesh = multihost.make_hybrid_mesh(config.mesh)
        elif config.train.distributed:
            from gnot_tpu.data.batch import fixed_pad_lengths
            from gnot_tpu.parallel import multihost

            self.mesh = multihost.make_hybrid_mesh(config.mesh)
            if not pad_nodes:
                # Distributed batches need one fixed shape: per-batch
                # padding would diverge across hosts (different local
                # samples -> different bucketed maxima -> SPMD shape
                # mismatch). Multi-process drivers set these from the
                # PRE-shard dataset (main.py); computing from local
                # samples here covers the single-process case.
                pad_nodes, pad_funcs = fixed_pad_lengths(
                    list(train_samples) + list(test_samples),
                    bucket=config.data.bucket,
                )
            # Fail at startup, not mid-epoch: every batch must split
            # over the mesh axes.
            local_data = self.mesh.shape["data"] // max(1, jax.process_count())
            if config.data.batch_size % max(1, local_data):
                raise ValueError(
                    f"batch_size={config.data.batch_size} must be divisible "
                    f"by the per-host data axis ({local_data})"
                )
            if self.mesh.shape["seq"] > 1 and not config.data.bucket:
                raise ValueError(
                    "sequence parallelism (mesh seq>1) requires bucketed "
                    "padding (lengths divisible by the seq axis); drop "
                    "--no_bucket"
                )
            if self.mesh.shape.get("pipe", 1) > 1:
                from gnot_tpu.parallel import pipeline

                pipeline.validate_local_batch(
                    self.mesh,
                    config.data.batch_size,
                    config.mesh.microbatches,
                    max(1, jax.process_count()),
                )
            if len(train_samples) % config.data.batch_size:
                drop_remainder = True  # partial batches can't shard
            tail = len(test_samples) % config.data.batch_size
            if tail:
                # The reference evaluates the ragged tail batch
                # (main.py:113-132). A short batch can't shard over the
                # mesh, so pad it with repeats of the last sample and
                # drop them from the metric (predict's discipline,
                # see evaluate()). Multi-process runs require
                # n_test % n_process == 0 (main.py), so every host's
                # local tail has the same length — same batch count,
                # no cross-host divergence.
                self._eval_tail = tail
                test_samples = list(test_samples) + [test_samples[-1]] * (
                    config.data.batch_size - tail
                )
        self.model = GNOT(model_cfg)
        self._packed = config.data.packed
        if self._packed:
            from gnot_tpu.data.batch import PackedLoader

            row_multiple = self.mesh.shape["data"] if self.mesh is not None else 1
            self.train_loader = PackedLoader(
                train_samples,
                config.data.batch_size,
                chunk=config.data.pack_chunk,
                shuffle=config.data.shuffle_train,
                seed=config.data.seed,
                row_multiple=row_multiple,
            )
            self.test_loader = (
                PackedLoader(
                    test_samples,
                    config.data.batch_size,
                    chunk=config.data.pack_chunk,
                    row_multiple=row_multiple,
                )
                if len(test_samples)
                else Loader([], config.data.batch_size)
            )
        else:
            self.train_loader = Loader(
                train_samples,
                config.data.batch_size,
                shuffle=config.data.shuffle_train,
                seed=config.data.seed,
                bucket=config.data.bucket,
                drop_remainder=drop_remainder,
                pad_nodes=pad_nodes,
                pad_funcs=pad_funcs,
            )
            self.test_loader = Loader(
                test_samples,
                config.data.batch_size,
                shuffle=False,
                bucket=config.data.bucket,
                pad_nodes=pad_nodes,
                pad_funcs=pad_funcs,
            )
        # debug_checks: main() enables process-global jax_debug_nans at
        # startup (before any tracing — the only point it reliably
        # instruments, and a global flag is the CLI's to own, not a
        # library constructor's); the trainer's own guard is the
        # host-side per-step finiteness check in fit().
        # scan_layers: the stacked forward substitutes via loss_fn in
        # every (non-pipeline) dispatch mode; the pipeline path scans
        # its own stages already.
        self._loss_fn = (
            stacked_loss_fn(model_cfg, config.train.loss)
            if model_cfg.scan_layers
            and not (self.mesh is not None and self.mesh.shape.get("pipe", 1) > 1)
            else None
        )
        if self._packed:
            # Packed forward + per-segment pooled loss for BOTH train
            # and eval steps (the eval metric is the dispatch's mean
            # per-sample loss, the packed analogue of the reference's
            # per-batch mean).
            self._loss_fn = packed_loss_fn(self.model, config.train.loss)
        self._flat = config.optim.flat_params
        self._unravel = None  # set by initialize() in flat mode
        if self._flat:
            if model_cfg.scan_layers:
                raise ValueError(
                    "flat_params and scan_layers both re-lay-out the "
                    "params (flat buffer vs stacked blocks) and do not "
                    "compose; pick one"
                )
            if self.mesh is not None and any(
                self.mesh.shape.get(a, 1) > 1 for a in ("model", "expert", "pipe")
            ):
                raise ValueError(
                    "flat_params keeps the params as one replicated "
                    "buffer and composes with the data/seq mesh axes "
                    "only; set mesh model=expert=pipe=1"
                )
        # All step builders live in initialize(): the sharded jits need
        # the state's sharding layout, the flat jits need the unravel fn
        # (a function of the initialized param tree's shapes), and one
        # build site keeps the loss_fn wiring in one place.
        self.train_step = self.eval_step = None
        if (
            config.optim.grad_accum > 1
            and len(self.train_loader) % config.optim.grad_accum
        ):
            import logging

            logging.getLogger(__name__).warning(
                "steps_per_epoch=%d is not divisible by grad_accum=%d: "
                "accumulation windows straddle epoch boundaries and the "
                "final partial window is discarded",
                len(self.train_loader),
                config.optim.grad_accum,
            )
        self.lr_fn = make_lr_fn(
            config.optim,
            steps_per_epoch=len(self.train_loader),
            epochs=config.train.epochs,
        )
        if config.train.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{config.train.steps_per_dispatch}"
            )
        # Telemetry runtime pieces (obs/): built in fit() when enabled.
        self._telemetry = None
        self._recompiles = None
        self.metrics_sink = metrics_sink
        self.checkpointer = checkpointer
        # Resilience pieces (resilience/): the fault injector parses
        # train.inject_fault (plus the stop_after_epoch alias) at
        # construction so a bad spec fails HERE, not mid-run; the
        # recovery supervisor is built in fit() when train.recovery.
        from gnot_tpu.resilience.faults import FaultInjector

        self._faults = FaultInjector.from_config(config.train)
        self._supervisor = None
        if self.checkpointer is not None:
            # One injector instance end to end: the ckpt_io error budget
            # is shared between trainer- and checkpointer-side hooks, and
            # recovery/restore/retry events flow into the same sink.
            if self._faults is not None and self.checkpointer.fault_injector is None:
                self.checkpointer.fault_injector = self._faults
            if self.checkpointer.on_event is None and metrics_sink is not None:
                self.checkpointer.on_event = metrics_sink.log
        self.multi_train_step = None
        self.multi_eval_step = None
        self._tail_eval_step = None
        self.state: TrainState | None = None
        self._forward = None  # jitted inference fn, built on first predict()
        self._forward_builder = None  # fresh-jit factory (serve/aot.py)
        self._engine = None  # serve.InferenceEngine, built on first predict()
        self.best_metric = float("inf")
        self.start_epoch = 0
        # Host-side mirror of state.step: reading the device counter every
        # batch would force a blocking transfer per step.
        self.host_step = 0

    def initialize(self) -> TrainState:
        # Shape probe: collate one batch directly — going through the
        # loader would spin up its prefetch thread and collate batches
        # that get thrown away.
        probe = self.test_loader if len(self.test_loader) else self.train_loader
        if self._packed:
            sample = probe.probe_batch()
        else:
            sample = probe._collate_at(
                np.arange(min(probe.batch_size, len(probe.samples)))
            )
        if self.mesh is not None and self.mesh.shape.get("pipe", 1) > 1:
            from gnot_tpu.parallel import pipeline

            # Pipeline layout: block params stacked on a pipe-sharded
            # layer axis. Checkpoints save/restore this layout directly.
            self.state = pipeline.init_pipeline_state(
                self.model, self.config.optim, sample, self.config.train.seed,
                self.mesh,
            )
            already_sharded = True
        elif self.model.config.scan_layers:
            from gnot_tpu.parallel import pipeline

            # Stacked layout (scan_layers): GSPMD sharding (if any)
            # applies below — mesh._param_pspec knows the blocks stack.
            self.state = pipeline.init_stacked_state(
                self.model, self.config.optim, sample, self.config.train.seed
            )
            already_sharded = False
        elif self._flat:
            self.state, self._unravel = init_flat_state(
                self.model, self.config.optim, sample, self.config.train.seed
            )
            self._loss_fn = flat_loss_fn(
                self.model, self._unravel, self.config.train.loss
            )
            already_sharded = False
        else:
            self.state = init_state(
                self.model, self.config.optim, sample, self.config.train.seed
            )
            already_sharded = False
        if self.mesh is not None and not already_sharded:
            from gnot_tpu.parallel import mesh as mesh_lib

            # Shard BEFORE any restore: Orbax then restores straight
            # into the mesh layout (each process reads only its shards).
            # Restoring into a local template and re-sharding would need
            # a committed-array cross-host device_put, which non-TPU
            # backends reject.
            self.state = mesh_lib.shard_state(self.mesh, self.state)
        if self.checkpointer is not None and self.config.train.resume:
            restored = self.checkpointer.restore_latest(self.state)
            if restored is not None:
                self.state, self.start_epoch, self.best_metric = restored
                self.host_step = int(self.state.step)  # one-time sync
        # Telemetry swaps in the instrumented step builders — SAME
        # signatures, same train_step_body math, extra side outputs
        # (obs/telemetry.py) — selected once here; eval steps are
        # shared with the plain path.
        telemetry_on = self.config.train.telemetry
        if telemetry_on:
            from gnot_tpu.obs import telemetry as obs_telemetry
        if self.mesh is None:
            build_step = (
                obs_telemetry.make_train_step if telemetry_on else make_train_step
            )
            self.train_step = build_step(
                self.model, self.config.optim, self.config.train.loss,
                loss_fn=self._loss_fn,
            )
            self.eval_step = make_eval_step(
                self.model, self.config.train.loss, loss_fn=self._loss_fn
            )
        if self.mesh is not None:
            from gnot_tpu.parallel import mesh as mesh_lib

            build_step = (
                obs_telemetry.make_sharded_train_step
                if telemetry_on
                else mesh_lib.make_sharded_train_step
            )
            self.train_step = build_step(
                self.model, self.config.optim, self.config.train.loss,
                self.mesh, self.state, self.config.mesh.microbatches,
                loss_fn=self._loss_fn,
            )
            self.eval_step = mesh_lib.make_sharded_eval_step(
                self.model, self.config.train.loss, self.mesh, self.state,
                self.config.mesh.microbatches, loss_fn=self._loss_fn,
            )
            if self._eval_tail:
                # Per-sample metric vector for the repeat-padded tail
                # batch; evaluate() slices the real rows on the host.
                if self._flat:
                    tail_loss_fn = flat_loss_fn(
                        self.model, self._unravel, self.config.train.loss,
                        per_sample=True,
                    )
                elif self._loss_fn is not None:
                    tail_loss_fn = stacked_loss_fn(
                        self.model.config, self.config.train.loss, per_sample=True
                    )
                else:
                    tail_loss_fn = None
                self._tail_eval_step = mesh_lib.make_sharded_eval_step(
                    self.model, self.config.train.loss, self.mesh, self.state,
                    self.config.mesh.microbatches, loss_fn=tail_loss_fn,
                    per_sample=True,
                )
        if self.config.train.steps_per_dispatch > 1:
            if self.mesh is None:
                build_multi = (
                    obs_telemetry.make_multi_train_step
                    if telemetry_on
                    else make_multi_train_step
                )
                self.multi_train_step = build_multi(
                    self.model, self.config.optim, self.config.train.loss,
                    loss_fn=self._loss_fn,
                )
                self.multi_eval_step = make_multi_eval_step(
                    self.model, self.config.train.loss, loss_fn=self._loss_fn
                )
            else:
                from gnot_tpu.parallel import mesh as mesh_lib

                build_multi = (
                    obs_telemetry.make_sharded_multi_train_step
                    if telemetry_on
                    else mesh_lib.make_sharded_multi_train_step
                )
                self.multi_train_step = build_multi(
                    self.model, self.config.optim, self.config.train.loss,
                    self.mesh, self.state, loss_fn=self._loss_fn,
                )
                self.multi_eval_step = mesh_lib.make_sharded_multi_eval_step(
                    self.model, self.config.train.loss, self.mesh, self.state,
                    loss_fn=self._loss_fn,
                )
        # Donation sanitizer seam (utils/sanitizer.py): under
        # GNOT_ALIAS_GUARD=poison the donating dispatches poison any
        # registered host view of the state they just donated, so a
        # stale `jax.device_get` snapshot fails loudly at its read
        # site. Identity (the bare jitted step, zero wrapper frames)
        # in off/copy mode.
        from gnot_tpu.utils import sanitizer

        self.train_step = sanitizer.guard_donating(self.train_step)
        if self.multi_train_step is not None:
            self.multi_train_step = sanitizer.guard_donating(
                self.multi_train_step
            )
        return self.state

    def standard_params(self):
        """Current params in the standard ``block_i`` layout (unstacks
        the pipeline layout when the mesh carries ``pipe > 1``) — the
        layout predict / torch export / the reference weight mapping
        expect. Single-process only: multi-process callers must gather
        first (``gathered_standard_params``), because unstacking indexes
        eagerly into arrays that may not be fully addressable here."""
        if self._flat:
            return self._unravel(self.state.params)
        return self._unstack_if_pipelined(self.state.params)

    def gathered_standard_params(self):
        """Multi-process variant: allgather the global param values onto
        every host (collective — ALL processes must call together), then
        unstack. Gather happens on the stacked tree; eager indexing into
        a non-fully-addressable sharded array would raise."""
        from jax.experimental import multihost_utils

        # tiled=True: gather each array's GLOBAL value (the default
        # stacks a per-process leading axis and rejects global inputs).
        params = multihost_utils.process_allgather(self.state.params, tiled=True)
        if self._flat:
            return self._unravel(params)
        return self._unstack_if_pipelined(params)

    def _unstack_if_pipelined(self, params):
        if "blocks" in params:
            from gnot_tpu.parallel import pipeline

            params = pipeline.unstack_params(
                params, self.model.config.n_attn_layers
            )
        return params

    def _device_batch(self, batch: MeshBatch, *, stacked: bool = False) -> MeshBatch:
        """Place a host batch for the step: sharded over the mesh when
        distributed (cross-host assembly on multi-process runs).
        ``stacked=True`` for K-step stacked batches."""
        if self.mesh is None:
            return batch
        from gnot_tpu.parallel import mesh as mesh_lib, multihost

        if jax.process_count() > 1:
            return multihost.global_batch(self.mesh, batch, stacked=stacked)
        return mesh_lib.shard_batch(self.mesh, batch, stacked=stacked)

    def evaluate(self) -> float:
        if len(self.test_loader) == 0:
            # No test set: nothing to select a best checkpoint on
            # (np.mean([]) would propagate NaN into best-metric logic).
            return float("inf")
        # The SAME grouping iterator as the train loop (group_batches;
        # all-singles when steps_per_dispatch is 1 or the multi builder
        # is absent). In multi-process mode each batch is assembled
        # globally (_device_batch -> global_batch), so every process
        # computes the same full-test metric — no cross-host
        # aggregation needed.
        k = (
            self.config.train.steps_per_dispatch
            if self.multi_eval_step is not None
            else 1
        )
        # Ragged distributed test set: the final batch was padded with
        # repeats of the last sample (__init__); peel it off the grouped
        # iteration and score it per-sample so the repeats drop out. The
        # loader doesn't shuffle, so the tail is the last batch; divert
        # it while streaming (keeps the prefetch overlap — no list()).
        # Without a diverted tail, iterate the loader EXHAUSTIVELY —
        # truncating at len() would silently drop the final dispatch of
        # a PackedLoader whose first-fit packing needed one more row
        # group than the canonical count.
        it = iter(self.test_loader)
        stream = (
            itertools.islice(it, len(self.test_loader) - 1)
            if self._eval_tail
            else it
        )
        metrics: list[np.ndarray] = []
        for kind, item in group_batches(stream, k):
            if kind == "group":
                metrics.append(
                    np.asarray(
                        self.multi_eval_step(
                            self.state.params,
                            self._device_batch(stack_batches(item), stacked=True),
                        )
                    )
                )
            else:
                metrics.append(
                    np.asarray(
                        self.eval_step(self.state.params, self._device_batch(item))
                    )
                )
        if self._eval_tail:
            per = np.asarray(
                self._tail_eval_step(
                    self.state.params, self._device_batch(next(it))
                )
            )
            # The global batch concatenates per-host batches in process
            # order; each host contributed _eval_tail real samples then
            # repeats. Mean over the real rows == the batch-mean the
            # single-device ragged tail batch would produce.
            bs = self.config.data.batch_size
            real = np.concatenate(
                [
                    np.arange(p * bs, p * bs + self._eval_tail)
                    for p in range(jax.process_count())
                ]
            )
            metrics.append(np.mean(per[real]))
        return float(np.mean(np.concatenate([np.atleast_1d(m) for m in metrics])))

    def predict(self, samples) -> list[np.ndarray]:
        """Inference: per-sample UNPADDED model outputs ``[n_i, out_dim]``.

        A capability the reference lacks entirely (it writes
        ``best_model.pth`` and never reads it back, main.py:149-151;
        there is no inference entry point). Batches are padded/masked
        like eval; padding rows are sliced off before returning, so
        callers see exactly the ragged mesh they passed in. On a mesh,
        the tail batch is filled with repeats of the last sample so
        every batch shards evenly; the repeats are dropped on return.

        Inputs are validated up front (``data.batch.validate_samples``):
        oversize meshes against the trainer's fixed pad lengths AND
        non-finite coords/theta/targets/input-function values are
        rejected with the offending sample index — a NaN query must
        fail loudly, not poison its padded batchmates.

        The mechanics (validation, bucketed collate, forward, unpad
        slicing) live in ``serve.InferenceEngine`` — the SAME code path
        the request-serving layer dispatches through (docs/serving.md).

        Multi-process runs: the forward runs SHARDED on the mesh —
        params stay in their mesh layout (no host-side
        ``process_allgather``, which would not scale past toy sizes);
        only the output is replicated (an on-device collective over
        ICI). ALL processes must call predict together with the same
        samples: each host feeds its contiguous slice of every global
        batch and every process returns the full predictions.
        """
        return self.inference_engine().predict(samples)

    def inference_engine(self, dtype: str = "float32"):
        """The trainer's ``serve.InferenceEngine`` over its CURRENT
        params: layout-aware jitted forward (flat / stacked / standard,
        mesh-replicated outputs), the training data's fixed pad
        lengths, and the mesh batch-placement hook. Built once; params
        are re-published on every call so post-fit/restore weights are
        always what serves.

        ``dtype`` is the SERVING compute dtype (models/precision.py);
        the trainer's own params stay f32 — the engine publishes a
        cast copy. Standard param layout only: the flat/stacked
        layout-aware forwards are not threaded through the serve-model
        clone, so bf16 serving of those layouts fails with the flag to
        flip instead of silently serving f32."""
        multiproc = jax.process_count() > 1
        if self.state is None:
            self.initialize()
        if multiproc and self.mesh is None:
            raise ValueError(
                "multi-process predict() requires the distributed "
                "trainer (a mesh) — run with --distributed"
            )
        if dtype != "float32" and (
            self._flat or "blocks" in self.state.params
        ):
            raise ValueError(
                "serve.dtype='bfloat16' serves the standard param "
                "layout only; drop --scan_layers/--flat_params (or "
                "serve float32)"
            )
        if self._engine is not None and self._engine.dtype != dtype:
            # One engine cache, one serving dtype per trainer run: a
            # mid-process dtype flip would need a second jitted
            # forward + AOT table — rebuild instead of mixing them.
            self._engine = None
            self._forward = None
            self._forward_builder = None
        if self._forward is None:
            from gnot_tpu.models.precision import serve_model

            model = serve_model(self.model, dtype)
            if self._flat:
                unravel = self._unravel
                fwd = lambda params, batch: apply_batch(
                    model, unravel(params), batch
                )
            elif "blocks" in self.state.params:
                # Stacked layout (scan_layers / pipeline): run the
                # stacked forward on the params as-is — no unstack, and
                # no re-paying the per-depth compile that scan_layers
                # exists to avoid. Pipe-sharded block stacks gather
                # on-device under GSPMD (an ICI all-gather of ~MBs,
                # not a host collective).
                from gnot_tpu.parallel.pipeline import stacked_forward

                mc = model.config
                fwd = lambda params, batch: stacked_forward(mc, params, batch)
            else:
                fwd = lambda params, batch: apply_batch(model, params, batch)
            if self.mesh is not None:
                # Replicate the output so every host can read the full
                # prediction rows (multiproc) / no cross-shard fetches
                # are needed (single-process mesh).
                from jax.sharding import NamedSharding, PartitionSpec

                from gnot_tpu.serve.engine import rename_forward

                replicated = NamedSharding(self.mesh, PartitionSpec())
                self._forward_builder = lambda tag=None: jax.jit(
                    rename_forward(fwd, tag), out_shardings=replicated
                )
            else:
                from gnot_tpu.serve.engine import rename_forward

                self._forward_builder = lambda tag=None: jax.jit(
                    rename_forward(fwd, tag)
                )
            self._forward = self._forward_builder()
        if self._engine is None:
            from gnot_tpu.serve.engine import InferenceEngine

            self._engine = InferenceEngine(
                self.model,
                self.state.params,
                batch_size=self.config.data.batch_size,
                bucket=self.config.data.bucket,
                pad_nodes=self.train_loader.pad_nodes,
                pad_funcs=self.train_loader.pad_funcs,
                dtype=dtype,
                forward=self._forward,
                forward_builder=self._forward_builder,
                device_put=self._device_batch,
                group_pad=self.mesh is not None,
                n_proc=jax.process_count(),
                p_idx=jax.process_index(),
            )
        else:
            self._engine.swap_params(self.state.params)
        return self._engine

    def evaluate_from_checkpoint(self) -> float:
        """Restore the best checkpoint and run eval only — the load path
        the reference never had (it writes best_model.pth and never
        reads it back, main.py:149-151)."""
        if self.checkpointer is None:
            raise ValueError("eval-only mode needs --checkpoint_dir")
        if self.state is None:
            self.initialize()
        restored = self.checkpointer.restore_best(self.state)
        if restored is None:
            raise FileNotFoundError(
                f"no best checkpoint under {self.checkpointer.directory}"
            )
        self.state, epoch, best = restored
        res = self.evaluate()
        print(f"Eval (best checkpoint from epoch {epoch}): {res}")
        return res

    def _watchdog_loss_fn(self):
        """Scalar loss for the current layout — what the NaN watchdog
        re-executes under utils.debug.checked to localize the op."""
        if self._loss_fn is not None:
            return self._loss_fn
        model, loss_name = self.model, self.config.train.loss
        return lambda p, b: batch_loss(model, p, b, loss_name)

    def _handle_nonfinite_loss(self, step, epoch, loss, batch) -> None:
        """NaN watchdog (fires from TelemetryBuffer.drain on the first
        non-finite loss). With the recovery supervisor active
        (``train.recovery``) this raises the typed NonFiniteLossError
        the fit harness catches to roll back; otherwise it is the
        original hard abort: localize via a checkify re-run of the
        offending batch, record the event, and stop the run — training
        past a NaN only burns chips."""
        if self._supervisor is not None:
            from gnot_tpu.resilience.supervisor import NonFiniteLossError

            raise NonFiniteLossError(
                f"non-finite train loss at epoch {epoch}, step {step}",
                step=step, epoch=epoch, batch=batch,
            )
        self._abort_nonfinite(step, epoch, loss, batch)

    def _abort_nonfinite(self, step, epoch, loss, batch) -> None:
        """The hard abort (the recovery ladder's last rung, and the
        only rung when recovery is off). Multi-process runs skip the
        localization re-run (only process 0 would enter it: a one-host
        collective would hang the job before the error surfaces)."""
        detail = None
        if batch is not None and jax.process_count() == 1:
            from gnot_tpu.obs import health

            detail = health.localize_nan(
                self._watchdog_loss_fn(), self.state.params, batch
            )
        if self.metrics_sink is not None:
            self.metrics_sink.log(
                event=events.NON_FINITE_LOSS, step=step, epoch=epoch,
                loss=loss, detail=detail,
            )
            self.metrics_sink.flush()
        raise FloatingPointError(
            f"non-finite train loss at epoch {epoch}, step {step}"
            + (
                f" (checkify: {detail})"
                if detail
                else " (checkify re-run did not reproduce — the bad "
                     "value predates this step's forward)"
                if batch is not None and jax.process_count() == 1
                else ""
            )
        )

    def fit(self) -> float:
        if self.state is None:
            self.initialize()
        cfg = self.config
        if cfg.train.telemetry:
            from gnot_tpu.obs import health
            from gnot_tpu.obs import telemetry as obs_telemetry

            self._recompiles = health.RecompileMonitor()
            self._recompiles.register("train_step", self.train_step)
            self._recompiles.register("eval_step", self.eval_step)
            self._recompiles.register("multi_train_step", self.multi_train_step)
            self._recompiles.register("multi_eval_step", self.multi_eval_step)
            # Buffer on EVERY process (the health checks need the
            # replicated losses everywhere); only process 0 has a sink
            # and writes records.
            self._telemetry = obs_telemetry.TelemetryBuffer(
                self.metrics_sink,
                cfg.train.log_every,
                slow_step=health.SlowStepMonitor(),
                on_nonfinite=self._handle_nonfinite_loss,
                # Batch refs feed only the (single-process) checkify
                # localization; multi-process skips it, so don't pin a
                # drain window of padded batches per host for nothing.
                keep_batches=jax.process_count() == 1,
                metrics=self._metrics_registry,
            )
        import contextlib

        from gnot_tpu.resilience.preemption import PreemptionHandler
        from gnot_tpu.resilience.supervisor import (
            PreemptionRequested,
            RecoverySupervisor,
            RestoreEscalation,
        )

        self._supervisor = (
            RecoverySupervisor(
                snapshot_every=cfg.train.snapshot_every,
                max_rollbacks=cfg.train.max_rollbacks,
            )
            if cfg.train.recovery
            else None
        )
        preempt_cm = (
            PreemptionHandler(sync_every=cfg.train.preempt_sync_every)
            if cfg.train.graceful_preempt
            else contextlib.nullcontext()
        )
        # Trace the second executed epoch (warm jit caches), or the only
        # one if the run has a single epoch.
        trace_at = min(self.start_epoch + 1, cfg.train.epochs - 1)
        with preempt_cm as preempt:
            epoch = self.start_epoch
            while epoch < cfg.train.epochs:
                try:
                    self._fit_epoch(epoch, trace_at, preempt)
                except PreemptionRequested as stop:
                    self._preempt_save(stop)
                    break
                except RestoreEscalation as esc:
                    epoch = self._escalate_restore(esc)
                    continue
                if self._faults is not None and self._faults.stop_after_epoch(
                    epoch
                ):
                    # Simulated preemption (fault injection): exit the
                    # loop cleanly; the final wait() below commits
                    # in-flight saves.
                    print(f"Stopping after epoch {epoch} (--stop_after_epoch)")
                    break
                epoch += 1

        if self.checkpointer is not None:
            self.checkpointer.wait()  # flush in-flight async saves
        print(f"\nBest Test Metric: {self.best_metric}")
        return self.best_metric

    def _tspan(self, trace, name: str, **args):
        """One train-phase span under the epoch's trace — a
        nullcontext when tracing is off or this epoch was sampled out,
        so the untraced path pays one None check and nothing else."""
        if self._tracer is None or trace is None:
            return contextlib.nullcontext()
        return self._tracer.span(name, trace=trace, args=args or None)

    def _fit_epoch(self, epoch: int, trace_at: int, preempt) -> None:
        """One epoch — dispatch loop (under the recovery harness),
        eval, health checks, epoch record, checkpoint saves. With a
        tracer (--trace_path), the epoch is ONE trace (head-sampled
        here): an ``epoch`` root span with ``data_iter`` / ``step``
        (containing ``host_to_device`` + ``step_dispatch``) /
        ``telemetry_drain`` / ``eval`` / ``checkpoint_save`` phase
        children — docs/observability.md "Tracing"."""
        trace = (
            self._tracer.start_trace() if self._tracer is not None else None
        )
        with self._tspan(trace, "epoch", epoch=epoch):
            self._run_epoch(epoch, trace_at, preempt, trace)

    def _run_epoch(self, epoch: int, trace_at: int, preempt, trace) -> None:
        cfg = self.config
        # Shuffle order is a function of (seed, epoch): resumed runs
        # replay the continuous run's batch order exactly.
        self.train_loader.set_epoch(epoch)
        t0 = time.perf_counter()
        losses, points = [], 0
        k_dis = cfg.train.steps_per_dispatch

        def run_single(batch):
            if self._faults is not None:
                self._faults.maybe_sigterm(self.host_step + 1)
                batch = self._faults.poison_batch(batch, self.host_step + 1)
            lr = self.lr_fn(self.host_step, epoch)
            # The telemetry step returns (state, (loss, telem));
            # the plain step (state, loss) — one call site, the
            # unpack is the only difference.
            with self._tspan(trace, "step", step=self.host_step + 1) as sp:
                with self._tspan(trace, "host_to_device"):
                    device_batch = self._device_batch(batch)
                with self._tspan(trace, "step_dispatch"):
                    self.state, out = self.train_step(
                        self.state,
                        device_batch,
                        jnp.asarray(lr, jnp.float32),
                    )
            loss, telem = out if self._telemetry is not None else (out, None)
            self.host_step += 1
            losses.append(loss)
            if self._telemetry is not None:
                # Device arrays only — the buffer syncs at drains.
                self._telemetry.append(
                    steps=[self.host_step], epoch=epoch, lrs=[lr],
                    loss=loss, telem=telem, batches=[batch],
                    span_ids=[sp.span_id if sp is not None else None],
                )
            if cfg.train.debug_checks and not np.isfinite(
                float(np.asarray(loss))
            ):
                from gnot_tpu.resilience.supervisor import NonFiniteLossError

                # Deterministic guard (jax_debug_nans does not
                # reliably fire on warm jit paths); the
                # sync-per-step cost is the debug-build trade.
                # NonFiniteLossError IS a FloatingPointError, so
                # non-recovery callers see the original behavior;
                # with recovery on, the harness catches it.
                raise NonFiniteLossError(
                    f"non-finite train loss at epoch {epoch}, "
                    f"step {self.host_step}",
                    step=self.host_step, epoch=epoch, batch=batch,
                )
            if (
                self._telemetry is None
                and self.metrics_sink is not None
                and cfg.train.log_every
                and self.host_step % cfg.train.log_every == 0
            ):
                # float(loss) syncs; per-step logging is opt-in
                # and meant for coarse cadences. (With telemetry on
                # the buffer writes richer step records instead,
                # without the per-step sync.)
                self.metrics_sink.log(
                    step=self.host_step,
                    epoch=epoch,
                    loss=float(np.asarray(loss)),
                    lr=lr,
                )

        def run_group(group):
            if self._faults is not None:
                for i in range(len(group)):
                    self._faults.maybe_sigterm(self.host_step + 1 + i)
                group = [
                    self._faults.poison_batch(b, self.host_step + 1 + i)
                    for i, b in enumerate(group)
                ]
            # One dispatch for len(group) steps: stacked batches +
            # per-step LRs scanned on device (make_multi_train_step).
            lrs = [
                self.lr_fn(self.host_step + i, epoch)
                for i in range(len(group))
            ]
            with self._tspan(
                trace, "step", step=self.host_step + 1, k=len(group)
            ) as sp:
                with self._tspan(trace, "host_to_device"):
                    device_batches = self._device_batch(
                        stack_batches(group), stacked=True
                    )
                with self._tspan(trace, "step_dispatch"):
                    self.state, out = self.multi_train_step(
                        self.state,
                        device_batches,
                        jnp.asarray(lrs, dtype=jnp.float32),
                    )
            loss_k, telem_k = (
                out if self._telemetry is not None else (out, None)
            )
            start = self.host_step
            self.host_step += len(group)
            losses.append(loss_k)
            if self._telemetry is not None:
                # One stacked entry for the K scanned steps; the
                # drain unstacks after the (single) fetch.
                self._telemetry.append(
                    steps=list(range(start + 1, start + len(group) + 1)),
                    epoch=epoch, lrs=lrs, loss=loss_k, telem=telem_k,
                    batches=group,
                    span_ids=[sp.span_id if sp is not None else None]
                    * len(group),
                )
            if cfg.train.debug_checks and not np.all(
                np.isfinite(np.asarray(loss_k))
            ):
                from gnot_tpu.resilience.supervisor import NonFiniteLossError

                bad = int(
                    np.argmax(~np.isfinite(np.atleast_1d(np.asarray(loss_k))))
                )
                raise NonFiniteLossError(
                    f"non-finite train loss at epoch {epoch}, "
                    f"steps {start + 1}..{self.host_step}",
                    step=start + bad + 1, epoch=epoch, batch=group[bad],
                )
            if (
                self._telemetry is None
                and self.metrics_sink is not None
                and cfg.train.log_every
            ):
                host_lk = None
                for i in range(len(group)):
                    s = start + i + 1
                    if s % cfg.train.log_every == 0:
                        if host_lk is None:
                            host_lk = np.asarray(loss_k)  # one sync
                        self.metrics_sink.log(
                            step=s,
                            epoch=epoch,
                            loss=float(host_lk[i]),
                            lr=lrs[i],
                        )

        from gnot_tpu.resilience.supervisor import (
            NonFiniteLossError,
            PreemptionRequested,
        )

        sup = self._supervisor
        multiproc = jax.process_count() > 1
        quarantine: set[int] = set()
        resume_at = 0

        with profiling.trace_epoch(
            cfg.train.profile_dir, epoch, trace_at=trace_at
        ):
            with profiling.annotate("train_epoch"):
                if sup is not None:
                    sup.begin_epoch(self.state, host_step=self.host_step)
                while True:  # recovery attempts; single pass normally
                    # Re-pin the shuffle epoch EVERY attempt: __iter__
                    # advances the loader's epoch counter, so without
                    # this a rollback replay would shuffle with
                    # (seed, epoch+1) and the ordinal-based resume/
                    # quarantine skips would hit the wrong batches.
                    self.train_loader.set_epoch(epoch)
                    ordinal = -1
                    try:
                        # The SAME grouping iterator evaluate() uses
                        # (all-singles at k=1). Re-iterating after a
                        # rollback replays the epoch's deterministic
                        # (seed, epoch) order; already-done and
                        # quarantined dispatches are skipped.
                        batches = group_batches(self.train_loader, k_dis)
                        if trace is not None:
                            # data_iter spans: time spent WAITING on
                            # the loader (prefetch included) per pull.
                            batches = self._tracer.timed_iter(
                                batches, "data_iter", trace=trace
                            )
                        for ordinal, (kind, item) in enumerate(batches):
                            if ordinal < resume_at or ordinal in quarantine:
                                continue
                            start_step = self.host_step
                            if kind == "group":
                                points += sum(b.n_real_points for b in item)
                                run_group(item)
                            else:
                                points += item.n_real_points
                                run_single(item)
                            if sup is not None:
                                sup.after_dispatch(
                                    self.state, ordinal=ordinal,
                                    start_step=start_step,
                                    end_step=self.host_step,
                                    losses=losses, points=points,
                                    epoch=epoch,
                                )
                            if preempt is not None and preempt.should_stop(
                                multiprocess=multiproc
                            ):
                                raise PreemptionRequested(
                                    epoch, self.host_step
                                )
                        if self._telemetry is not None:
                            # Flush the partial window BEFORE eval:
                            # the NaN watchdog must fire before eval
                            # wastes a pass on a dead run, and the
                            # epoch boundary is a sync point anyway
                            # (train_loss fetch below).
                            with self._tspan(trace, "telemetry_drain"):
                                self._telemetry.drain()
                        if sup is not None:
                            # Epoch-end check: a NaN in the final
                            # partial snapshot window must not
                            # reach eval/checkpointing.
                            sup.check_losses(losses, epoch=epoch)
                        break
                    except NonFiniteLossError as err:
                        if sup is None:
                            raise
                        bad = (
                            err.ordinal
                            if err.ordinal is not None
                            else sup.ordinal_for_step(err.step)
                        )
                        if bad is None:
                            bad = ordinal  # the dispatch in flight
                        action = sup.plan(err)
                        if action == "restore":
                            from gnot_tpu.resilience.supervisor import (
                                RestoreEscalation,
                            )

                            raise RestoreEscalation(err)
                        if action == "abort":
                            self._abort_nonfinite(
                                err.step, err.epoch, None, err.batch
                            )
                        if self._telemetry is not None:
                            # Buffered records from rolled-back
                            # steps are bogus, and the NaN inside
                            # them must not re-fire the watchdog.
                            self._telemetry.discard()
                        snap = sup.rollback()
                        self.state = snap.state
                        self.host_step = snap.host_step
                        del losses[snap.n_losses :]
                        points = snap.points
                        quarantine.add(bad)
                        resume_at = snap.ordinal
                        print(
                            f"Recovery: non-finite loss at step "
                            f"{err.step} — rolled back to step "
                            f"{snap.host_step}, quarantined dispatch "
                            f"{bad} ({sup.rollbacks_used}/"
                            f"{sup.max_rollbacks} rollbacks used)"
                        )
                        if self.metrics_sink is not None:
                            self.metrics_sink.log(
                                event=events.ROLLBACK, epoch=epoch,
                                step=err.step, to_step=snap.host_step,
                                rollbacks_used=sup.rollbacks_used,
                            )
                            self.metrics_sink.log(
                                event=events.BATCH_QUARANTINED, epoch=epoch,
                                step=err.step, ordinal=bad,
                            )
            train_loss = float(
                np.mean(
                    np.concatenate(
                        [np.atleast_1d(np.asarray(l)) for l in losses]
                    )
                )
            ) if losses else float("nan")
            dt = time.perf_counter() - t0
            # Reference's exact console line (main.py:105).
            print(f"Epoch {epoch}, Loss: {train_loss}")

            with profiling.annotate("eval_epoch"), self._tspan(trace, "eval"):
                res = self.evaluate()
        print(f"Epoch {epoch}, Test Metric: {res}")
        print("-----------------------------------")

        if self._recompiles is not None:
            # First check baselines the warm-up compiles; later
            # positive deltas are recompiles (shape leaks).
            deltas = self._recompiles.check()
            if deltas:
                import logging

                logging.getLogger(__name__).warning(
                    "recompilation detected during epoch %d: %s "
                    "(shape leak? check bucketing and static args)",
                    epoch, deltas,
                )
                if self.metrics_sink is not None:
                    self.metrics_sink.log(
                        event=events.RECOMPILE, epoch=epoch,
                        **{f"compiles/{k}": v for k, v in deltas.items()},
                    )
        if self._telemetry is not None and jax.process_count() > 1:
            # Straggler gauge — COLLECTIVE, so every process calls
            # it; only process 0 (the sink owner) writes.
            from gnot_tpu.parallel import multihost

            per_host = multihost.per_host_gauge(
                dt / max(1, len(self.train_loader))
            )
            if self.metrics_sink is not None:
                self.metrics_sink.log(
                    event=events.HOST_SKEW, epoch=epoch,
                    step_time_per_host=per_host,
                    skew_s=float(per_host.max() - per_host.min()),
                )

        if self.metrics_sink is not None:
            self.metrics_sink.log(
                epoch=epoch,
                train_loss=train_loss,
                test_metric=res,  # sink serializes non-finite as null
                lr=self.lr_fn(self.host_step, epoch),
                points_per_sec=points / dt,
                epoch_seconds=dt,
            )
        if res < self.best_metric:
            self.best_metric = res
            if self.checkpointer is not None:
                with self._tspan(trace, "checkpoint_save", which="best"):
                    self.checkpointer.save_best(
                        self.state, epoch, self.best_metric
                    )
        if self.checkpointer is not None and (
            cfg.train.checkpoint_every
            and (epoch + 1) % cfg.train.checkpoint_every == 0
        ):
            with self._tspan(trace, "checkpoint_save", which="latest"):
                self.checkpointer.save_latest(
                    self.state, epoch + 1, self.best_metric
                )

    def _preempt_save(self, stop) -> None:
        """Graceful-preemption exit: save ``latest`` at the CURRENT
        epoch (resume replays the partial epoch on top of the saved
        params — at-least-once epoch semantics, docs/robustness.md),
        flush the sink, leave the run resume-ready."""
        print(
            f"Preemption: stopping at epoch {stop.epoch}, step {stop.step}"
            + (
                " — saving 'latest' and exiting resume-ready"
                if self.checkpointer is not None
                else " (no --checkpoint_dir: exiting without a save)"
            )
        )
        state = self.state
        if self._telemetry is not None:
            try:
                self._telemetry.drain()
            except FloatingPointError:
                # The final drain surfaced a NaN buried in the un-drained
                # window: the live state is poisoned. Save the last-good
                # snapshot instead (recovery on), or nothing — a 'latest'
                # full of NaNs would strand the resume either way.
                state = (
                    self._supervisor.last_good_state()
                    if self._supervisor is not None
                    else None
                )
                print(
                    "Preemption: non-finite loss in the final telemetry "
                    "window — "
                    + (
                        "saving the last-good recovery snapshot instead "
                        "of the poisoned live state"
                        if state is not None
                        else "NOT saving 'latest' (live state is poisoned "
                             "and no recovery snapshot exists)"
                    )
                )
        if self.checkpointer is not None and state is not None:
            self.checkpointer.save_latest(state, stop.epoch, self.best_metric)
            self.checkpointer.wait()
        if self.metrics_sink is not None:
            self.metrics_sink.log(
                event=events.PREEMPT_SAVE, epoch=stop.epoch, step=stop.step,
                resumable=self.checkpointer is not None and state is not None,
            )
            self.metrics_sink.flush()

    def _escalate_restore(self, esc) -> int:
        """Recovery ladder rung 2: the rollback budget is spent (or no
        clean snapshot exists) — restore the newest restorable
        checkpoint and re-enter the epoch loop at its epoch. No
        checkpointer / nothing restorable falls through to the hard
        abort (rung 3). Returns the epoch to continue from."""
        err = esc.cause
        if self._telemetry is not None:
            self._telemetry.discard()
        restored = (
            self.checkpointer.restore_latest(self.state)
            if self.checkpointer is not None
            else None
        )
        if restored is None:
            self._abort_nonfinite(err.step, err.epoch, None, err.batch)
        self.state, epoch, self.best_metric = restored
        self.host_step = int(self.state.step)
        print(
            f"Recovery: rollback budget exhausted — restored checkpoint "
            f"(epoch {epoch}); continuing"
        )
        if self.metrics_sink is not None:
            self.metrics_sink.log(
                event=events.RECOVERY_RESTORE, epoch=err.epoch, step=err.step,
                restored_epoch=epoch,
                restored_from=(self.checkpointer.last_restore or {}).get("dir"),
            )
        return epoch
