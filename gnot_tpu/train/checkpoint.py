"""Orbax checkpointing: a strict capability superset of the reference.

The reference saves only ``state_dict`` of the best-eval model to a
hardcoded ``best_model.pth`` and has no load path at all
(``/root/reference/main.py:149-151``; SURVEY.md §5). Here:

* ``best.<epoch>/`` — best-eval model (reference behavior), full train
  state;
* ``latest.<epoch>/`` — periodic checkpoint for preemption-safe
  ``--resume`` (TPU VMs are preemptible; resumability is the minimal
  failure-recovery story a TPU framework needs);
* ``best.json`` / ``latest.json`` sidecars with
  ``{epoch, best_metric, dir}`` naming the committed directory.

Crash-safety protocol: each save goes to a fresh epoch-suffixed
directory (never overwriting the previous committed one), and the meta
sidecar is written — atomically, via tmp + ``os.replace``, by process 0
only — strictly AFTER the async commit finalizes (at the next
wait/save). A crash anywhere in the window therefore leaves the old
meta pointing at the old, still-intact checkpoint; superseded
directories are pruned only once the new one is committed and named by
the sidecar.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(self, directory: str, extra_meta: dict | None = None):
        """``extra_meta`` is provenance recorded in every sidecar —
        notably the RESOLVED model numerics (gelu flavor, attention
        mode, dtype). The masked-mode default gelu changed erf->tanh in
        round 4, so a checkpoint's training-time flavor can differ from
        a later config's auto-resolution; restore warns on mismatch so
        the ~1e-3 activation shift never lands silently (pass --gelu
        explicitly to pin it)."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.extra_meta = dict(extra_meta or {})
        self._ckptr = ocp.StandardCheckpointer()
        # Saves kicked off but whose meta is not yet committed:
        # (name, meta dict, committed dir basename).
        self._pending: list[tuple[str, dict, str]] = []
        # Last published dir per name, tracked in memory on EVERY
        # process: the sidecar file is written by process 0 only, so
        # re-reading it from disk on other hosts (e.g. over NFS right
        # after a flush) can return a stale dir and desynchronize the
        # collective orbax save targets.
        self._published: dict[str, str] = {}

    # -- commit protocol ---------------------------------------------------

    def _flush_pending(self) -> None:
        """Commit sidecars for finished saves; prune superseded dirs.

        Call only after ``wait_until_finished()``: at that point every
        pending save's directory is finalized on disk.
        """
        for name, meta, dirname in self._pending:
            self._published[name] = dirname
            if jax.process_index() != 0:
                continue
            meta_path = os.path.join(self.directory, f"{name}.json")
            tmp = f"{meta_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, meta_path)
            for d in os.listdir(self.directory):
                full = os.path.join(self.directory, d)
                # d == name: a pre-upgrade unsuffixed checkpoint dir.
                if (
                    (d == name or d.startswith(f"{name}."))
                    and d != dirname
                    and os.path.isdir(full)
                ):
                    shutil.rmtree(full, ignore_errors=True)
        self._pending.clear()

    def _save(self, name: str, state: Any, epoch: int, best_metric: float) -> None:
        """Async save: waits for the PREVIOUS save (then publishes its
        sidecar), kicks off this one, and returns while it commits in
        the background — training overlaps the checkpoint write."""
        self._ckptr.wait_until_finished()
        self._flush_pending()
        dirname = f"{name}.{epoch}"
        # Resume-replay can revisit an epoch whose directory the
        # published sidecar already names; force=True would delete that
        # committed checkpoint at kickoff, so uniquify instead — the old
        # one stays restorable until the new commit's sidecar lands.
        published = self._published.get(name)
        if published is None:
            # First save this process lifetime: the on-disk sidecar (if
            # any) predates this run and is stable, so reading it is
            # safe on every host — unlike mid-run reads (see __init__).
            meta_path = os.path.join(self.directory, f"{name}.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    published = json.load(f).get("dir")
                    self._published[name] = published
        tick = 0
        while dirname == published:
            tick += 1
            dirname = f"{name}.{epoch}r{tick}"
        self._ckptr.save(os.path.join(self.directory, dirname), state, force=True)
        meta = {"epoch": epoch, "best_metric": best_metric, "dir": dirname}
        meta.update(self.extra_meta)
        self._pending.append((name, meta, dirname))

    def wait(self) -> None:
        """Block until any in-flight save has committed (and publish its
        sidecar)."""
        self._ckptr.wait_until_finished()
        self._flush_pending()

    def save_best(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("best", state, epoch, best_metric)

    def save_latest(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("latest", state, epoch, best_metric)

    # -- restore -----------------------------------------------------------

    def _restore(self, name: str, target: Any):
        self.wait()
        meta_path = os.path.join(self.directory, f"{name}.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        # Older checkpoints used an unsuffixed directory and no "dir" key.
        path = os.path.join(self.directory, meta.get("dir", name))
        if not os.path.isdir(path):
            return None
        mismatch = {
            k: (meta[k], v)
            for k, v in self.extra_meta.items()
            if k in meta and meta[k] != v
        }
        missing = [k for k in self.extra_meta if k not in meta]
        # State-LAYOUT provenance is its own message: a flat/tree
        # mismatch is not a numerics drift, and the orbax restore below
        # will fail on it with a tree-structure error — name the flag
        # first.
        layout_mismatch = mismatch.pop("flat_params", None)
        if "flat_params" in missing:
            # Sidecars predating layout provenance are tree-layout
            # checkpoints; only a flat-layout run needs the warning.
            missing.remove("flat_params")
            if self.extra_meta.get("flat_params"):
                layout_mismatch = (False, True)
        if jax.process_index() == 0:
            if layout_mismatch is not None:
                ck, cur = layout_mismatch
                print(
                    f"warning: '{name}' checkpoint was saved in the "
                    f"{'flat [P]-vector' if ck else 'standard tree'} state "
                    f"layout but this run uses the "
                    f"{'flat' if cur else 'tree'} layout — restore will "
                    "fail with a tree-structure mismatch; "
                    f"{'pass' if ck else 'drop'} --flat_params to match"
                )
            if mismatch:
                detail = ", ".join(
                    f"{k}: checkpoint={a!r} current={b!r}"
                    for k, (a, b) in mismatch.items()
                )
                print(
                    f"warning: restoring '{name}' checkpoint trained under "
                    f"different numerics ({detail}) — pass the matching flags "
                    "(e.g. --gelu) to reproduce its training-time behavior"
                )
            if missing:
                # Sidecar lacks some provenance keys (pre-round-5
                # checkpoints lack all of them; future key additions
                # leave older sidecars partially covered): the numerics
                # check cannot vouch for those keys, so say so — the
                # erf->tanh default flip is the canonical silent hazard.
                print(
                    f"note: '{name}' checkpoint sidecar has no recorded "
                    f"{'/'.join(missing)}; the numerics check cannot "
                    "verify them — if the run predates the tanh-GELU "
                    "default, pass --gelu erf to restore its "
                    "training-time activation"
                )
        state = self._ckptr.restore(path, target)
        return state, int(meta["epoch"]), float(meta["best_metric"])

    def restore_latest(self, target: Any):
        """Returns (state, epoch, best_metric) or None. Prefers the
        periodic ``latest`` checkpoint, falls back to ``best``."""
        return self._restore("latest", target) or self._restore("best", target)

    def restore_best(self, target: Any):
        return self._restore("best", target)
