"""Orbax checkpointing: a strict capability superset of the reference.

The reference saves only ``state_dict`` of the best-eval model to a
hardcoded ``best_model.pth`` and has no load path at all
(``/root/reference/main.py:149-151``; SURVEY.md §5). Here:

* ``best.<epoch>/`` — best-eval model (reference behavior), full train
  state;
* ``latest.<epoch>/`` — periodic checkpoint for preemption-safe
  ``--resume`` (TPU VMs are preemptible; resumability is the minimal
  failure-recovery story a TPU framework needs);
* ``best.json`` / ``latest.json`` sidecars with
  ``{epoch, best_metric, dir}`` naming the committed directory.

Crash-safety protocol: each save goes to a fresh epoch-suffixed
directory (never overwriting the previous committed one), and the meta
sidecar is written — atomically, via tmp + ``os.replace``, by process 0
only — strictly AFTER the async commit finalizes (at the next
wait/save). A crash anywhere in the window therefore leaves the old
meta pointing at the old, still-intact checkpoint; superseded
directories are pruned only once the new one is committed and named by
the sidecar.

I/O hardening (resilience/, docs/robustness.md): save kickoff, restore
reads, and sidecar writes retry transient OSErrors with exponential
backoff + jitter (``resilience.retry``); restore walks a FALLBACK
chain — the sidecar-named directory, then any other committed ``name.*``
directories newest-epoch-first, then the same for ``best`` — so a
truncated orbax dir, a missing sidecar, or a sidecar pointing at a
deleted dir degrades to an older checkpoint instead of crashing the
run. Which checkpoint actually restored is logged, recorded in
``last_restore`` (surfaced into the run manifest by main.py), and
emitted through ``on_event`` as a ``restore``/``restore_fallback``
sink record.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
from typing import Any, Callable

import jax
import orbax.checkpoint as ocp

from gnot_tpu.obs import events
from gnot_tpu.resilience.retry import RetryPolicy, retry_io

logger = logging.getLogger(__name__)


class Checkpointer:
    def __init__(
        self,
        directory: str,
        extra_meta: dict | None = None,
        *,
        fault_injector=None,
        retry_policy: RetryPolicy | None = None,
        on_event: Callable[..., None] | None = None,
    ):
        """``extra_meta`` is provenance recorded in every sidecar —
        notably the RESOLVED model numerics (gelu flavor, attention
        mode, dtype). The masked-mode default gelu changed erf->tanh in
        round 4, so a checkpoint's training-time flavor can differ from
        a later config's auto-resolution; restore warns on mismatch so
        the ~1e-3 activation shift never lands silently (pass --gelu
        explicitly to pin it)."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.extra_meta = dict(extra_meta or {})
        # Resilience wiring: the injector's ckpt_io budget fires at each
        # I/O attempt (inside the retry loop, so injected transients are
        # retried like real ones); on_event routes retry/fallback events
        # to the metrics sink (trainer-owned); last_restore records which
        # checkpoint a restore ACTUALLY used, for the run manifest.
        self.fault_injector = fault_injector
        self.retry_policy = retry_policy or RetryPolicy()
        self.on_event = on_event
        self.last_restore: dict | None = None
        self._ckptr = ocp.StandardCheckpointer()
        # Saves kicked off but whose meta is not yet committed:
        # (name, meta dict, committed dir basename).
        self._pending: list[tuple[str, dict, str]] = []
        # Last published dir per name, tracked in memory on EVERY
        # process: the sidecar file is written by process 0 only, so
        # re-reading it from disk on other hosts (e.g. over NFS right
        # after a flush) can return a stale dir and desynchronize the
        # collective orbax save targets.
        self._published: dict[str, str] = {}

    # -- hardened I/O ------------------------------------------------------

    def _io(self, op: str, fn, *, deadline: float | None = None):
        """Run one checkpoint-I/O operation under the retry policy.
        The fault injector (when armed) fires INSIDE the retried
        attempt, so injected transient errors exercise the same
        backoff path real ones do. ``deadline`` (absolute
        ``time.monotonic``) clamps the backoff sleeps so a
        deadline-bounded caller — a serving hot reload mid-traffic —
        never has its retries outlive it."""

        def attempt():
            if self.fault_injector is not None:
                self.fault_injector.maybe_io_error(op)
            return fn()

        def note(attempt_n: int, exc: BaseException) -> None:
            if self.on_event is not None:
                self.on_event(
                    event=events.IO_RETRY, op=op, attempt=attempt_n,
                    error=str(exc),
                )

        return retry_io(
            attempt, policy=self.retry_policy, describe=op, on_retry=note,
            deadline=deadline,
        )

    # -- commit protocol ---------------------------------------------------

    def _flush_pending(self) -> None:
        """Commit sidecars for finished saves; prune superseded dirs.

        Call only after ``wait_until_finished()``: at that point every
        pending save's directory is finalized on disk.
        """
        for name, meta, dirname in self._pending:
            self._published[name] = dirname
            if jax.process_index() != 0:
                continue
            meta_path = os.path.join(self.directory, f"{name}.json")

            def write_sidecar():
                tmp = f"{meta_path}.tmp"
                with open(tmp, "w") as f:
                    json.dump(meta, f)
                os.replace(tmp, meta_path)

            self._io(f"sidecar:{name}", write_sidecar)
            if self.fault_injector is not None:
                # corrupt_ckpt@EPOCH fires once the checkpoint is fully
                # committed and published — the torn-write shape the
                # restore fallback walk must survive.
                self.fault_injector.post_save(
                    name, os.path.join(self.directory, dirname),
                    int(meta.get("epoch", -1)),
                )
            for d in os.listdir(self.directory):
                full = os.path.join(self.directory, d)
                # d == name: a pre-upgrade unsuffixed checkpoint dir.
                if (
                    (d == name or d.startswith(f"{name}."))
                    and d != dirname
                    and os.path.isdir(full)
                ):
                    shutil.rmtree(full, ignore_errors=True)
        self._pending.clear()

    def _save(self, name: str, state: Any, epoch: int, best_metric: float) -> None:
        """Async save: waits for the PREVIOUS save (then publishes its
        sidecar), kicks off this one, and returns while it commits in
        the background — training overlaps the checkpoint write."""
        self._ckptr.wait_until_finished()
        self._flush_pending()
        # Copy the state before the async kickoff: the caller's buffers
        # get DONATED by the next train step while the background write
        # is still reading them (on CPU the writer sees zero-copy views
        # of the XLA buffers), which silently corrupts the checkpoint —
        # or the heap. The copy is device-side and async (no host
        # sync); its buffers are never donated, so the writer owns
        # stable data for as long as it needs.
        import jax.numpy as jnp

        state = jax.tree.map(jnp.copy, state)
        dirname = f"{name}.{epoch}"
        # Resume-replay can revisit an epoch whose directory the
        # published sidecar already names; force=True would delete that
        # committed checkpoint at kickoff, so uniquify instead — the old
        # one stays restorable until the new commit's sidecar lands.
        published = self._published.get(name)
        if published is None:
            # First save this process lifetime: the on-disk sidecar (if
            # any) predates this run and is stable, so reading it is
            # safe on every host — unlike mid-run reads (see __init__).
            meta_path = os.path.join(self.directory, f"{name}.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    published = json.load(f).get("dir")
                    self._published[name] = published
        tick = 0
        while dirname == published:
            tick += 1
            dirname = f"{name}.{epoch}r{tick}"
        # Retry the KICKOFF (directory creation, async-save scheduling)
        # against transient filesystem errors; the async commit itself
        # is orbax's, surfacing at the next wait().
        self._io(
            f"save:{name}",
            lambda: self._ckptr.save(
                os.path.join(self.directory, dirname), state, force=True
            ),
        )
        meta = {"epoch": epoch, "best_metric": best_metric, "dir": dirname}
        meta.update(self.extra_meta)
        self._pending.append((name, meta, dirname))

    def wait(self) -> None:
        """Block until any in-flight save has committed (and publish its
        sidecar)."""
        self._ckptr.wait_until_finished()
        self._flush_pending()

    def save_best(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("best", state, epoch, best_metric)

    def save_latest(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("latest", state, epoch, best_metric)

    # -- restore -----------------------------------------------------------

    #: Committed checkpoint directories: ``<name>.<epoch>`` plus the
    #: resume-replay uniquifier (``latest.3``, ``latest.3r1``, ...).
    _DIR_RE = re.compile(r"^(?P<name>[a-z]+)\.(?P<epoch>\d+)(?:r(?P<tick>\d+))?$")

    def _read_sidecar(self, name: str) -> dict | None:
        meta_path = os.path.join(self.directory, f"{name}.json")
        try:
            with open(meta_path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError) as exc:
            # A torn/unreadable sidecar is itself a corruption shape:
            # fall through to the on-disk directory scan.
            logger.warning("unreadable sidecar %s (%s); scanning dirs", meta_path, exc)
            return None

    def _candidates(self, name: str) -> list[tuple[str, dict, str]]:
        """Restore candidates for ``name``, in trust order: the
        sidecar-named directory (authoritative — a newer UNPUBLISHED dir
        on disk may be a torn commit), then every other committed
        ``name.*`` directory newest-epoch-first (their sidecar was lost:
        epoch comes from the dirname, best_metric degrades to +inf so
        the next eval re-establishes it). Returns (path, meta, via)."""
        cands: list[tuple[str, dict, str]] = []
        meta = self._read_sidecar(name)
        sidecar_dir = None
        if meta is not None:
            # Older checkpoints used an unsuffixed dir and no "dir" key.
            sidecar_dir = meta.get("dir", name)
            cands.append(
                (os.path.join(self.directory, sidecar_dir), meta, "sidecar")
            )
        try:
            entries = os.listdir(self.directory)
        except OSError:
            entries = []
        scanned = []
        for d in entries:
            m = self._DIR_RE.match(d)
            if (
                m is None
                or m.group("name") != name
                or d == sidecar_dir
                or not os.path.isdir(os.path.join(self.directory, d))
            ):
                continue
            scanned.append((int(m.group("epoch")), int(m.group("tick") or 0), d))
        for epoch, _, d in sorted(scanned, reverse=True):
            cands.append(
                (
                    os.path.join(self.directory, d),
                    {"epoch": epoch, "best_metric": float("inf")},
                    "scan",
                )
            )
        return cands

    def _restore(
        self,
        name: str,
        target: Any,
        *,
        requested: str | None = None,
        deadline: float | None = None,
    ):
        """Walk the candidate chain; the first directory orbax can
        restore (under the transient-error retry policy) wins. Records
        WHICH checkpoint restored in ``last_restore`` / the log / the
        event stream — the silent-fallback hazard this hardening
        exists to remove. ``requested`` names the checkpoint the CALLER
        asked for when this walk is already a fallback (restore_latest
        walking on to 'best'), so exactly ONE restore/restore_fallback
        event describes the whole restore. ``deadline`` bounds each
        attempt's retry backoff (resilience.retry). Returns (state,
        epoch, best_metric) or None when no candidate is restorable."""
        requested = requested or name
        self.wait()
        multiproc = jax.process_count() > 1
        tried: list[str] = []
        for path, meta, via in self._candidates(name):
            dirname = os.path.basename(path)
            if not os.path.isdir(path):
                # Sidecar pointing at a deleted dir (the crash-window
                # shape inverted): fall through to the scan candidates.
                tried.append(f"{dirname} (missing directory)")
                continue
            if multiproc:
                # The sharded restore is a cross-process COLLECTIVE:
                # hosts attempting different candidates (per-host
                # transient I/O desynchronizing the walk) would hang
                # the pod in the collective, not error. Agree on the
                # candidate first; divergence fails loudly instead.
                from gnot_tpu.parallel import multihost

                if not multihost.all_agree(f"{name}:{dirname}"):
                    raise RuntimeError(
                        f"checkpoint restore walk diverged across hosts "
                        f"(this host chose {dirname!r} for {name!r}); "
                        "per-host I/O failures left hosts seeing "
                        "different candidates — refusing the collective "
                        "restore that would hang the pod"
                    )
            layout_conflict = via == "sidecar" and self._warn_numerics(name, meta)
            state, failure = None, None
            try:
                state = self._io(
                    f"restore:{name}",
                    lambda p=path: self._ckptr.restore(p, target),
                    deadline=deadline,
                )
            except Exception as exc:  # noqa: BLE001 — any restore failure
                if layout_conflict:
                    # A flat/tree layout mismatch is the RUN's config,
                    # not storage corruption: every candidate shares the
                    # layout, so walking on would only bury the actionable
                    # error the warning above just named.
                    raise
                failure = exc
            if multiproc:
                # Outcome agreement (collective): if ANY host failed
                # this candidate, every host discards it and walks on
                # together — a lone success would leave that host
                # returning while the rest re-enter collectives.
                from gnot_tpu.parallel import multihost

                if multihost.sync_flag(failure is not None):
                    failure = failure or RuntimeError(
                        "another host failed to restore this candidate"
                    )
            if failure is not None:
                tried.append(f"{dirname} ({type(failure).__name__}: {failure})")
                logger.warning(
                    "restore of %s checkpoint %s failed (%s); trying next candidate",
                    name, dirname, failure,
                )
                continue
            # Copy before returning: restored arrays can be backed by
            # checkpoint-file buffers (zero-copy reads), and the trainer
            # DONATES its state to the compiled step — donating a
            # file-backed buffer corrupts the heap. The copy is device-
            # side and async; the copies are plain XLA buffers, safe to
            # donate.
            import jax.numpy as jnp

            state = jax.tree.map(jnp.copy, state)
            fallback = via != "sidecar" or bool(tried) or requested != name
            self.last_restore = {
                "requested": requested,
                "name": name,
                "dir": dirname,
                "epoch": int(meta["epoch"]),
                "best_metric": float(meta["best_metric"]),
                "fallback": fallback,
                "skipped": tried,
            }
            if jax.process_index() == 0:
                print(
                    f"Restored '{name}' checkpoint from {dirname} "
                    f"(epoch {int(meta['epoch'])})"
                    + (f" after skipping: {'; '.join(tried)}" if tried else "")
                )
            if self.on_event is not None:
                self.on_event(
                    event=events.RESTORE_FALLBACK if fallback else events.RESTORE,
                    **self.last_restore,
                )
            return state, int(meta["epoch"]), float(meta["best_metric"])
        if multiproc:
            # Exhaustion agreement: a host that ran out of candidates
            # while another still walks would leave that one hanging in
            # the candidate-agreement collective above.
            from gnot_tpu.parallel import multihost

            if not multihost.all_agree(f"{name}:<exhausted>"):
                raise RuntimeError(
                    f"checkpoint restore walk diverged across hosts: this "
                    f"host exhausted every '{name}' candidate while others "
                    "still see one — refusing the collective restore that "
                    "would hang the pod"
                )
        if tried and jax.process_index() == 0:
            print(
                f"warning: no restorable '{name}' checkpoint "
                f"(tried: {'; '.join(tried)})"
            )
        return None

    def _warn_numerics(self, name: str, meta: dict) -> bool:
        """Provenance checks against a sidecar's recorded numerics;
        returns True when a state-LAYOUT conflict (flat vs tree) was
        detected — the one mismatch that makes the orbax restore itself
        fail, which the caller must not paper over with fallbacks."""
        mismatch = {
            k: (meta[k], v)
            for k, v in self.extra_meta.items()
            if k in meta and meta[k] != v
        }
        missing = [k for k in self.extra_meta if k not in meta]
        # State-LAYOUT provenance is its own message: a flat/tree
        # mismatch is not a numerics drift, and the orbax restore below
        # will fail on it with a tree-structure error — name the flag
        # first.
        layout_mismatch = mismatch.pop("flat_params", None)
        if "flat_params" in missing:
            # Sidecars predating layout provenance are tree-layout
            # checkpoints; only a flat-layout run needs the warning.
            missing.remove("flat_params")
            if self.extra_meta.get("flat_params"):
                layout_mismatch = (False, True)
        if jax.process_index() == 0:
            if layout_mismatch is not None:
                ck, cur = layout_mismatch
                print(
                    f"warning: '{name}' checkpoint was saved in the "
                    f"{'flat [P]-vector' if ck else 'standard tree'} state "
                    f"layout but this run uses the "
                    f"{'flat' if cur else 'tree'} layout — restore will "
                    "fail with a tree-structure mismatch; "
                    f"{'pass' if ck else 'drop'} --flat_params to match"
                )
            if mismatch:
                detail = ", ".join(
                    f"{k}: checkpoint={a!r} current={b!r}"
                    for k, (a, b) in mismatch.items()
                )
                print(
                    f"warning: restoring '{name}' checkpoint trained under "
                    f"different numerics ({detail}) — pass the matching flags "
                    "(e.g. --gelu) to reproduce its training-time behavior"
                )
            if missing:
                # Sidecar lacks some provenance keys (pre-round-5
                # checkpoints lack all of them; future key additions
                # leave older sidecars partially covered): the numerics
                # check cannot vouch for those keys, so say so — the
                # erf->tanh default flip is the canonical silent hazard.
                print(
                    f"note: '{name}' checkpoint sidecar has no recorded "
                    f"{'/'.join(missing)}; the numerics check cannot "
                    "verify them — if the run predates the tanh-GELU "
                    "default, pass --gelu erf to restore its "
                    "training-time activation"
                )
        return layout_mismatch is not None

    def restore_latest(self, target: Any, *, deadline: float | None = None):
        """Returns (state, epoch, best_metric) or None. Prefers the
        periodic ``latest`` checkpoint (walking its fallback chain),
        then falls back to ``best`` — LOUDLY: which checkpoint actually
        restored is printed, recorded in ``last_restore`` (the manifest
        field), and emitted as a ``restore_fallback`` event, because a
        run silently restarting from ``best`` instead of ``latest``
        replays epochs the operator thinks are done. ``deadline``
        (absolute ``time.monotonic``) clamps the retry backoff of each
        I/O attempt — the serving hot-reload path's budget."""
        out = self._restore("latest", target, deadline=deadline)
        if out is not None:
            return out
        out = self._restore(
            "best", target, requested="latest", deadline=deadline
        )
        if out is not None and jax.process_index() == 0:
            print(
                "note: no restorable 'latest' checkpoint — resumed "
                f"from 'best' ({self.last_restore['dir']}, epoch "
                f"{self.last_restore['epoch']})"
            )
        return out

    def restore_best(self, target: Any, *, deadline: float | None = None):
        return self._restore("best", target, deadline=deadline)
