"""Orbax checkpointing: a strict capability superset of the reference.

The reference saves only ``state_dict`` of the best-eval model to a
hardcoded ``best_model.pth`` and has no load path at all
(``/root/reference/main.py:149-151``; SURVEY.md §5). Here:

* ``best/`` — best-eval model (reference behavior), full train state;
* ``latest/`` — periodic checkpoint for preemption-safe ``--resume``
  (TPU VMs are preemptible; resumability is the minimal failure-recovery
  story a TPU framework needs);
* JSON sidecar with ``{epoch, best_metric, step}``.
"""

from __future__ import annotations

import json
import os
from typing import Any

import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()

    def _save(self, name: str, state: Any, epoch: int, best_metric: float) -> None:
        """Async save: waits for the PREVIOUS save, then returns while
        this one commits in the background — training overlaps the
        checkpoint write. Orbax finalizes atomically (tmp dir + rename),
        so a crash mid-save never leaves a torn checkpoint at ``path``;
        ``_restore`` tolerates a meta file whose directory never landed."""
        path = os.path.join(self.directory, name)
        self._ckptr.wait_until_finished()
        self._ckptr.save(path, state, force=True)
        meta = {"epoch": epoch, "best_metric": best_metric}
        with open(os.path.join(self.directory, f"{name}.json"), "w") as f:
            json.dump(meta, f)

    def wait(self) -> None:
        """Block until any in-flight save has committed."""
        self._ckptr.wait_until_finished()

    def save_best(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("best", state, epoch, best_metric)

    def save_latest(self, state: Any, epoch: int, best_metric: float) -> None:
        self._save("latest", state, epoch, best_metric)

    def _restore(self, name: str, target: Any):
        self._ckptr.wait_until_finished()
        path = os.path.join(self.directory, name)
        meta_path = f"{path}.json"
        # Require both the meta sidecar and the committed directory: an
        # async save interrupted before finalize leaves meta without path.
        if not os.path.exists(meta_path) or not os.path.isdir(path):
            return None
        state = self._ckptr.restore(path, target)
        with open(meta_path) as f:
            meta = json.load(f)
        return state, int(meta["epoch"]), float(meta["best_metric"])

    def restore_latest(self, target: Any):
        """Returns (state, epoch, best_metric) or None. Prefers the
        periodic ``latest`` checkpoint, falls back to ``best``."""
        return self._restore("latest", target) or self._restore("best", target)

    def restore_best(self, target: Any):
        return self._restore("best", target)
