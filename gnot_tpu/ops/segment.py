"""Masked per-graph (per-sample) reductions and losses.

The reference computes per-graph losses with DGL segment pooling over a
batched graph (``/root/reference/loss.py:4-23``): a segment-sum keyed by
graph membership after the padded batch has been unpadded and concatenated
(``/root/reference/main.py:87-98``).

TPU-native form: keep everything padded/dense ``[B, L, C]`` and fold the
ragged structure into a 0/1 node mask — mathematically identical (the
sum over a graph's nodes == the masked sum over its padded row) but with
static shapes and zero host round-trips. No graph library is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def masked_segment_sum(values: Array, mask: Array) -> Array:
    """Per-sample masked sum over the length axis.

    Args:
      values: ``[B, L, C]``.
      mask: ``[B, L]`` 0/1.
    Returns:
      ``[B, C]`` — equivalent of DGL ``SumPooling`` over each graph.
    """
    return jnp.einsum("blc,bl->bc", values, mask.astype(values.dtype))


def masked_segment_mean(values: Array, mask: Array) -> Array:
    """Per-sample masked mean over the length axis (DGL ``AvgPooling``)."""
    s = masked_segment_sum(values, mask)
    n = jnp.sum(mask, axis=1).astype(values.dtype)
    return s / n[:, None]


def rel_l2_loss(predictions: Array, targets: Array, mask: Array) -> Array:
    """Per-graph relative L2, averaged over graphs and channels.

    Matches ``RelL2Loss`` (reference loss.py:19-23):
    ``mean_{g,c} sqrt( sum_l (p-t)^2 / sum_l t^2 )``.
    """
    num = masked_segment_sum((predictions - targets) ** 2, mask)
    den = masked_segment_sum(targets**2, mask)
    return jnp.mean(jnp.sqrt(num / den))


def mse_loss(predictions: Array, targets: Array, mask: Array) -> Array:
    """Per-graph node-mean of squared error, then mean over graphs and
    channels. Matches ``MSELoss`` (reference loss.py:9-12)."""
    per_graph = masked_segment_mean((predictions - targets) ** 2, mask)
    return jnp.mean(per_graph)


def rel_l2_per_sample(predictions: Array, targets: Array, mask: Array) -> Array:
    """``[B]`` per-graph relative L2 (channel-averaged) — the per-sample
    decomposition of ``rel_l2_loss``: the batch mean of this vector is
    the scalar loss (up to fp reduction order). Used by the distributed
    ragged-tail eval, which pads the last test batch with repeats and
    must drop them from the metric on the host."""
    num = masked_segment_sum((predictions - targets) ** 2, mask)
    den = masked_segment_sum(targets**2, mask)
    return jnp.mean(jnp.sqrt(num / den), axis=1)


def mse_per_sample(predictions: Array, targets: Array, mask: Array) -> Array:
    """``[B]`` per-graph node-mean squared error (channel-averaged)."""
    per_graph = masked_segment_mean((predictions - targets) ** 2, mask)
    return jnp.mean(per_graph, axis=1)


LOSSES = {"rel_l2": rel_l2_loss, "mse": mse_loss}
PER_SAMPLE_LOSSES = {"rel_l2": rel_l2_per_sample, "mse": mse_per_sample}


# --- Packed layout ("pack, don't pad" — multiple samples per row) --------


def packed_segment_sums(
    values: Array, mask: Array, node_seg: Array, n_seg: int
) -> Array:
    """Per-SEGMENT masked sums over a packed layout.

    Args:
      values: ``[R, L, C]`` packed rows.
      mask: ``[R, L]`` 0/1 token mask.
      node_seg: ``[R, N]`` chunk->segment ids (pad chunks = ``n_seg``).
      n_seg: static segment-slot count.
    Returns:
      ``[S, C]`` per-segment sums — the packed equivalent of
      ``masked_segment_sum``'s ``[B, C]``.
    """
    tok_seg = jnp.repeat(node_seg, values.shape[1] // node_seg.shape[1], axis=1)
    oh = jax.nn.one_hot(tok_seg, n_seg + 1, dtype=values.dtype)[..., :n_seg]
    oh = oh * mask[..., None].astype(values.dtype)
    return jnp.einsum("rlc,rls->sc", values, oh)


def _packed_counts(mask: Array, node_seg: Array, n_seg: int) -> Array:
    """``[S]`` real-token counts per segment (0 for empty slots)."""
    tok_seg = jnp.repeat(node_seg, mask.shape[1] // node_seg.shape[1], axis=1)
    oh = jax.nn.one_hot(tok_seg, n_seg + 1, dtype=jnp.float32)[..., :n_seg]
    return jnp.einsum("rl,rls->s", mask.astype(jnp.float32), oh)


def packed_rel_l2_per_seg(
    predictions: Array, targets: Array, mask: Array, node_seg: Array, n_seg: int
) -> tuple[Array, Array]:
    """``([S] metric, [S] valid)`` — per-segment relative L2 and a 0/1
    validity mask for empty slots (whose metric is defined as 0)."""
    num = packed_segment_sums((predictions - targets) ** 2, mask, node_seg, n_seg)
    den = packed_segment_sums(targets**2, mask, node_seg, n_seg)
    valid = (_packed_counts(mask, node_seg, n_seg) > 0).astype(num.dtype)
    # Double-where: empty slots have num == den == 0, and sqrt'(0) is
    # inf — masking only the VALUE would still propagate 0 * inf = nan
    # into the gradients. Substitute ratio 1 inside the sqrt for empty
    # slots, then zero the value.
    ratio = num / jnp.where(den == 0.0, 1.0, den)
    ratio = jnp.where(valid[:, None] > 0, ratio, 1.0)
    per = jnp.mean(jnp.sqrt(ratio), axis=1)
    return per * valid, valid


def packed_rel_l2_loss(
    predictions: Array, targets: Array, mask: Array, node_seg: Array, n_seg: int
) -> Array:
    """Mean per-sample relative L2 over the samples actually present in
    the packed dispatch — the packed counterpart of ``rel_l2_loss``
    (whose batch is always exactly B samples)."""
    per, valid = packed_rel_l2_per_seg(predictions, targets, mask, node_seg, n_seg)
    return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1.0)


def packed_mse_loss(
    predictions: Array, targets: Array, mask: Array, node_seg: Array, n_seg: int
) -> Array:
    """Packed counterpart of ``mse_loss``: per-segment node-mean squared
    error, mean over present segments and channels."""
    s = packed_segment_sums((predictions - targets) ** 2, mask, node_seg, n_seg)
    n = _packed_counts(mask, node_seg, n_seg)
    valid = (n > 0).astype(s.dtype)
    per = jnp.mean(s / jnp.maximum(n, 1.0)[:, None].astype(s.dtype), axis=1)
    return jnp.sum(per * valid) / jnp.maximum(jnp.sum(valid), 1.0)


PACKED_LOSSES = {"rel_l2": packed_rel_l2_loss, "mse": packed_mse_loss}
