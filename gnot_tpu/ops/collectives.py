"""Explicit collective schedules for shard_map code paths.

GSPMD code (the default XLA einsum path) never calls these — XLA picks
its own all-reduce schedule for the collectives it inserts. They exist
for the explicit shard_map paths (fused pallas attention, the pipeline),
where the schedule is ours to write.

``ring_allreduce`` is the classic ring schedule: S-1 ppermute hops, each
device forwarding the partial it last received while accumulating. For
GNOT's linear attention the sequence-sharded reduction payload is the
fixed-size ``[F, B, E, E]`` Gram accumulator (independent of sequence
length), so a single fused ``psum`` is already optimal and remains the
default; the ring form exists as an alternative schedule whose hops XLA
can overlap with independent compute between attention stages — and as
the honest demonstration that "ring attention" for a *linear* attention
degenerates to a ring all-reduce of partial sums (there is no O(steps)
K/V block rotation to do because no L x L score matrix exists;
SURVEY.md §5 long-context note).
"""

from __future__ import annotations

import jax

Array = jax.Array


def shard_map(f, mesh, in_specs, out_specs, *, axis_names=None, check_vma=True):
    """``jax.shard_map`` across jax versions — THE one entry point for
    this repo's explicit shard_map paths (pipeline, fused pallas
    attention). Newer jax exposes ``jax.shard_map`` with
    ``axis_names``/``check_vma``; 0.4.x ships
    ``jax.experimental.shard_map.shard_map`` with the complementary
    ``auto`` set and ``check_rep`` instead — same semantics, translated
    here so call sites stay on the modern spelling."""
    new = getattr(jax, "shard_map", None)
    if new is not None:
        kwargs = dict(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return new(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy

    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names is not None
        else frozenset()
    )
    return legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto,
    )


def ring_allreduce(x: Array, axis_name: str, axis_size: int) -> Array:
    """Sum ``x`` over ``axis_name`` with S-1 neighbor hops instead of a
    one-shot psum. Differentiable (scan over ppermute; ppermute
    transposes to the inverse permute)."""
    if axis_size <= 1:
        return x
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, _):
        acc, buf = carry
        buf = jax.lax.ppermute(buf, axis_name, perm)
        return (acc + buf, buf), None

    (acc, _), _ = jax.lax.scan(step, (x, x), None, length=axis_size - 1)
    return acc
