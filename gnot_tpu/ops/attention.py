"""Normalized (Galerkin-style) linear attention — the GNOT core op.

TPU-first formulation: everything is a batched einsum over ``[B, H, L, D]``
so XLA can tile the contractions onto the MXU; no ``L x L`` matrix is ever
materialized (the op is O(L * D^2 / H)).

Semantics mirror the reference implementation
(``/root/reference/model.py:53-107``):

* queries AND keys are softmax-normalized over the **feature** (head_dim)
  axis, not the sequence axis;
* the normalizer is ``alpha = 1 / sum_d(q_d * (sum_l k_ld))``;
* the output is ``alpha * q @ (k^T v)``.

Two masking modes:

* ``mask=None`` — *parity* mode. Zero-padded rows pass through the
  (biased) projections and pollute ``k_sum`` / ``k^T v`` exactly like the
  reference, whose padding is unmasked (``/root/reference/main.py:63-82``).
* ``mask=[B, Lk]`` — *masked* mode (the correct TPU-native default).
  Padded key rows are zeroed after the feature softmax, so they drop out
  of both reductions and the result is independent of pad length.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def feature_softmax(x: Array) -> Array:
    """Softmax over the trailing (head feature) axis in float32.

    The reference applies ``F.softmax(.., dim=-1)`` to per-head q/k
    (``/root/reference/model.py:59,72,93``). Computed in f32 regardless of
    input dtype — softmax in bf16 loses the normalization property that
    the alpha term relies on.
    """
    dtype = x.dtype
    out = jax.nn.softmax(x.astype(jnp.float32), axis=-1)
    return out.astype(dtype)


def _reduced_precision(*arrays: Array) -> bool:
    """True when any operand computes below float32 — the switch for
    the f32-accumulation path (models/precision.py policy: attention
    reductions and the normalizer NEVER accumulate in bf16). False for
    the all-f32 case, which keeps the historical ops byte-identical."""
    return any(a.dtype != jnp.float32 for a in arrays)


def normalized_linear_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    kv_mask: Array | None = None,
    eps: float = 0.0,
) -> Array:
    """Core normalized linear attention.

    Args:
      q: ``[B, H, Lq, D]`` — already feature-softmaxed queries.
      k: ``[B, H, Lk, D]`` — already feature-softmaxed keys.
      v: ``[B, H, Lk, D]`` — values (not normalized).
      kv_mask: optional ``[B, Lk]`` 0/1 mask; masked rows are removed from
        both ``k_sum`` and ``k^T v``.
      eps: optional denominator guard (0 to match the reference exactly).

    Returns:
      ``[B, H, Lq, D]`` attention output (pre residual / out-projection).
    """
    if kv_mask is not None:
        mk = kv_mask[:, None, :, None].astype(k.dtype)
        k = k * mk
        # v is multiplied implicitly via k in the k^T v contraction; no
        # need to mask v separately.

    # Reduced-precision inputs (bf16 serving): contractions accumulate
    # in f32 via explicit preferred_element_type, and the normalizer
    # (<q, k_sum> and the reciprocal) is f32 END TO END — the precision
    # policy (models/precision.py). The all-f32 path takes the
    # historical branch, byte-identical.
    lowp = _reduced_precision(q, k, v)
    acc = {"preferred_element_type": jnp.float32} if lowp else {}
    # k_sum over the sequence axis: [B, H, D]
    k_sum = jnp.sum(k, axis=2, dtype=jnp.float32) if lowp else jnp.sum(k, axis=2)
    # alpha = 1 / <q, k_sum> : [B, H, Lq, 1]
    denom = jnp.einsum("bhld,bhd->bhl", q, k_sum, **acc)
    if kv_mask is not None:
        # An all-masked key set (a record with an empty input function) has
        # k_sum == 0 exactly — softmaxed k rows are strictly positive, so
        # any unmasked row makes denom > 0. Select 1 there so the (also
        # exactly zero) numerator yields a clean 0 contribution instead of
        # inf * 0 = nan. No-op whenever at least one key survives the mask;
        # parity mode (kv_mask=None) is left untouched to match the
        # reference bit-for-bit.
        denom = jnp.where(denom == 0.0, 1.0, denom)
    alpha = 1.0 / (denom + eps)
    # k^T v : [B, H, D, D] — the hot MXU contraction.
    kv = jnp.einsum("bhld,bhle->bhde", k, v, **acc)
    out = jnp.einsum("bhld,bhde->bhle", q, kv, **acc)
    out = alpha[..., None] * out
    # Hand the block back its compute dtype (the f32 head casts at the
    # model level); alpha/out above stayed f32 through the reductions.
    return out.astype(q.dtype) if lowp else out


def segment_one_hot(seg: Array, n_seg: int, dtype=jnp.float32) -> Array:
    """``[.., N]`` chunk->segment ids -> ``[.., N, S]`` one-hot map with
    the pad slot (id ``n_seg``) sliced off. Computed ONCE per forward
    (outside any remat boundary — ``n_seg`` is a static int that must
    not become a tracer) and threaded as an array through the blocks."""
    return jax.nn.one_hot(seg, n_seg + 1, dtype=dtype)[..., :n_seg]


def packed_normalized_linear_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    q_seg_oh: Array,
    kv_seg_oh: Array,
    kv_mask: Array | None = None,
) -> Array:
    """Normalized linear attention over PACKED sequences.

    "Pack, don't pad": multiple samples (segments) share one sequence
    row, each occupying a contiguous chunk-aligned span, so ragged
    meshes stop paying bucket-padding FLOPs (~30% of tokens on the
    ragged benchmark configs). The linear-attention form makes exact
    per-segment attention cheap: ``k_sum`` and ``k^T v`` are sums over
    the sequence, so per-CHUNK partial Grams (the same total MXU work
    as the batched op) scatter-add into per-SEGMENT Grams with a tiny
    one-hot contraction, and each query chunk gathers its segment's
    Gram back. No token ever attends across segment boundaries; the
    result is exactly the per-sample computation (up to fp summation
    order).

    Args:
      q: ``[Bq, H, Lq, D]`` feature-softmaxed queries; ``Lq = Nq * C``.
      k: ``[Bk, H, Lk, D]`` feature-softmaxed keys; ``Lk = Nk * C``.
        The KEY rows may be a different packing than the query rows
        (cross-attention packs input functions separately) — segments
        are global ids shared by both sides.
      v: ``[Bk, H, Lk, D]`` values.
      q_seg_oh: ``[Bq, Nq, S]`` one-hot chunk->segment map
        (``segment_one_hot``); pad chunks have all-zero rows, so they
        scatter to and gather from nothing.
      kv_seg_oh: ``[Bk, Nk, S]`` likewise for the key/value chunks.
      kv_mask: optional ``[Bk, Lk]`` 0/1 token mask for intra-chunk
        padding (segment tails that don't fill their last chunk).

    Returns:
      ``[Bq, H, Lq, D]`` — rows aligned with ``q``.
    """
    bq, h, lq, d = q.shape
    bk, _, lk, _ = k.shape
    nq, nk = q_seg_oh.shape[-2], kv_seg_oh.shape[-2]
    if lq % nq or lk % nk:
        raise ValueError(
            f"sequence lengths {lq}/{lk} not divisible by chunk counts {nq}/{nk}"
        )
    cq, ck = lq // nq, lk // nk
    if kv_mask is not None:
        k = k * kv_mask[:, None, :, None].astype(k.dtype)

    # Reduced-precision inputs: every scatter/gather contraction below
    # accumulates in f32 (preferred_element_type) and the normalizer
    # stays f32 — the same precision policy as the unpacked op. The
    # all-f32 path is byte-identical to the historical einsums.
    lowp = _reduced_precision(q, k, v)
    acc = {"preferred_element_type": jnp.float32} if lowp else {}
    oh_k = kv_seg_oh.astype(jnp.float32 if lowp else k.dtype)  # [Bk,Nk,S]
    oh_q = q_seg_oh.astype(jnp.float32 if lowp else q.dtype)  # [Bq,Nq,S]

    kc = k.reshape(bk, h, nk, ck, d)
    vc = v.reshape(bk, h, nk, ck, d)
    # Per-chunk partial Grams / key sums: the SAME total contraction
    # work as the unpacked op, just summed chunkwise.
    kv_chunk = jnp.einsum("bhncd,bhnce->bhnde", kc, vc, **acc)  # [Bk,H,Nk,D,D]
    ks_chunk = (
        jnp.sum(kc, axis=3, dtype=jnp.float32) if lowp else jnp.sum(kc, axis=3)
    )  # [Bk,H,Nk,D]
    # Scatter-add into global per-segment Grams (tiny contractions).
    kv_seg_gram = jnp.einsum("bns,bhnde->shde", oh_k, kv_chunk, **acc)  # [S,H,D,D]
    ks_seg_sum = jnp.einsum("bns,bhnd->shd", oh_k, ks_chunk, **acc)  # [S,H,D]
    # Gather each query chunk's segment Gram / key sum.
    kv_q = jnp.einsum("bns,shde->bhnde", oh_q, kv_seg_gram, **acc)  # [Bq,H,Nq,D,D]
    ks_q = jnp.einsum("bns,shd->bhnd", oh_q, ks_seg_sum, **acc)  # [Bq,H,Nq,D]

    qc = q.reshape(bq, h, nq, cq, d)
    denom = jnp.einsum("bhncd,bhnd->bhnc", qc, ks_q, **acc)
    # Pad chunks/tokens and empty segments have denom == 0 exactly
    # (softmaxed k rows are strictly positive — same argument as the
    # masked unpacked op); select 1 for a clean 0 output there.
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = jnp.einsum("bhncd,bhnde->bhnce", qc, kv_q, **acc)
    out = out / denom[..., None]
    out = out.reshape(bq, h, lq, d)
    return out.astype(q.dtype) if lowp else out


def split_heads(x: Array, n_head: int) -> Array:
    """``[B, L, E] -> [B, H, L, E/H]`` (reference model.py:57-58)."""
    b, l, e = x.shape
    x = x.reshape(b, l, n_head, e // n_head)
    return x.transpose(0, 2, 1, 3)


def merge_heads(x: Array) -> Array:
    """``[B, H, L, D] -> [B, L, H*D]`` (reference model.py:81,83)."""
    b, h, l, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, l, h * d)
