"""Pallas TPU kernel: fused normalized linear attention.

The XLA path (``gnot_tpu.ops.attention``) splits heads into a
``[B, H, L, D]`` layout (D = 32 at reference defaults) and materializes
the feature softmaxes, masked keys, ``k_sum``, ``k^T v`` and the
normalizer between fused regions. On TPU that layout is hostile: D=32
in the lane axis wastes 3/4 of every 128-lane tile (VMEM and VPU), and
the transposes for split/merge are extra HBM passes.

This kernel keeps the **merged-head layout** ``[L, E]`` (E = H*D, 256 at
defaults) end-to-end and expresses every per-head operation as a
lane-group operation:

* per-head feature softmax == softmax within each D-lane group. A
  shared per-row max is subtracted (any per-row constant cancels inside
  each group's ratio), then group sums come from one ``[L,E] x [E,E]``
  matmul with a block-diagonal ones matrix — an MXU op, not a lane
  shuffle;
* per-head ``k^T v`` == the block-diagonal part of the full ``[E, E]``
  contraction. We compute the full Gram matrix (perfectly MXU-shaped)
  and mask off the cross-head blocks;
* the ``1/<q, k_sum>`` normalizer per head broadcasts to its lane group
  through the same block-diagonal matmul.

Two kernels pipeline over sequence tiles so VMEM stays bounded at any
length (Heatsink3d-scale point clouds included):

1. ``_reduce_kernel`` — grid ``(B, F, Lk/TILE)``: accumulates the masked
   ``k^T v`` Gram matrix ``[E, E]`` and ``k_sum [1, E]`` per (batch,
   input-function) into revisited output blocks.
2. ``_apply_kernel`` — grid ``(B, L/TILE, F)``: softmaxes the query tile
   (the tile's HBM fetch is shared across the F innermost steps; the
   cheap softmax itself is recomputed per F), applies the Gram matrix
   and normalizer, and emits both the attention output and softmax(q) —
   GNOT's residual adds the *softmaxed* query (reference
   ``/root/reference/model.py:86,104``), so downstream needs it.

Semantics match ``feature_softmax`` + ``normalized_linear_attention``
composed over heads (reference ``/root/reference/model.py:53-107``);
outputs come back head-merged exactly as ``merge_heads`` would produce
(the non-parity merge — parity mode's interleaved merge stays on the
XLA path).

The backward pass recomputes the forward in einsum form and
differentiates that (rematerialization — the standard TPU trade of
FLOPs for HBM bandwidth).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

TILE = 256  # sequence tile: M dim of every matmul, multiple of all buckets


def _interpret_default() -> bool:
    """Compiled on TPU; interpreter on CPU (tests). Other backends must
    opt in explicitly — silently emulating on, say, GPU would be an
    orders-of-magnitude perf trap."""
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "cpu":
        return True
    raise ValueError(
        f"attention_impl='pallas' supports tpu (compiled) and cpu "
        f"(interpreted) backends, not {backend!r}; use attention_impl='xla'"
    )


def _block_diag_mask(e: int, d: int, dtype=jnp.float32) -> Array:
    """[E, E] with 1 inside each head's DxD diagonal block."""
    r = jax.lax.broadcasted_iota(jnp.int32, (e, e), 0) // d
    c = jax.lax.broadcasted_iota(jnp.int32, (e, e), 1) // d
    return (r == c).astype(dtype)


def _group_softmax(x: Array, n_head: int) -> Array:
    """Per-head (lane-group) softmax of ``[T, E]`` rows.

    Subtracting the shared per-row max is safe: within each head's group
    the constant cancels from the exp ratio. Group sums are computed by
    one MXU matmul with the block-diagonal ones matrix.
    """
    e = x.shape[-1]
    ex = jnp.exp(x - jnp.max(x, axis=-1, keepdims=True))
    gsum = jax.lax.dot_general(
        ex,
        _block_diag_mask(e, e // n_head),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return ex / gsum


def _reduce_kernel(k_ref, v_ref, m_ref, kv_ref, ksum_ref, *, n_head):
    lk_i = pl.program_id(2)

    @pl.when(lk_i == 0)
    def _():
        kv_ref[0, 0] = jnp.zeros_like(kv_ref[0, 0])
        ksum_ref[0, 0] = jnp.zeros_like(ksum_ref[0, 0])

    k = k_ref[0, 0].astype(jnp.float32)  # [T, E]
    v = v_ref[0, 0].astype(jnp.float32)  # [T, E]
    m = m_ref[0, 0].astype(jnp.float32)  # [T, 1]
    ks = _group_softmax(k, n_head) * m
    kv_ref[0, 0] += jax.lax.dot_general(  # k^T v Gram tile: [E, E]
        ks, v, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ksum_ref[0, 0] += jnp.sum(ks, axis=0, keepdims=True)


def _apply_kernel(q_ref, kv_ref, ksum_ref, out_ref, qs_ref, *, n_head):
    f_i = pl.program_id(2)
    e = q_ref.shape[-1]
    bd = _block_diag_mask(e, e // n_head)

    qs = _group_softmax(q_ref[0].astype(jnp.float32), n_head)  # [T, E]

    @pl.when(f_i == 0)
    def _():
        qs_ref[0] = qs.astype(qs_ref.dtype)

    kv = kv_ref[0, 0] * bd  # keep only each head's diagonal block
    ksum = ksum_ref[0, 0]  # [1, E]
    # Per-head <q, k_sum>, broadcast back to the head's lanes: [T, E].
    denom = jax.lax.dot_general(
        qs * ksum, bd, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    out = (
        jnp.dot(qs, kv, preferred_element_type=jnp.float32) / denom
    )
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _seq_pad(n: int) -> tuple[int, int]:
    """(padded_length, tile): tile the sequence dim, sublane-aligned."""
    if n >= TILE:
        return _round_up(n, TILE), TILE
    t = _round_up(n, 8)
    return t, t


def _fused_nla_call(q, k, v, mask, n_head: int, interpret: bool):
    b, l, e = q.shape
    f, _, lk, _ = k.shape
    lp, tl = _seq_pad(l)
    lkp, tlk = _seq_pad(lk)

    # Pad sequence dims to tile multiples. Padded key rows get mask 0, so
    # they vanish from the reductions; padded query rows are sliced off.
    qp = jnp.pad(q, ((0, 0), (0, lp - l), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, lkp - lk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, lkp - lk), (0, 0)))
    mp = jnp.pad(mask, ((0, 0), (0, 0), (0, lkp - lk)))[..., None]  # [F,B,Lkp,1]

    kv, ksum = pl.pallas_call(
        functools.partial(_reduce_kernel, n_head=n_head),
        grid=(b, f, lkp // tlk),
        in_specs=[
            pl.BlockSpec((1, 1, tlk, e), lambda bi, fi, li: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tlk, e), lambda bi, fi, li: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tlk, 1), lambda bi, fi, li: (fi, bi, li, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, e, e), lambda bi, fi, li: (fi, bi, 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, fi, li: (fi, bi, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((f, b, e, e), jnp.float32),
            jax.ShapeDtypeStruct((f, b, 1, e), jnp.float32),
        ),
        interpret=interpret,
    )(kp, vp, mp)

    out, qs = pl.pallas_call(
        functools.partial(_apply_kernel, n_head=n_head),
        grid=(b, lp // tl, f),
        in_specs=[
            pl.BlockSpec((1, tl, e), lambda bi, li, fi: (bi, li, 0)),
            pl.BlockSpec((1, 1, e, e), lambda bi, li, fi: (fi, bi, 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, li, fi: (fi, bi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, tl, e), lambda bi, li, fi: (fi, bi, li, 0)),
            pl.BlockSpec((1, tl, e), lambda bi, li, fi: (bi, li, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((f, b, lp, e), q.dtype),
            jax.ShapeDtypeStruct((b, lp, e), q.dtype),
        ),
        interpret=interpret,
    )(qp, kv, ksum)

    return out[:, :, :l], qs[:, :l]


def _reference_impl(q, k, v, mask, n_head: int):
    """Einsum formulation in the merged-head layout with the kernel's f32
    semantics — backward-pass source and test oracle."""

    def gsm(x):
        shaped = x.reshape(*x.shape[:-1], n_head, x.shape[-1] // n_head)
        return jax.nn.softmax(shaped.astype(jnp.float32), axis=-1)

    qs = gsm(q)  # [B, L, H, D]
    ks = gsm(k) * mask[..., None, None]  # [F, B, Lk, H, D]
    vh = v.reshape(*v.shape[:-1], n_head, v.shape[-1] // n_head).astype(jnp.float32)
    k_sum = jnp.sum(ks, axis=2)  # [F, B, H, D]
    denom = jnp.einsum("blhd,fbhd->fblh", qs, k_sum)
    kv = jnp.einsum("fblhd,fblhe->fbhde", ks, vh)
    out = jnp.einsum("blhd,fbhde->fblhe", qs, kv) / denom[..., None]
    out = out.reshape(*out.shape[:-2], -1)  # merge heads: [F, B, L, E]
    return out.astype(q.dtype), qs.reshape(*q.shape).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_nla(
    q: Array,
    k: Array,
    v: Array,
    mask: Array,
    n_head: int,
    interpret: bool | None = None,
):
    """Fused normalized linear attention in the merged-head layout.

    Args:
      q: ``[B, L, E]`` raw projected queries (pre-softmax, heads merged).
      k: ``[F, B, Lk, E]`` raw keys, one slab per input function
        (``F=1`` for self-attention).
      v: ``[F, B, Lk, E]`` values.
      mask: ``[F, B, Lk]`` 0/1 key mask (pass ones for unmasked).
      n_head: number of heads (E must be divisible by it).
      interpret: force pallas interpreter mode; ``None`` auto-selects
        (compiled on TPU, interpreted on CPU for tests).

    Returns:
      ``(out [F, B, L, E], q_softmaxed [B, L, E])``, both head-merged.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _fused_nla_call(q, k, v, mask, n_head, interpret)


def _fused_nla_fwd(q, k, v, mask, n_head, interpret):
    interpret = _interpret_default() if interpret is None else interpret
    return _fused_nla_call(q, k, v, mask, n_head, interpret), (q, k, v, mask)


def _fused_nla_bwd(n_head, interpret, residuals, cotangents):
    del interpret
    q, k, v, mask = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_impl(q_, k_, v_, mask, n_head), q, k, v
    )
    dq, dk, dv = vjp(cotangents)
    return dq, dk, dv, jnp.zeros_like(mask)


fused_nla.defvjp(_fused_nla_fwd, _fused_nla_bwd)
