"""Pallas TPU kernels: fused normalized linear attention.

The XLA path (``gnot_tpu.ops.attention``) splits heads into a
``[B, H, L, D]`` layout (D = 32 at reference defaults) and materializes
the feature softmaxes, masked keys, ``k_sum``, ``k^T v`` and the
normalizer between fused regions. On TPU that layout is hostile: D=32
in the lane axis wastes 3/4 of every 128-lane tile (VMEM and VPU), and
the transposes for split/merge are extra HBM passes.

These kernels keep the **merged-head layout** ``[L, E]`` (E = H*D, 256
at defaults) end-to-end and express every per-head operation as a
lane-group operation:

* per-head feature softmax == softmax within each D-lane group,
  statically unrolled over head lane-slices with a per-group max (so
  every group's exps are anchored at 1 — no cross-head underflow);
* per-head ``k^T v`` == the block-diagonal part of the full ``[E, E]``
  contraction. We accumulate the full Gram matrix (perfectly
  MXU-shaped) and mask off the cross-head blocks at apply time;
* the ``1/<q, k_sum>`` normalizer per head broadcasts to its lane group
  through the same block-diagonal matmul.

The op is split into two composable stages, each a pallas kernel with a
``custom_vjp`` (backward recomputes in einsum form — the standard TPU
rematerialization trade of FLOPs for HBM):

1. ``nla_reduce`` — grid ``(B, F, Lk/TILE)``: accumulates the masked
   ``k^T v`` Gram matrix ``[E, E]`` and ``k_sum [1, E]`` per (batch,
   input-function) into revisited output blocks.
2. ``nla_apply`` — grid ``(B, L/TILE, F)``: softmaxes the query tile
   (the tile's HBM fetch is shared across the F innermost steps; the
   cheap softmax itself is recomputed per F), applies the Gram matrix
   and normalizer, and emits both the attention output and softmax(q) —
   GNOT's residual adds the *softmaxed* query (reference
   ``/root/reference/model.py:86,104``), so downstream needs it.

``fused_nla`` composes them on one device. ``fused_nla_sp`` is the
long-context / sequence-parallel form: because linear attention's
sequence reduction is a sum, SP needs exactly ONE ``psum`` of the
``[E, E]`` Gram accumulators over the sequence mesh axis — a fixed-size
collective independent of sequence length, strictly cheaper than ring
attention's O(steps) rotation of K/V blocks (SURVEY.md §5 long-context
note). Autodiff flows through ``shard_map`` + ``psum`` and the
per-stage VJPs compose correctly.

Semantics match ``feature_softmax`` + ``normalized_linear_attention``
composed over heads (reference ``/root/reference/model.py:53-107``);
outputs come back head-merged exactly as ``merge_heads`` would produce
(the non-parity merge — parity mode's interleaved merge stays on the
XLA path).

``nla_reduce_seg`` / ``nla_apply_seg`` / ``fused_nla_packed`` are the
SEGMENT-AWARE forms for packed ragged execution ("pack, don't pad"):
with the kernel tile pinned to the packing chunk, segment structure is
carried as prefetched scalar index tables (the grouped-matmul idiom)
and packed sequences can never attend across segment boundaries. One
segment-aware kernel pays off across the physics-attention family —
Transolver's framing (PAPERS.md, arXiv 2511.06294) shows the same
linear-attention reduction recurs in every planned variant.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

Array = jax.Array

TILE = 256  # preferred sequence tile (matmul M dim); _seq_pad may drop
# to 128 so the 1.5x buckets (384, 768, 1536, ...) don't re-pad by 33%.


def _interpret_default() -> bool:
    """Compiled on TPU; interpreter on CPU (tests). Other backends must
    not silently fall into interpret mode — an orders-of-magnitude perf
    trap."""
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend == "cpu":
        return True
    raise ValueError(
        f"attention_impl='pallas' supports tpu (compiled) and cpu "
        f"(interpreted) backends, not {backend!r}; use attention_impl='xla'"
    )


def _block_diag_mask(e: int, d: int, dtype=jnp.float32) -> Array:
    """[E, E] with 1 inside each head's DxD diagonal block."""
    r = jax.lax.broadcasted_iota(jnp.int32, (e, e), 0) // d
    c = jax.lax.broadcasted_iota(jnp.int32, (e, e), 1) // d
    return (r == c).astype(dtype)


def _group_softmax(x: Array, n_head: int) -> Array:
    """Per-head (lane-group) softmax of ``[T, E]`` rows.

    The max is computed per group, not per row: a shared row max cancels
    in exact arithmetic, but a head whose logits sit ~87+ below another
    head's spike would underflow every exp in its group to 0 and divide
    0/0. With the per-group max each group contains an exact
    ``exp(0) == 1``, so the group sum is always >= 1. Statically
    unrolled over head lane-slices (a ``[T,E]->[T,H,D]`` reshape does
    not lower in Mosaic; D-lane slices do), with the sum and divide kept
    per slice too — no cross-head matmul needed.
    """
    e = x.shape[-1]
    d = e // n_head
    parts = []
    for i in range(n_head):
        s = x[:, i * d : (i + 1) * d]
        ex = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
        parts.append(ex / jnp.sum(ex, axis=-1, keepdims=True))
    return jnp.concatenate(parts, axis=-1)


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _seq_pad(n: int) -> tuple[int, int]:
    """(padded_length, tile): tile the sequence dim, sublane-aligned.

    Prefers TILE; falls back to TILE/2 when that avoids re-padding
    (Loader buckets include 1.5x-of-power-of-two lengths like 384)."""
    if n >= TILE:
        lp = _round_up(n, TILE // 2)
        tile = TILE if lp % TILE == 0 else TILE // 2
        return lp, tile
    t = _round_up(n, 8)
    return t, t


# --------------------------------------------------------------------------
# Stage 1: reduce — masked group-softmax(k)^T v Gram + k_sum accumulation.
# --------------------------------------------------------------------------


def _reduce_kernel(k_ref, v_ref, m_ref, kv_ref, ksum_ref, *, n_head):
    lk_i = pl.program_id(2)

    @pl.when(lk_i == 0)
    def _():
        kv_ref[0, 0] = jnp.zeros_like(kv_ref[0, 0])
        ksum_ref[0, 0] = jnp.zeros_like(ksum_ref[0, 0])

    k = k_ref[0, 0].astype(jnp.float32)  # [T, E]
    v = v_ref[0, 0].astype(jnp.float32)  # [T, E]
    m = m_ref[0, 0].astype(jnp.float32)  # [T, 1]
    ks = _group_softmax(k, n_head) * m
    kv_ref[0, 0] += jax.lax.dot_general(  # k^T v Gram tile: [E, E]
        ks, v, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ksum_ref[0, 0] += jnp.sum(ks, axis=0, keepdims=True)


def _reduce_call(k, v, mask, n_head: int, interpret: bool):
    f, b, lk, e = k.shape
    lkp, tlk = _seq_pad(lk)
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, lkp - lk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, lkp - lk), (0, 0)))
    # Padded key rows get mask 0, so they vanish from the reductions.
    mp = jnp.pad(mask, ((0, 0), (0, 0), (0, lkp - lk)))[..., None]  # [F,B,Lkp,1]

    return pl.pallas_call(
        functools.partial(_reduce_kernel, n_head=n_head),
        grid=(b, f, lkp // tlk),
        in_specs=[
            pl.BlockSpec((1, 1, tlk, e), lambda bi, fi, li: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tlk, e), lambda bi, fi, li: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tlk, 1), lambda bi, fi, li: (fi, bi, li, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, e, e), lambda bi, fi, li: (fi, bi, 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, fi, li: (fi, bi, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((f, b, e, e), jnp.float32),
            jax.ShapeDtypeStruct((f, b, 1, e), jnp.float32),
        ),
        interpret=interpret,
    )(kp, vp, mp)


def _reduce_ref(k, v, mask, n_head: int):
    """Einsum form of the reduce stage (backward source + test oracle)."""

    def gsm(x):
        shaped = x.reshape(*x.shape[:-1], n_head, x.shape[-1] // n_head)
        sm = jax.nn.softmax(shaped.astype(jnp.float32), axis=-1)
        return sm.reshape(x.shape)

    ks = gsm(k) * mask[..., None]  # [F, B, Lk, E]
    kv = jnp.einsum("fbld,fble->fbde", ks, v.astype(jnp.float32))
    ksum = jnp.sum(ks, axis=2, keepdims=True)  # [F, B, 1, E]
    return kv, ksum


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def nla_reduce(k: Array, v: Array, mask: Array, n_head: int, interpret: bool | None = None):
    """Masked Gram accumulation: ``(kv [F,B,E,E], k_sum [F,B,1,E])`` in f32.

    Sequence-parallel note: ``kv``/``k_sum`` are plain sums over Lk, so
    partial results from sequence shards combine with one ``psum``.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _reduce_call(k, v, mask, n_head, interpret)


def _nla_reduce_fwd(k, v, mask, n_head, interpret):
    interpret = _interpret_default() if interpret is None else interpret
    return _reduce_call(k, v, mask, n_head, interpret), (k, v, mask)


def _nla_reduce_bwd(n_head, interpret, residuals, cotangents):
    del interpret
    k, v, mask = residuals
    _, vjp = jax.vjp(lambda k_, v_: _reduce_ref(k_, v_, mask, n_head), k, v)
    dk, dv = vjp(cotangents)
    return dk, dv, jnp.zeros_like(mask)


nla_reduce.defvjp(_nla_reduce_fwd, _nla_reduce_bwd)


# --------------------------------------------------------------------------
# Stage 2: apply — softmax(q), normalizer, Gram application.
# --------------------------------------------------------------------------


def _apply_kernel(q_ref, kv_ref, ksum_ref, out_ref, qs_ref, *, n_head):
    f_i = pl.program_id(2)
    e = q_ref.shape[-1]
    bd = _block_diag_mask(e, e // n_head)

    qs = _group_softmax(q_ref[0].astype(jnp.float32), n_head)  # [T, E]

    @pl.when(f_i == 0)
    def _():
        qs_ref[0] = qs.astype(qs_ref.dtype)

    kv = kv_ref[0, 0] * bd  # keep only each head's diagonal block
    ksum = ksum_ref[0, 0]  # [1, E]
    # Per-head <q, k_sum>, broadcast back to the head's lanes: [T, E].
    denom = jax.lax.dot_general(
        qs * ksum, bd, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # All-masked function slab: ksum == 0 → denom == 0 with a zero
    # numerator; select 1 so the contribution is 0, not nan (the softmaxed
    # k rows are strictly positive, so any surviving key makes denom > 0).
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = jnp.dot(qs, kv, preferred_element_type=jnp.float32) / denom
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _apply_call(q, kv, ksum, n_head: int, interpret: bool):
    b, l, e = q.shape
    f = kv.shape[0]
    lp, tl = _seq_pad(l)
    qp = jnp.pad(q, ((0, 0), (0, lp - l), (0, 0)))

    out, qs = pl.pallas_call(
        functools.partial(_apply_kernel, n_head=n_head),
        grid=(b, lp // tl, f),
        in_specs=[
            pl.BlockSpec((1, tl, e), lambda bi, li, fi: (bi, li, 0)),
            pl.BlockSpec((1, 1, e, e), lambda bi, li, fi: (fi, bi, 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, li, fi: (fi, bi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, tl, e), lambda bi, li, fi: (fi, bi, li, 0)),
            pl.BlockSpec((1, tl, e), lambda bi, li, fi: (bi, li, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((f, b, lp, e), q.dtype),
            jax.ShapeDtypeStruct((b, lp, e), q.dtype),
        ),
        interpret=interpret,
    )(qp, kv, ksum)
    return out[:, :, :l], qs[:, :l]


def _apply_ref(q, kv, ksum, n_head: int):
    """Einsum form of the apply stage (backward source + test oracle)."""
    e = q.shape[-1]
    shaped = q.reshape(*q.shape[:-1], n_head, e // n_head)
    qs = jax.nn.softmax(shaped.astype(jnp.float32), axis=-1).reshape(q.shape)
    bd = _block_diag_mask(e, e // n_head)
    kvm = kv * bd
    # Per-head <q, k_sum>, broadcast to the head's lanes via bd.
    denom = jnp.einsum("fble,ed->fbld", qs[None] * ksum, bd)
    denom = jnp.where(denom == 0.0, 1.0, denom)  # all-masked slab → 0, not nan
    out = jnp.einsum("bld,fbde->fble", qs, kvm) / denom
    return out.astype(q.dtype), qs.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def nla_apply(q: Array, kv: Array, ksum: Array, n_head: int, interpret: bool | None = None):
    """Apply the (psum-combined) Gram accumulators to the query stream.

    Returns ``(out [F,B,L,E], q_softmaxed [B,L,E])``, head-merged.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _apply_call(q, kv, ksum, n_head, interpret)


def _nla_apply_fwd(q, kv, ksum, n_head, interpret):
    interpret = _interpret_default() if interpret is None else interpret
    return _apply_call(q, kv, ksum, n_head, interpret), (q, kv, ksum)


def _nla_apply_bwd(n_head, interpret, residuals, cotangents):
    del interpret
    q, kv, ksum = residuals
    _, vjp = jax.vjp(
        lambda q_, kv_, ks_: _apply_ref(q_, kv_, ks_, n_head), q, kv, ksum
    )
    return vjp(cotangents)


nla_apply.defvjp(_nla_apply_fwd, _nla_apply_bwd)


# --------------------------------------------------------------------------
# Composed forms.
# --------------------------------------------------------------------------


def fused_nla(
    q: Array,
    k: Array,
    v: Array,
    mask: Array,
    n_head: int,
    interpret: bool | None = None,
):
    """Fused normalized linear attention in the merged-head layout.

    Args:
      q: ``[B, L, E]`` raw projected queries (pre-softmax, heads merged).
      k: ``[F, B, Lk, E]`` raw keys, one slab per input function
        (``F=1`` for self-attention).
      v: ``[F, B, Lk, E]`` values.
      mask: ``[F, B, Lk]`` 0/1 key mask (pass ones for unmasked).
      n_head: number of heads (E must be divisible by it).
      interpret: force pallas interpreter mode; ``None`` auto-selects
        (compiled on TPU, interpreted on CPU for tests).

    Returns:
      ``(out [F, B, L, E], q_softmaxed [B, L, E])``, both head-merged.
    """
    kv, ksum = nla_reduce(k, v, mask, n_head, interpret)
    return nla_apply(q, kv, ksum, n_head, interpret)


def fused_nla_sp(
    q: Array,
    k: Array,
    v: Array,
    mask: Array,
    n_head: int,
    mesh,
    *,
    data_axis: str | None = None,
    seq_axis: str | None = "seq",
    model_axis: str | None = None,
    interpret: bool | None = None,
    sp_collective: str = "psum",
):
    """Distributed fused attention over a DP x SP x TP device mesh.

    Per-axis layout (any subset of the axes may be None/size-1):

    * ``data_axis`` — batch dim B sharded; no communication.
    * ``seq_axis`` — L and Lk sharded. Each device reduces its local
      Gram accumulators; one ``psum`` (fixed ``[F, B, E, E]`` payload,
      independent of sequence length) combines them — strictly cheaper
      than ring attention's O(steps) K/V rotation for this op.
    * ``model_axis`` — the embed dim E sharded by WHOLE head groups
      (requires ``n_head %% model_size == 0``). Heads never mix in
      normalized linear attention (the Gram matrix is head-block
      diagonal), so each shard runs the kernel on its local heads with
      no communication at all.

    ``sp_collective`` selects the schedule that combines the per-shard
    Gram partials over ``seq_axis``: ``"psum"`` (one fused all-reduce,
    the default and recommendation) or ``"ring"`` (S-1 ppermute hops —
    see ops/collectives.ring_allreduce for when that schedule makes
    sense). Differentiable end-to-end either way (psum transposes to
    psum, the ring replays in reverse, through the per-stage custom
    VJPs).
    """
    from gnot_tpu.ops.collectives import ring_allreduce, shard_map

    if sp_collective not in ("psum", "ring"):
        raise ValueError(f"unknown sp_collective {sp_collective!r}")
    model_size = mesh.shape[model_axis] if model_axis else 1
    if n_head % model_size:
        raise ValueError(
            f"n_head={n_head} must be divisible by the model axis size "
            f"{model_size} (TP shards whole head groups)"
        )
    local_heads = n_head // model_size

    def local_fn(q_l, k_l, v_l, m_l):
        kv_l, ksum_l = nla_reduce(k_l, v_l, m_l, local_heads, interpret)
        if seq_axis:
            if sp_collective == "ring":
                size = mesh.shape[seq_axis]
                kv_l = ring_allreduce(kv_l, seq_axis, size)
                ksum_l = ring_allreduce(ksum_l, seq_axis, size)
            else:
                kv_l = jax.lax.psum(kv_l, seq_axis)
                ksum_l = jax.lax.psum(ksum_l, seq_axis)
        return nla_apply(q_l, kv_l, ksum_l, local_heads, interpret)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(data_axis, seq_axis, model_axis),
            P(None, data_axis, seq_axis, model_axis),
            P(None, data_axis, seq_axis, model_axis),
            P(None, data_axis, seq_axis),
        ),
        out_specs=(
            P(None, data_axis, seq_axis, model_axis),
            P(data_axis, seq_axis, model_axis),
        ),
        check_vma=False,  # pallas_call outputs don't declare varying-axes
    )(q, k, v, mask)


def _reference_impl(q, k, v, mask, n_head: int):
    """Full einsum oracle in the merged-head layout (tests)."""
    kv, ksum = _reduce_ref(k, v, mask, n_head)
    return _apply_ref(q, kv, ksum, n_head)


# --------------------------------------------------------------------------
# Segment-packed stages: "pack, don't pad" in the kernel itself.
#
# Packed rows carry several samples (segments) as contiguous,
# chunk-aligned spans (data/batch.py::PackedBatch). With the kernel tile
# pinned to the packing chunk, every sequence tile belongs to exactly
# ONE segment, so segment structure enters the kernels as *indices*, not
# masks:
#
# * ``nla_reduce_seg`` scatters each tile's Gram/k_sum contribution
#   straight into its segment's output block — the output BlockSpec's
#   index map reads the prefetched chunk->segment id table
#   (pltpu.PrefetchScalarGridSpec), the grouped-matmul idiom. A
#   segment's tiles are contiguous in grid order (one placement per
#   sample), so each output block is revisited in a single run and the
#   zero-init fires on the prefetched run-start flag.
# * ``nla_apply_seg`` gathers each query tile's segment Gram/k_sum the
#   same way (read-only, so revisit order is unconstrained).
#
# No token ever attends across a segment boundary BY CONSTRUCTION: a
# tile only ever meets its own segment's accumulators. Pad chunks carry
# segment id S (one garbage slot, sliced off / zero-Gram'd), and
# intra-chunk tail padding rides the ordinary 0/1 token mask.
# --------------------------------------------------------------------------


def _run_starts(seg: Array) -> Array:
    """[B, N] tile segment ids -> int32 1/0 first-tile-of-run flags.
    Contiguous placement means a segment's tiles form one run per row;
    the reduce kernel zero-inits its output block exactly there."""
    seg = seg.astype(jnp.int32)
    first = jnp.ones_like(seg[:, :1])
    return jnp.concatenate(
        [first, (seg[:, 1:] != seg[:, :-1]).astype(jnp.int32)], axis=1
    )


def _seg_tile(l: int, n_tiles: int, what: str) -> int:
    if l % n_tiles:
        raise ValueError(
            f"{what}: sequence length {l} not divisible by the segment "
            f"tile count {n_tiles} (chunk-aligned packing required)"
        )
    tile = l // n_tiles
    if tile % 8:
        raise ValueError(
            f"{what}: packing chunk {tile} must be a multiple of 8 "
            "(TPU sublane alignment); repack with chunk in {64, 128, 256}"
        )
    return tile


def _visited_mask(seg: Array, n_seg: int) -> Array:
    """[S] 0/1: which segment slots any tile actually wrote. Unvisited
    output blocks hold uninitialized memory — zeroed after the call."""
    flat = jnp.clip(seg.reshape(-1), 0, n_seg)  # garbage slot folds to S
    return jnp.zeros(n_seg + 1, jnp.float32).at[flat].max(1.0)[:n_seg]


def _reduce_seg_kernel(
    seg_ref, init_ref, k_ref, v_ref, m_ref, kv_ref, ksum_ref, *, n_head
):
    b_i = pl.program_id(0)
    lk_i = pl.program_id(2)

    @pl.when(init_ref[b_i, lk_i] == 1)
    def _():
        kv_ref[0, 0] = jnp.zeros_like(kv_ref[0, 0])
        ksum_ref[0, 0] = jnp.zeros_like(ksum_ref[0, 0])

    k = k_ref[0, 0].astype(jnp.float32)  # [T, E]
    v = v_ref[0, 0].astype(jnp.float32)  # [T, E]
    m = m_ref[0, 0].astype(jnp.float32)  # [T, 1]
    ks = _group_softmax(k, n_head) * m
    kv_ref[0, 0] += jax.lax.dot_general(
        ks, v, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ksum_ref[0, 0] += jnp.sum(ks, axis=0, keepdims=True)


def _reduce_seg_call(k, v, mask, seg, n_seg: int, n_head: int, interpret: bool):
    f, b, lk, e = k.shape
    tile = _seg_tile(lk, seg.shape[1], "nla_reduce_seg")
    nt = lk // tile
    seg32 = seg.astype(jnp.int32)
    init = _run_starts(seg32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, f, nt),
        in_specs=[
            pl.BlockSpec((1, 1, tile, e), lambda bi, fi, li, s_r, i_r: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tile, e), lambda bi, fi, li, s_r, i_r: (fi, bi, li, 0)),
            pl.BlockSpec((1, 1, tile, 1), lambda bi, fi, li, s_r, i_r: (fi, bi, li, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, e, e), lambda bi, fi, li, s_r, i_r: (fi, s_r[bi, li], 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, fi, li, s_r, i_r: (fi, s_r[bi, li], 0, 0)),
        ),
    )
    kv, ksum = pl.pallas_call(
        functools.partial(_reduce_seg_kernel, n_head=n_head),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((f, n_seg + 1, e, e), jnp.float32),
            jax.ShapeDtypeStruct((f, n_seg + 1, 1, e), jnp.float32),
        ),
        interpret=interpret,
    )(seg32, init, k, v, mask[..., None])
    # Slots no tile scattered into hold uninitialized memory; zero them
    # so empty sample slots read as "no keys" (like an all-masked slab).
    vis = _visited_mask(seg32, n_seg)
    kv = jnp.where(vis[None, :, None, None] > 0, kv[:, :n_seg], 0.0)
    ksum = jnp.where(vis[None, :, None, None] > 0, ksum[:, :n_seg], 0.0)
    return kv, ksum


def _reduce_seg_ref(k, v, mask, seg, n_seg: int, n_head: int):
    """Einsum form of the segment reduce (backward source + oracle)."""

    def gsm(x):
        shaped = x.reshape(*x.shape[:-1], n_head, x.shape[-1] // n_head)
        sm = jax.nn.softmax(shaped.astype(jnp.float32), axis=-1)
        return sm.reshape(x.shape)

    lk = k.shape[2]
    ks = gsm(k) * mask[..., None]  # [F, B, Lk, E]
    tok_seg = jnp.repeat(seg, lk // seg.shape[1], axis=1)  # [B, Lk]
    oh = jax.nn.one_hot(tok_seg, n_seg + 1, dtype=jnp.float32)[..., :n_seg]
    kv = jnp.einsum("fbld,fble,bls->fsde", ks, v.astype(jnp.float32), oh)
    ksum = jnp.einsum("fbld,bls->fsd", ks, oh)[:, :, None, :]
    return kv, ksum


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def nla_reduce_seg(
    k: Array,
    v: Array,
    mask: Array,
    seg: Array,
    n_seg: int,
    n_head: int,
    interpret: bool | None = None,
):
    """Segment-scattered Gram accumulation over PACKED key rows.

    Args:
      k: ``[F, B, Lk, E]`` raw keys, rows packed (``F=1`` for
        self-attention over node rows).
      v: ``[F, B, Lk, E]`` values.
      mask: ``[F, B, Lk]`` 0/1 token mask (intra-chunk tail padding).
      seg: ``[B, N]`` int chunk->segment ids, ``Lk % N == 0``; pad
        chunks carry ``n_seg``. The kernel tile IS the packing chunk.
      n_seg: static segment-slot count S.

    Returns:
      ``(kv [F, S, E, E], k_sum [F, S, 1, E])`` in f32 — one Gram per
      segment; empty slots are exactly zero.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _reduce_seg_call(k, v, mask, seg, n_seg, n_head, interpret)


def _nla_reduce_seg_fwd(k, v, mask, seg, n_seg, n_head, interpret):
    interpret = _interpret_default() if interpret is None else interpret
    out = _reduce_seg_call(k, v, mask, seg, n_seg, n_head, interpret)
    return out, (k, v, mask, seg)


def _nla_reduce_seg_bwd(n_seg, n_head, interpret, residuals, cotangents):
    del interpret
    k, v, mask, seg = residuals
    _, vjp = jax.vjp(
        lambda k_, v_: _reduce_seg_ref(k_, v_, mask, seg, n_seg, n_head), k, v
    )
    dk, dv = vjp(cotangents)
    return dk, dv, jnp.zeros_like(mask), np.zeros(seg.shape, jax.dtypes.float0)


nla_reduce_seg.defvjp(_nla_reduce_seg_fwd, _nla_reduce_seg_bwd)


def _apply_seg_kernel(seg_ref, q_ref, kv_ref, ksum_ref, out_ref, qs_ref, *, n_head):
    f_i = pl.program_id(2)
    e = q_ref.shape[-1]
    bd = _block_diag_mask(e, e // n_head)

    qs = _group_softmax(q_ref[0].astype(jnp.float32), n_head)  # [T, E]

    @pl.when(f_i == 0)
    def _():
        qs_ref[0] = qs.astype(qs_ref.dtype)

    kv = kv_ref[0, 0] * bd  # this tile's SEGMENT Gram, head-diag blocks
    ksum = ksum_ref[0, 0]  # [1, E]
    denom = jax.lax.dot_general(
        qs * ksum, bd, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # Pad tiles gather the zero garbage Gram and empty segments have
    # ksum == 0: both give denom == 0 with a zero numerator; select 1
    # so their output is 0, not nan.
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = jnp.dot(qs, kv, preferred_element_type=jnp.float32) / denom
    out_ref[0, 0] = out.astype(out_ref.dtype)


def _apply_seg_call(q, kv, ksum, seg, n_head: int, interpret: bool):
    b, l, e = q.shape
    f, n_seg = kv.shape[0], kv.shape[1]
    tile = _seg_tile(l, seg.shape[1], "nla_apply_seg")
    nt = l // tile
    seg32 = seg.astype(jnp.int32)
    # One zero garbage block at index S: pad chunks (seg id == S)
    # gather it and emit exactly 0 (denominator select above).
    kv_g = jnp.concatenate([kv, jnp.zeros((f, 1, e, e), kv.dtype)], axis=1)
    ksum_g = jnp.concatenate([ksum, jnp.zeros((f, 1, 1, e), ksum.dtype)], axis=1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nt, f),
        in_specs=[
            pl.BlockSpec((1, tile, e), lambda bi, li, fi, s_r: (bi, li, 0)),
            pl.BlockSpec((1, 1, e, e), lambda bi, li, fi, s_r: (fi, s_r[bi, li], 0, 0)),
            pl.BlockSpec((1, 1, 1, e), lambda bi, li, fi, s_r: (fi, s_r[bi, li], 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, tile, e), lambda bi, li, fi, s_r: (fi, bi, li, 0)),
            pl.BlockSpec((1, tile, e), lambda bi, li, fi, s_r: (bi, li, 0)),
        ),
    )
    out, qs = pl.pallas_call(
        functools.partial(_apply_seg_kernel, n_head=n_head),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((f, b, l, e), q.dtype),
            jax.ShapeDtypeStruct((b, l, e), q.dtype),
        ),
        interpret=interpret,
    )(seg32, q, kv_g, ksum_g)
    return out, qs


def _apply_seg_ref(q, kv, ksum, seg, n_head: int):
    """Einsum form of the segment apply (backward source + oracle)."""
    b, l, e = q.shape
    n_seg = kv.shape[1]
    n = seg.shape[1]
    c = l // n
    shaped = q.reshape(*q.shape[:-1], n_head, e // n_head)
    qs = jax.nn.softmax(shaped.astype(jnp.float32), axis=-1).reshape(q.shape)
    bd = _block_diag_mask(e, e // n_head)
    oh = jax.nn.one_hot(seg, n_seg + 1, dtype=jnp.float32)[..., :n_seg]  # [B,N,S]
    kv_t = jnp.einsum("bns,fsde->fbnde", oh, kv * bd)
    ks_t = jnp.einsum("bns,fse->fbne", oh, ksum[:, :, 0])
    qc = qs.reshape(b, n, c, e)
    # Per-head <q, k_sum>, broadcast to the head's lanes via bd (the
    # masked unpacked op's denominator, per segment).
    denom = jnp.einsum("bncd,fbnd,de->fbnce", qc, ks_t, bd)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    out = jnp.einsum("bncd,fbnde->fbnce", qc, kv_t) / denom
    return out.reshape(kv.shape[0], b, l, e).astype(q.dtype), qs.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def nla_apply_seg(
    q: Array,
    kv: Array,
    ksum: Array,
    seg: Array,
    n_head: int,
    interpret: bool | None = None,
):
    """Apply per-SEGMENT Gram accumulators to packed query rows.

    Each query tile gathers exactly its own segment's ``kv``/``k_sum``
    (``seg [B, N]`` chunk->segment ids; pad chunks ``>= S`` emit 0), so
    two segments sharing a row can never see each other's keys.

    Returns ``(out [F, B, L, E], q_softmaxed [B, L, E])``, head-merged.
    """
    interpret = _interpret_default() if interpret is None else interpret
    return _apply_seg_call(q, kv, ksum, seg, n_head, interpret)


def _nla_apply_seg_fwd(q, kv, ksum, seg, n_head, interpret):
    interpret = _interpret_default() if interpret is None else interpret
    return _apply_seg_call(q, kv, ksum, seg, n_head, interpret), (q, kv, ksum, seg)


def _nla_apply_seg_bwd(n_head, interpret, residuals, cotangents):
    del interpret
    q, kv, ksum, seg = residuals
    _, vjp = jax.vjp(
        lambda q_, kv_, ks_: _apply_seg_ref(q_, kv_, ks_, seg, n_head),
        q, kv, ksum,
    )
    dq, dkv, dksum = vjp(cotangents)
    return dq, dkv, dksum, np.zeros(seg.shape, jax.dtypes.float0)


nla_apply_seg.defvjp(_nla_apply_seg_fwd, _nla_apply_seg_bwd)


def fused_nla_packed(
    q: Array,
    k: Array,
    v: Array,
    mask: Array,
    q_seg: Array,
    kv_seg: Array,
    n_seg: int,
    n_head: int,
    interpret: bool | None = None,
):
    """Fused normalized linear attention over PACKED rows.

    The packed counterpart of ``fused_nla``: ``kv_seg``/``q_seg`` are
    ``[B, N]`` chunk->segment id tables for the key and query rows
    (DIFFERENT packings allowed — cross-attention packs input functions
    separately; segments are global ids shared by both sides). Exact
    per-segment attention: tokens never attend across segment
    boundaries, so the result for each segment equals running the
    unpacked kernel on that segment alone (fp summation order aside).
    """
    kv, ksum = nla_reduce_seg(k, v, mask, kv_seg, n_seg, n_head, interpret)
    return nla_apply_seg(q, kv, ksum, q_seg, n_head, interpret)


def _reference_seg_impl(q, k, v, mask, q_seg, kv_seg, n_seg: int, n_head: int):
    """Full einsum oracle for the packed stages (tests)."""
    kv, ksum = _reduce_seg_ref(k, v, mask, kv_seg, n_seg, n_head)
    return _apply_seg_ref(q, kv, ksum, q_seg, n_head)
