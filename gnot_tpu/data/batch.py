"""Dense batch container + ragged->dense batching.

The reference carries ragged meshes as edge-less DGL graphs and pads them
inline in the train loop (``/root/reference/main.py:37-39,63-82``). The
TPU-native form is a single static-shaped pytree, ``MeshBatch``, with the
ragged structure folded into 0/1 masks — XLA-friendly (no recompiles per
shape when bucketing is on, no graph library, no host round trips).

Reference-faithful padding semantics preserved:
  * input functions are padded to the **single max length across ALL
    functions of ALL samples in the batch** (main.py:63 — one shared
    max, not per-function);
  * coords/targets are padded to the per-batch max node count
    (main.py:78-80);
  * zero padding at the tail of the length axis (utils.py:3-4).

On top, an optional bucketing scheme rounds pad lengths up to the next
bucket boundary so XLA compiles O(log L) programs instead of one per
distinct length. Bucketing changes numerics only in parity (unmasked)
mode, so parity runs disable it.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Iterator, Sequence

import flax.struct
import numpy as np


@flax.struct.dataclass
class MeshBatch:
    """One padded batch of ragged PDE meshes. All arrays are dense.

    Shapes: B batch, L max nodes, Lf max input-function points, F number
    of input functions, dx/df/dy coordinate/function/output dims, T theta.
    """

    coords: np.ndarray  # [B, L, dx] mesh point coordinates
    theta: np.ndarray  # [B, T] global (per-sample) parameters
    y: np.ndarray  # [B, L, dy] padded targets
    node_mask: np.ndarray  # [B, L] 1 for real nodes, 0 for padding
    funcs: np.ndarray | None = None  # [F, B, Lf, df] padded input functions
    func_mask: np.ndarray | None = None  # [F, B, Lf]

    @property
    def n_real_points(self) -> int:
        """Total un-padded mesh points — the throughput denominator."""
        return int(np.sum(np.asarray(self.node_mask)))


@flax.struct.dataclass
class PackedBatch:
    """A PACKED batch: multiple samples share each row as chunk-aligned
    contiguous segments ("pack, don't pad"). Ragged meshes stop paying
    bucket-padding FLOPs (~30% of tokens on the ragged benchmark
    configs); the linear attention stays exactly per-sample via segment
    Grams (ops.attention.packed_normalized_linear_attention).

    Shapes: R rows, L row length (multiple of the chunk size C),
    N = L/C chunks per row, S static sample-slot count, F input
    functions, Lf function pad length. Input functions are NOT packed —
    they stay slot-indexed ``[F, S, Lf, df]`` (each slot-row is one
    one-chunk segment), which reuses the per-sample K/V layout and
    keeps the packer trivial; node tokens dominate the FLOPs."""

    coords: np.ndarray  # [R, L, dx]
    theta: np.ndarray  # [S, T] per-sample params (slot-indexed)
    y: np.ndarray  # [R, L, dy]
    node_mask: np.ndarray  # [R, L]
    node_seg: np.ndarray  # [R, N] int32 chunk->slot ids; pad chunks = S
    funcs: np.ndarray | None = None  # [F, S, Lf, df]
    func_mask: np.ndarray | None = None  # [F, S, Lf]
    func_seg: np.ndarray | None = None  # [S, 1] slot ids (S for empty slots)
    n_seg: int = flax.struct.field(pytree_node=False, default=0)

    @property
    def n_real_points(self) -> int:
        return int(np.sum(np.asarray(self.node_mask)))


@dataclasses.dataclass
class MeshSample:
    """One ragged sample: ``[X, Y, theta, (f1, f2, ...)]`` — the pickle
    record schema of the reference (dataset.py:7)."""

    coords: np.ndarray  # [n, dx]
    y: np.ndarray  # [n, dy]
    theta: np.ndarray  # [T]
    funcs: tuple[np.ndarray, ...] = ()  # each [m_i, df]


def bucket_length(n: int, *, min_size: int = 64) -> int:
    """Round up to the next power-of-two-ish bucket (1, 1.5 mantissa)."""
    size = min_size
    while size < n:
        if int(size * 1.5) >= n and (size & (size - 1)) == 0:
            return int(size * 1.5)
        size *= 2
    return size


def validate_samples(
    samples: Sequence[MeshSample],
    *,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
    check_finite: bool = True,
) -> None:
    """Reject malformed inference inputs with the offending sample index.

    Two failure classes, both raised as ValueError naming ``sample i``:

    * oversize meshes/functions against FIXED pad lengths (an unseen
      longer mesh cannot be packed into pads captured from the training
      data — fail with the limit, not a cryptic broadcast error from
      the packer);
    * non-finite coords / input-function values / theta / targets (a
      NaN query poisons the whole padded batch it rides in — under
      linear attention every sample attends through shared normalization
      Grams, so one bad request can corrupt its batchmates' outputs
      and, serving-side, trip the circuit breaker).

    The one validation gate shared by ``Trainer.predict`` and the
    serving ``InferenceEngine``.
    """
    for i, s in enumerate(samples):
        if pad_nodes and s.coords.shape[0] > pad_nodes:
            raise ValueError(
                f"sample {i} has {s.coords.shape[0]} mesh points but the "
                f"fixed pad length is {pad_nodes} (set from the training "
                "data); rebuild with larger pad_nodes"
            )
        if pad_funcs:
            for j, f in enumerate(s.funcs):
                if f.shape[0] > pad_funcs:
                    raise ValueError(
                        f"sample {i} input function {j} has {f.shape[0]} "
                        f"points but the fixed pad length is {pad_funcs}; "
                        "rebuild with larger pad_funcs"
                    )
        if not check_finite:
            continue
        if not np.all(np.isfinite(s.coords)):
            raise ValueError(f"sample {i} has non-finite mesh coordinates")
        if not np.all(np.isfinite(np.asarray(s.theta, dtype=np.float64))):
            raise ValueError(f"sample {i} has non-finite theta parameters")
        if s.y is not None and not np.all(np.isfinite(s.y)):
            raise ValueError(f"sample {i} has non-finite target values")
        for j, f in enumerate(s.funcs):
            if not np.all(np.isfinite(f)):
                raise ValueError(
                    f"sample {i} input function {j} has non-finite values"
                )


def fixed_pad_lengths(
    samples: Sequence[MeshSample], *, bucket: bool = True
) -> tuple[int, int]:
    """Dataset-wide ``(pad_nodes, pad_funcs)`` targets: the maxima over
    ALL samples (bucketed). With these, every batch has one static
    shape — multi-host SPMD safe, zero recompiles."""
    pn = max(s.coords.shape[0] for s in samples)
    pf = max((f.shape[0] for s in samples for f in s.funcs), default=0)
    if bucket:
        pn = bucket_length(pn)
        pf = bucket_length(pf) if pf else 0
    return pn, pf


def pad_rows(arr: np.ndarray, length: int) -> np.ndarray:
    """Zero-pad axis 0 to ``length`` (reference utils.py:3-4)."""
    if arr.shape[0] == length:
        return arr
    pad = [(0, length - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def collate(
    samples: Sequence[MeshSample],
    *,
    bucket: bool = True,
    pad_nodes: int = 0,
    pad_funcs: int = 0,
    dtype: str = "float32",
) -> MeshBatch:
    """Pad and stack ragged samples into a dense MeshBatch.

    ``pad_nodes``/``pad_funcs`` force fixed pad lengths (0 = per-batch
    max, optionally bucketed). Fixed lengths give every batch one static
    shape — required for multi-host SPMD (every process must assemble
    identically-shaped global arrays regardless of its local samples)
    and they eliminate XLA recompiles outright.

    The packing hot loop runs in the native C++ packer
    (``gnot_tpu/native/ragged_pack.cpp``) when available: one
    memcpy+memset sweep per field with the mask written in the same
    pass; pure-numpy fallback otherwise (identical output).
    ``dtype="bfloat16"`` is the serving low-precision path: the native
    sweep FUSES the pad with the f32->bf16 cast, so the dispatch batch
    is assembled half-width in one pass (training always collates
    f32)."""
    from gnot_tpu import native

    if pad_nodes:
        max_nodes = pad_nodes
    else:
        max_nodes = max(s.coords.shape[0] for s in samples)
        if bucket:
            max_nodes = bucket_length(max_nodes)

    coords, node_mask = native.pack_rows(
        [s.coords for s in samples], max_nodes, dtype
    )
    y, _ = native.pack_rows([s.y for s in samples], max_nodes, dtype)
    theta = np.stack([np.atleast_1d(np.asarray(s.theta, np.float32)) for s in samples])
    theta = theta.astype(coords.dtype, copy=False)

    n_funcs = len(samples[0].funcs)
    funcs = func_mask = None
    if n_funcs:
        if pad_funcs:
            max_f = pad_funcs
        else:
            # Single shared max across every function of every sample
            # (reference main.py:63).
            max_f = max(f.shape[0] for s in samples for f in s.funcs)
            if bucket:
                max_f = bucket_length(max_f)
        packed = [
            native.pack_rows([s.funcs[j] for s in samples], max_f, dtype)
            for j in range(n_funcs)
        ]
        funcs = np.stack([p[0] for p in packed])
        func_mask = np.stack([p[1] for p in packed])

    return MeshBatch(
        coords=coords,
        theta=theta,
        y=y,
        node_mask=node_mask,
        funcs=funcs,
        func_mask=func_mask,
    )


def pack_collate(
    samples: Sequence[MeshSample],
    placements: Sequence[tuple[int, int]],
    *,
    n_rows: int,
    row_len: int,
    chunk: int,
    n_slots: int,
    pad_funcs: int,
    dtype: str = "float32",
) -> PackedBatch:
    """Assemble one PackedBatch from samples + their (row, offset)
    placements (offsets chunk-aligned; produced by ``PackedLoader``).
    Slot ids are assignment order; unused rows/slots stay zero/pad.
    ``dtype="bfloat16"``: float fields assemble half-width (the bf16
    packed serving dispatch); segment id maps stay int32."""
    from gnot_tpu.models.precision import np_dtype

    ft = np_dtype(dtype)
    dx = samples[0].coords.shape[-1]
    dy = samples[0].y.shape[-1]
    n_funcs = len(samples[0].funcs)
    coords = np.zeros((n_rows, row_len, dx), ft)
    y = np.zeros((n_rows, row_len, dy), ft)
    node_mask = np.zeros((n_rows, row_len), ft)
    node_seg = np.full((n_rows, row_len // chunk), n_slots, np.int32)
    theta = np.zeros((n_slots, np.atleast_1d(samples[0].theta).shape[-1]), ft)
    funcs = func_mask = func_seg = None
    if n_funcs:
        df = samples[0].funcs[0].shape[-1]
        funcs = np.zeros((n_funcs, n_slots, pad_funcs, df), ft)
        func_mask = np.zeros((n_funcs, n_slots, pad_funcs), ft)
        func_seg = np.full((n_slots, 1), n_slots, np.int32)
    for slot, (s, (r, off)) in enumerate(zip(samples, placements)):
        n = s.coords.shape[0]
        coords[r, off : off + n] = s.coords
        y[r, off : off + n] = s.y
        node_mask[r, off : off + n] = 1.0
        node_seg[r, off // chunk : (off + n + chunk - 1) // chunk] = slot
        theta[slot] = np.atleast_1d(np.asarray(s.theta, np.float32))
        for j, f in enumerate(s.funcs):
            funcs[j, slot, : f.shape[0]] = f
            func_mask[j, slot, : f.shape[0]] = 1.0
        if n_funcs:
            func_seg[slot, 0] = slot
    return PackedBatch(
        coords=coords, theta=theta, y=y, node_mask=node_mask,
        node_seg=node_seg, funcs=funcs, func_mask=func_mask,
        func_seg=func_seg, n_seg=n_slots,
    )


@dataclasses.dataclass(frozen=True)
class PackPlan:
    """The STATIC shape of one packed serve dispatch: ``n_rows`` rows of
    ``row_len`` tokens (chunk-aligned segments), ``n_slots`` sample
    slots, input functions padded to ``pad_funcs``. One plan == one
    compiled XLA program, no matter how many small requests ride each
    dispatch — the serving counterpart of ``PackedLoader``'s fixed
    epoch shape (docs/performance.md "Pack, don't pad").
    """

    row_len: int
    chunk: int
    n_rows: int
    n_slots: int
    pad_funcs: int

    def __post_init__(self) -> None:
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if self.row_len % self.chunk:
            raise ValueError(
                f"row_len {self.row_len} must be a multiple of chunk "
                f"{self.chunk}"
            )
        if self.n_rows < 1 or self.n_slots < 1:
            raise ValueError("n_rows and n_slots must be >= 1")

    @classmethod
    def from_samples(
        cls,
        samples: Sequence[MeshSample],
        *,
        chunk: int = 128,
        n_rows: int = 0,
        batch_size: int = 4,
        row_len: int = 0,
    ) -> "PackPlan":
        """Derive a plan from representative traffic (the serve warmup
        set), mirroring ``PackedLoader``'s shape derivation: row_len
        fits ~2 max-size samples (bucketed), ``n_rows`` defaults to
        carrying ~batch_size samples per dispatch, slots sized so no
        packing of the row grid can overflow them."""
        if not samples:
            raise ValueError("PackPlan.from_samples needs at least one sample")
        aligned = [-(-s.coords.shape[0] // chunk) * chunk for s in samples]
        if not row_len:
            row_len = -(-bucket_length(2 * max(aligned)) // chunk) * chunk
        mean_a = float(np.mean(aligned))
        if not n_rows:
            n_rows = max(1, -(-int(batch_size * mean_a) // row_len))
        # Static slot capacity: traffic may include samples down to one
        # chunk, so no packing of the row grid can overflow this.
        n_slots = n_rows * (row_len // chunk)
        pad_funcs = max(
            (f.shape[0] for s in samples for f in s.funcs), default=0
        )
        if pad_funcs:
            pad_funcs = bucket_length(pad_funcs)
        return cls(
            row_len=row_len, chunk=chunk, n_rows=n_rows,
            n_slots=n_slots, pad_funcs=pad_funcs,
        )

    def aligned(self, n: int) -> int:
        """Chunk-aligned token footprint of an n-point mesh."""
        return -(-n // self.chunk) * self.chunk

    def packable(self, sample: MeshSample) -> bool:
        """Whether this sample can ride a packed dispatch: its aligned
        span fits one row and every input function fits the slot pad.
        Oversize requests fall back to the per-bucket padded path."""
        if self.aligned(sample.coords.shape[0]) > self.row_len:
            return False
        return all(f.shape[0] <= self.pad_funcs for f in sample.funcs)

    @property
    def capacity_tokens(self) -> int:
        """Token capacity of one dispatch (the pad-waste denominator)."""
        return self.n_rows * self.row_len

    @classmethod
    def for_slices(
        cls,
        samples: Sequence["MeshSample"],
        *,
        chunk: int,
        batch_size: int,
        per_devices: int,
    ) -> "PackPlan":
        """``from_samples`` whose row grid divides over a
        ``per_devices``-wide replica slice — packed dispatch rows shard
        over the slice exactly like padded rows, so every slice must
        get whole rows. THE single source of the alignment rule
        (``main._run_serve`` and ``tools/serve_smoke.py`` both call
        this)."""
        plan = cls.from_samples(samples, chunk=chunk, batch_size=batch_size)
        per = max(1, per_devices)
        if plan.n_rows % per:
            plan = cls.from_samples(
                samples,
                chunk=chunk,
                batch_size=batch_size,
                n_rows=-(-plan.n_rows // per) * per,
            )
        return plan


def pack_prefix(
    sizes: Sequence[int], plan: PackPlan
) -> list[tuple[int, int]]:
    """First-fit FIFO *prefix* packing into one ``plan``-shaped
    dispatch: place each sample (in order) into the first row with
    space; STOP at the first that fits nowhere (or when slots run out)
    so a dispatch is always an arrival-order prefix — a request never
    overtakes an older one, preserving the Batcher's FIFO/monotone
    queue-wait contract. Returns ``(row, offset)`` placements for the
    packed prefix (``len(result)`` = how many were placed)."""
    used = [0] * plan.n_rows
    placements: list[tuple[int, int]] = []
    for n in sizes:
        if len(placements) >= plan.n_slots:
            break
        a = plan.aligned(n)
        for r in range(plan.n_rows):
            if used[r] + a <= plan.row_len:
                placements.append((r, used[r]))
                used[r] += a
                break
        else:
            break
    return placements


class PackedLoader:
    """Epoch iterator over PACKED batches: the epoch's (shuffled) sample
    stream is first-fit packed into rows of one fixed length, then R
    consecutive rows form each dispatch — every dispatch has ONE static
    shape and rows fill to ~90%+ instead of the ~70% bucket-padding
    utilization on ragged meshes. ``batch_size`` keeps its meaning as
    the NOMINAL samples per step (row count R is derived so a dispatch
    carries ~batch_size samples on average); the actual per-dispatch
    sample count varies with packing, like the reference's ragged final
    batch does."""

    def __init__(
        self,
        samples: Sequence[MeshSample],
        batch_size: int,
        *,
        chunk: int = 128,
        shuffle: bool = False,
        seed: int = 0,
        prefetch: int = 2,
        row_multiple: int = 1,
    ):
        if not samples:
            raise ValueError("PackedLoader needs at least one sample")
        self.samples = list(samples)
        self.batch_size = batch_size
        self.chunk = chunk
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = prefetch
        self._epoch = 0
        aligned = [
            -(-s.coords.shape[0] // chunk) * chunk for s in self.samples
        ]
        self._aligned = aligned
        max_a, min_a = max(aligned), min(aligned)
        # Row length: ~2 max-size samples per row, bucketed for a clean
        # XLA shape, rounded to the chunk grid.
        row = bucket_length(2 * max_a)
        self.row_len = -(-row // chunk) * chunk
        mean_a = float(np.mean(aligned))
        self.n_rows = max(1, -(-int(batch_size * mean_a) // self.row_len))
        # Mesh runs shard rows over the data axis: round the row count
        # up so every dispatch splits evenly.
        self.n_rows = -(-self.n_rows // row_multiple) * row_multiple
        # Static slot capacity: no R-row window can carry more samples.
        self.n_slots = self.n_rows * (self.row_len // min_a)
        self.pad_funcs = max(
            (f.shape[0] for s in self.samples for f in s.funcs), default=0
        )
        if self.pad_funcs:
            self.pad_funcs = bucket_length(self.pad_funcs)
        # Standard-loader attribute compatibility (predict() reads these
        # to build its unpacked inference loader).
        self.pad_nodes = 0
        self.bucket = True
        self._canonical_len: int | None = None

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def probe_batch(self) -> PackedBatch:
        """One canonical (unshuffled) dispatch for shape probing — does
        not advance the epoch counter."""
        epoch, shuffle = self._epoch, self.shuffle
        self.shuffle = False
        try:
            d = self._epoch_dispatches()[0]
        finally:
            self._epoch, self.shuffle = epoch, shuffle
        return self._collate_at(d)

    def _epoch_dispatches(self):
        order = np.arange(len(self.samples))
        if self.shuffle:
            np.random.default_rng((self.seed, self._epoch)).shuffle(order)
        self._epoch += 1
        # First-fit packing with OPEN bins (each sample goes into the
        # first row it fits; rows whose remaining space can't fit any
        # sample are closed) — measured ~86-89% fill on the ragged
        # configs vs ~70% for bucket padding and ~76% for the naive
        # one-open-row scheme.
        min_a = min(self._aligned)
        open_rows: list[list] = []  # [used, [(sample_idx, offset)]]
        closed: list[list] = []
        for i in order:
            a = self._aligned[i]
            for rb in open_rows:
                if rb[0] + a <= self.row_len:
                    rb[1].append((int(i), rb[0]))
                    rb[0] += a
                    break
            else:
                open_rows.append([a, [(int(i), 0)]])
            open_rows, newly_closed = (
                [rb for rb in open_rows if self.row_len - rb[0] >= min_a],
                [rb for rb in open_rows if self.row_len - rb[0] < min_a],
            )
            closed.extend(newly_closed)
        rows = [rb[1] for rb in closed + open_rows]
        # Group R rows per dispatch.
        dispatches = []
        for start in range(0, len(rows), self.n_rows):
            group = rows[start : start + self.n_rows]
            idx = [i for row in group for i, _ in row]
            placements = [
                (r, off) for r, row in enumerate(group) for _, off in row
            ]
            dispatches.append((idx, placements))
        return dispatches

    def __len__(self) -> int:
        # EXACT dispatch count for the canonical (unshuffled) stream —
        # computed by actually packing it once, since first-fit
        # fragmentation can need a row group more than total/row_len
        # predicts. Unshuffled loaders (eval) iterate exactly this many
        # dispatches; a shuffled epoch can still differ by ±1 (callers
        # that must not truncate iterate exhaustively — see
        # Trainer.evaluate).
        if self._canonical_len is None:
            epoch, shuffle = self._epoch, self.shuffle
            self.shuffle = False
            try:
                self._canonical_len = len(self._epoch_dispatches())
            finally:
                self._epoch, self.shuffle = epoch, shuffle
        return self._canonical_len

    def _collate_at(self, dispatch) -> PackedBatch:
        idx, placements = dispatch
        return pack_collate(
            [self.samples[i] for i in idx],
            placements,
            n_rows=self.n_rows,
            row_len=self.row_len,
            chunk=self.chunk,
            n_slots=self.n_slots,
            pad_funcs=self.pad_funcs,
        )

    def __iter__(self):
        yield from _prefetched(
            self._epoch_dispatches(), self._collate_at, self.prefetch
        )


def _prefetched(items, collate_fn, prefetch: int):
    """Collate ``items`` on a background thread with a bounded queue so
    the host packs batch N+1 while the device executes batch N — THE
    one prefetch pipeline both loaders share. ``prefetch <= 0`` (or a
    single item) degrades to synchronous collation."""
    if prefetch <= 0 or len(items) <= 1:
        for it in items:
            yield collate_fn(it)
        return

    q: queue.Queue = queue.Queue(maxsize=prefetch)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for it in items:
                if not put(collate_fn(it)):
                    return  # consumer abandoned the epoch
            put(_END)
        except BaseException as e:  # surface worker errors to the consumer
            put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        t.join()


class Loader:
    """Epoch iterator: shuffle, batch, collate, background prefetch.

    Replaces the reference's ``DataLoader(batch_size=4, shuffle=True,
    collate_fn=unzip)`` (main.py:37-42) without a torch dependency.
    With ``prefetch > 0`` (default), collation runs in a background
    thread so the host packs batch N+1 while the device executes batch
    N — the host->device pipeline never stalls on the packer.
    """

    def __init__(
        self,
        samples: Sequence[MeshSample],
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        bucket: bool = True,
        drop_remainder: bool = False,
        prefetch: int = 2,
        pad_nodes: int = 0,
        pad_funcs: int = 0,
        dtype: str = "float32",
    ):
        self.samples = list(samples)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.bucket = bucket
        self.drop_remainder = drop_remainder
        self.prefetch = prefetch
        self.pad_nodes = pad_nodes
        self.pad_funcs = pad_funcs
        # Collate dtype: float32 for training (always); the serving
        # engine's offline path passes its own serve dtype through.
        self.dtype = dtype
        self.seed = seed
        # Epoch counter for shuffling: each epoch's order is a pure
        # function of (seed, epoch), so a resumed run at epoch N sees
        # exactly the batches the continuous run would have (a stateful
        # rng stream would restart from epoch 0's order after resume).
        # Advanced by __iter__; set_epoch() pins it (trainer resume,
        # torch DistributedSampler-style).
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        n = len(self.samples)
        if self.drop_remainder:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self) -> list[np.ndarray]:
        order = np.arange(len(self.samples))
        if self.shuffle:
            np.random.default_rng((self.seed, self._epoch)).shuffle(order)
        self._epoch += 1
        chunks = []
        for start in range(0, len(order), self.batch_size):
            idx = order[start : start + self.batch_size]
            if self.drop_remainder and len(idx) < self.batch_size:
                break
            chunks.append(idx)
        return chunks

    def _collate_at(self, idx: np.ndarray) -> MeshBatch:
        return collate(
            [self.samples[i] for i in idx],
            bucket=self.bucket,
            pad_nodes=self.pad_nodes,
            pad_funcs=self.pad_funcs,
            dtype=self.dtype,
        )

    def __iter__(self) -> Iterator[MeshBatch]:
        yield from _prefetched(
            self._epoch_indices(), self._collate_at, self.prefetch
        )
