"""Dataset loading: the reference pickle schema + synthetic generators.

The reference's ``NS2dDataset`` (dataset.py:6-44) unpickles a list of
``[X, Y, theta, (f1, f2, ...)]`` records and wraps each in an edge-less
DGL graph used purely as a ragged container. Here the same schema loads
straight into ``MeshSample``s — no graph library (SURVEY.md §2 rows 5/7:
segment ids / masks fully replace DGL).

The synthetic generators cover the five benchmark configs in
``BASELINE.json`` so the full pipeline runs without external data files;
targets are smooth deterministic functions of the inputs so models can
actually fit them in convergence tests.
"""

from __future__ import annotations

import pickle
from typing import Callable, Sequence

import numpy as np

from gnot_tpu.data.batch import MeshSample


def load_pickle(path: str) -> list[MeshSample]:
    """Read a reference-schema pickle: list of ``[X, Y, theta, (f...)]``.

    Accepts everything the reference's ``NS2dDataset`` ingests
    (``/root/reference/dataset.py:7,30-38``): X/Y as numpy arrays of any
    float dtype (the reference casts via ``.float()``) or torch tensors
    (``np.asarray`` takes either), theta as a raw scalar / 0-d / 1-d
    value (kept uncast by the reference), input functions as a tuple or
    list (both truthy-checked there), possibly absent or empty.
    Malformed records raise a ValueError naming the record and the
    expected schema, not an index/broadcast error from deep inside.
    The read itself retries transient OSErrors with backoff
    (resilience/retry.py) — dataset files live on the same flaky
    remote filesystems checkpoints do; a truncated/garbled pickle
    (``UnpicklingError``) is NOT transient and raises immediately."""
    from gnot_tpu.resilience.retry import retry_io

    def read():
        with open(path, "rb") as f:
            return pickle.load(f)

    records = retry_io(read, describe=f"dataset read {path}")
    if not isinstance(records, (list, tuple)):
        raise ValueError(
            f"{path}: expected a pickled list of [X, Y, theta, (f...)] "
            f"records, got {type(records).__name__}"
        )
    samples = []
    for i, rec in enumerate(records):
        if not isinstance(rec, (list, tuple)) or len(rec) < 3:
            raise ValueError(
                f"{path}: record {i} must be [X, Y, theta, (f...)] with "
                f"at least 3 entries, got "
                + (f"{len(rec)} entries" if isinstance(rec, (list, tuple))
                   else type(rec).__name__)
            )
        x, y, theta = rec[0], rec[1], rec[2]
        try:
            x = np.asarray(x, np.float32)
            y = np.asarray(y, np.float32)
            theta = np.atleast_1d(np.asarray(theta, np.float32))
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"{path}: record {i} has non-numeric X/Y/theta: {e}"
            ) from e
        if x.ndim != 2 or y.ndim != 2 or x.shape[0] != y.shape[0]:
            raise ValueError(
                f"{path}: record {i} needs X [n, d] and Y [n, c] with "
                f"matching n, got X {x.shape} and Y {y.shape}"
            )
        if theta.ndim != 1:
            raise ValueError(
                f"{path}: record {i} theta must be a scalar or 1-d "
                f"vector, got shape {theta.shape}"
            )
        raw_funcs = rec[3] if len(rec) > 3 else ()
        if raw_funcs is None:
            raw_funcs = ()
        if not isinstance(raw_funcs, (list, tuple)):
            # Not `if rec[3]:` — an ndarray/tensor container would raise
            # an ambiguous-truthiness error with no record context here.
            raise ValueError(
                f"{path}: record {i} input functions must be a tuple or "
                f"list of [m, d] arrays, got {type(raw_funcs).__name__}"
            )
        try:
            funcs = tuple(np.asarray(fi, np.float32) for fi in raw_funcs)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"{path}: record {i} has a non-numeric input function: {e}"
            ) from e
        for j, fi in enumerate(funcs):
            if fi.ndim != 2:
                raise ValueError(
                    f"{path}: record {i} input function {j} must be "
                    f"[m, d], got shape {fi.shape}"
                )
        samples.append(MeshSample(coords=x, y=y, theta=theta, funcs=funcs))
    return samples


def save_pickle(samples: Sequence[MeshSample], path: str) -> None:
    """Write samples in the reference pickle schema (round-trippable)."""
    records = [
        [s.coords, s.y, np.asarray(s.theta), tuple(s.funcs)] for s in samples
    ]
    with open(path, "wb") as f:
        pickle.dump(records, f)


def _smooth_target(coords: np.ndarray, theta: np.ndarray, funcs) -> np.ndarray:
    """Deterministic smooth operator output: learnable but nontrivial."""
    t = float(np.sum(theta))
    base = np.sin(np.pi * coords).prod(axis=1, keepdims=True)
    mod = 1.0 + 0.5 * np.cos(2 * np.pi * coords[:, :1] + t)
    fmean = 0.0
    for f in funcs:
        fmean = fmean + float(f[:, -1].mean())
    return (base * mod + 0.1 * fmean + 0.2).astype(np.float32)


def _grid(n: int, dim: int = 2) -> np.ndarray:
    axes = [np.linspace(0.0, 1.0, n, dtype=np.float32)] * dim
    mesh = np.meshgrid(*axes, indexing="ij")
    return np.stack([m.ravel() for m in mesh], axis=1)


def synth_darcy2d(n_samples: int, seed: int = 0, grid_n: int = 16) -> list[MeshSample]:
    """Darcy2d: regular grid, one input function (permeability field).

    BASELINE.json configs[0] uses 64x64; tests use a smaller grid_n."""
    rng = np.random.default_rng(seed)
    coords = _grid(grid_n)
    out = []
    for _ in range(n_samples):
        theta = rng.uniform(0.5, 1.5, size=(1,)).astype(np.float32)
        a = (
            1.0
            + rng.uniform(0, 1)
            * np.cos(np.pi * coords @ rng.integers(1, 4, size=(2, 1)))
        ).astype(np.float32)
        f = np.concatenate([coords, a], axis=1)
        y = _smooth_target(coords, theta, (f,))
        out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=(f,)))
    return out


def synth_ns2d(n_samples: int, seed: int = 0, n_points: int = 1024) -> list[MeshSample]:
    """NS2d-1k: ~1k-point mesh, time-dependent (theta = time), one input
    function (initial vorticity on its own mesh). The throughput config."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        coords = rng.uniform(0, 1, size=(n_points, 2)).astype(np.float32)
        theta = rng.uniform(0, 1, size=(1,)).astype(np.float32)
        m = n_points // 2
        fc = rng.uniform(0, 1, size=(m, 2)).astype(np.float32)
        w0 = np.sin(2 * np.pi * fc @ rng.uniform(1, 2, size=(2, 1))).astype(np.float32)
        f = np.concatenate([fc, w0], axis=1)
        y = _smooth_target(coords, theta, (f,))
        out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=(f,)))
    return out


def synth_elasticity(n_samples: int, seed: int = 0, base_points: int = 512) -> list[MeshSample]:
    """Elasticity: variable-length irregular point cloud (ragged L) — the
    masking stress test. One geometry input function."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        n = int(base_points * rng.uniform(0.7, 1.3))
        coords = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
        theta = rng.uniform(0.5, 2.0, size=(2,)).astype(np.float32)
        m = max(16, n // 4)
        boundary = rng.uniform(-1, 1, size=(m, 2)).astype(np.float32)
        load = np.cos(np.pi * boundary[:, :1]).astype(np.float32)
        f = np.concatenate([boundary, load], axis=1)
        y = np.concatenate(
            [_smooth_target(coords, theta, (f,)), 0.5 * _smooth_target(coords, theta[::-1], (f,))],
            axis=1,
        )
        out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=(f,)))
    return out


def synth_inductor2d(n_samples: int, seed: int = 0, base_points: int = 512) -> list[MeshSample]:
    """Inductor2d: multiple input functions of different lengths — the
    heterogeneous cross-attention stress test (three branches)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        n = int(base_points * rng.uniform(0.8, 1.2))
        coords = rng.uniform(0, 1, size=(n, 2)).astype(np.float32)
        theta = rng.uniform(0.5, 1.5, size=(3,)).astype(np.float32)
        funcs = []
        for j in range(3):
            m = max(8, int(n * rng.uniform(0.1, 0.4)))
            fc = rng.uniform(0, 1, size=(m, 2)).astype(np.float32)
            val = np.sin((j + 1) * np.pi * fc[:, :1]).astype(np.float32)
            funcs.append(np.concatenate([fc, val], axis=1))
        y = _smooth_target(coords, theta, tuple(funcs))
        out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=tuple(funcs)))
    return out


def synth_heatsink3d(n_samples: int, seed: int = 0, base_points: int = 2048) -> list[MeshSample]:
    """Heatsink3d: large 3D point cloud — geometric-gating MoE at scale."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_samples):
        n = int(base_points * rng.uniform(0.9, 1.1))
        coords = rng.uniform(0, 1, size=(n, 3)).astype(np.float32)
        theta = rng.uniform(0.5, 1.5, size=(2,)).astype(np.float32)
        m = max(32, n // 8)
        inlet = rng.uniform(0, 1, size=(m, 3)).astype(np.float32)
        vel = np.cos(np.pi * inlet[:, :1]).astype(np.float32)
        f = np.concatenate([inlet, vel], axis=1)
        y = _smooth_target(coords, theta, (f,))
        out.append(MeshSample(coords=coords, y=y, theta=theta, funcs=(f,)))
    return out


SYNTHETIC: dict[str, Callable[..., list[MeshSample]]] = {
    "darcy2d": synth_darcy2d,
    "ns2d": synth_ns2d,
    "elasticity": synth_elasticity,
    "inductor2d": synth_inductor2d,
    "heatsink3d": synth_heatsink3d,
}

# Name of each generator's size kwarg, for DataConfig.synth_size.
_SIZE_KWARG = {
    "darcy2d": "grid_n",
    "ns2d": "n_points",
    "elasticity": "base_points",
    "inductor2d": "base_points",
    "heatsink3d": "base_points",
}


def load(data_cfg) -> tuple[list[MeshSample], list[MeshSample]]:
    """Load (train, test) per DataConfig: pickle paths or synthetic."""
    if data_cfg.train_path:
        train = load_pickle(data_cfg.train_path)
        test = load_pickle(data_cfg.test_path) if data_cfg.test_path else []
        return train, test
    gen = SYNTHETIC[data_cfg.synthetic]
    kwargs = {}
    if getattr(data_cfg, "synth_size", 0):
        kwargs[_SIZE_KWARG[data_cfg.synthetic]] = data_cfg.synth_size
    train = gen(data_cfg.n_train, seed=data_cfg.seed, **kwargs)
    test = gen(data_cfg.n_test, seed=data_cfg.seed + 1, **kwargs)
    return train, test


def infer_model_dims(samples: Sequence[MeshSample]) -> dict[str, int]:
    """Shape inference from sample 0 (reference main.py:30-35)."""
    s = samples[0]
    return dict(
        input_dim=s.coords.shape[1],
        theta_dim=int(np.atleast_1d(s.theta).shape[0]),
        input_func_dim=s.funcs[0].shape[1] if s.funcs else 1,
        out_dim=s.y.shape[1],
        n_input_functions=len(s.funcs),
    )
