"""Configuration dataclasses for the GNOT-TPU framework.

The reference configures everything through nine argparse flags plus
hardcoded constants (``/root/reference/main.py:15-23,41,50``). Here the
full surface is a set of dataclasses with CLI overrides; defaults
reproduce the reference regime exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any


def parse_tenant_spec(spec: str, *, what: str = "value") -> dict[str, str]:
    """Parse a ``tenant:value,tenant:value`` spec string — the shared
    grammar of ``--tenant_weights`` / ``--tenant_quotas`` /
    ``--tenant_priorities`` (docs/serving.md "Multi-tenant isolation")
    — into an ordered ``{tenant: raw value}`` dict. Empty string parses
    to an empty dict; malformed entries and duplicate tenants raise.
    Lives here (not serve/policies.py) so ``ServeConfig`` can validate
    specs without importing the serving package."""
    out: dict[str, str] = {}
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        name, sep, value = entry.partition(":")
        name, value = name.strip(), value.strip()
        if not sep or not name or not value:
            raise ValueError(
                f"malformed tenant {what} entry {entry!r}; expected "
                "'tenant:value,tenant:value'"
            )
        if name in out:
            raise ValueError(f"duplicate tenant {name!r} in {what} spec")
        out[name] = value
    return out


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """GNOT architecture hyperparameters (reference main.py:16-22)."""

    input_dim: int = 2
    theta_dim: int = 1
    input_func_dim: int = 1
    out_dim: int = 1
    n_input_functions: int = 1
    n_attn_layers: int = 4
    n_attn_hidden_dim: int = 256
    n_mlp_num_layers: int = 4
    n_mlp_hidden_dim: int = 256
    n_input_hidden_dim: int = 256
    n_expert: int = 3
    n_head: int = 8
    # --- TPU-native knobs (no reference equivalent) ---
    # "parity": unmasked padding, pollution-faithful to the reference.
    # "masked": correct masking; results independent of pad lengths.
    attention_mode: str = "masked"
    # "xla" is the only attention impl: the hand-written pallas kernel
    # lost the honest A/B at every scale (2.4x at L=1k, 1.6x at L=16k —
    # docs/performance.md "Why the fused attention kernel lost") and its
    # model-level dispatch was retired in round 4. The kernels survive
    # in ops/pallas_attention.py as validated kernel research.
    attention_impl: str = "xla"
    # "xla": batched-GEMM expert FFN (GSPMD-shardable). "pallas": whole
    # expert stack tile-resident in VMEM (ops/pallas_ffn.py);
    # single-device / DP only.
    ffn_impl: str = "xla"
    # GELU flavor for every MLP: "erf" (torch nn.GELU default — the
    # reference's op, reference model.py:8) or "tanh" (the standard
    # tanh approximation). "" auto-resolves to "erf" in parity mode
    # (bit-faithfulness) and "tanh" otherwise: exact erf is VPU-bound
    # on TPU and measures ~2x the whole forward pass at the default
    # architecture (docs/performance.md), while tanh-GELU changes
    # activations by ~1e-3 and final quality within noise (the quality
    # gates run against the erf-based torch oracle and still pass).
    gelu: str = ""
    # Compute dtype for the encoder stack; params stay float32.
    dtype: str = "float32"
    # Rematerialize each attention block in backward (jax.checkpoint):
    # trades ~1 extra forward of FLOPs for O(n_attn_layers) less
    # activation memory — the lever for long point clouds on one chip.
    remat: bool = False
    # Run the block stack as ONE lax.scan over stacked per-layer params
    # (the pipeline parameter layout) instead of n_attn_layers inlined
    # block copies: XLA traces/compiles one block regardless of depth —
    # the compile-time lever for deep configs. Same math; params live
    # in the stacked layout (pipeline.stack_params converts). xla
    # impls only.
    scan_layers: bool = False

    def __post_init__(self) -> None:
        if self.n_attn_hidden_dim % self.n_head:
            raise ValueError("n_attn_hidden_dim must be divisible by n_head")
        if self.attention_mode not in ("parity", "masked"):
            raise ValueError(f"unknown attention_mode {self.attention_mode!r}")
        if not self.gelu:
            object.__setattr__(
                self,
                "gelu",
                "erf" if self.attention_mode == "parity" else "tanh",
            )
        if self.gelu not in ("erf", "tanh"):
            raise ValueError(f"unknown gelu {self.gelu!r}")
        if self.attention_mode == "parity" and self.gelu != "erf":
            raise ValueError(
                "parity mode reproduces the reference bit-for-bit and "
                "requires gelu='erf' (torch nn.GELU); tanh-GELU is the "
                "masked-mode TPU default"
            )
        if self.attention_impl == "pallas":
            raise ValueError(
                "attention_impl='pallas' was retired in round 4: the "
                "fused kernel measured slower than the XLA einsum path "
                "at every scale under honest timing (docs/performance.md"
                " 'Why the fused attention kernel lost'). The kernels "
                "remain in ops/pallas_attention.py for research use."
            )
        if self.attention_impl != "xla":
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.ffn_impl not in ("xla", "pallas"):
            raise ValueError(f"unknown ffn_impl {self.ffn_impl!r}")
        if self.scan_layers and (
            self.attention_impl != "xla" or self.ffn_impl != "xla"
        ):
            raise ValueError("scan_layers requires the xla attention/ffn impls")


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    """AdamW + OneCycle regime (reference main.py:50-52)."""

    lr: float = 1e-3
    # torch.optim.AdamW defaults, set explicitly because optax's differ.
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.01
    # OneCycleLR defaults (torch): cos anneal, 3-phase off.
    pct_start: float = 0.3
    div_factor: float = 25.0
    final_div_factor: float = 1e4
    # The reference constructs OneCycleLR with steps_per_epoch but calls
    # scheduler.step() once per EPOCH (main.py:52,106), so the LR never
    # leaves the warm-up ramp. parity_schedule_bug=True reproduces that;
    # False steps the schedule per optimizer update (the correct form).
    parity_schedule_bug: bool = True
    grad_clip_norm: float = 0.0  # 0 = off (reference has no clipping)
    # Accumulate gradients over k micro-batches before each optimizer
    # update (1 = off). Effective batch = k x batch_size with the same
    # device memory — the lever when big meshes cap the per-step batch.
    # Keep steps_per_epoch divisible by k: MultiSteps discards a partial
    # trailing window, and windows straddling epoch boundaries make
    # per-epoch eval observe mid-window params.
    grad_accum: int = 1
    # Flat [P]-vector parameter/optimizer layout: params (and the AdamW
    # moments) live as ONE ravelled f32 buffer; the forward unravels it
    # into the param tree (slices/reshapes XLA folds away). The per-op
    # profile (docs/performance.md) attributes ~2 us of launch overhead
    # to EACH of the ~184 per-leaf optimizer ops plus per-leaf
    # while-carry copy plumbing; the flat layout fuses the whole update
    # into a few whole-buffer ops. Same math (ravel/unravel is exact).
    # Composes with the data/seq mesh axes (params stay one replicated
    # buffer); incompatible with model/expert/pipe sharding and
    # scan_layers, which need the tree layout.
    flat_params: bool = False

    def __post_init__(self) -> None:
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {self.grad_accum}")


@dataclasses.dataclass(frozen=True)
class DataConfig:
    train_path: str = ""
    test_path: str = ""
    # Synthetic fallback so nothing blocks on data files; one of the five
    # benchmark configs in BASELINE.json.
    synthetic: str = "ns2d"  # darcy2d | ns2d | elasticity | inductor2d | heatsink3d
    # Size knob of the synthetic generator (0 = its default): grid side
    # for darcy2d (points = size^2), mesh points for the others.
    synth_size: int = 0
    n_train: int = 64
    n_test: int = 16
    batch_size: int = 4  # reference main.py:41
    shuffle_train: bool = True
    seed: int = 0
    # Pad ragged lengths up to the next bucket boundary (power of two) to
    # bound XLA recompiles. 1 disables bucketing (per-batch max, as the
    # reference does — parity mode needs this).
    bucket: bool = True
    drop_remainder: bool = False
    # Fixed pad lengths (0 = per-batch). Distributed runs fill these in
    # from dataset-wide maxima so every host pads identically (SPMD).
    pad_nodes: int = 0
    pad_funcs: int = 0
    # "Pack, don't pad": multiple samples share each sequence row as
    # chunk-aligned contiguous segments; exact per-sample attention via
    # segment Grams (ops.attention.packed_normalized_linear_attention).
    # Recovers the ~30% of tokens bucket padding wastes on ragged
    # configs. Masked mode, single device. pack_chunk is the segment
    # alignment granularity (tokens): it is also the per-chunk Gram
    # contraction depth, and the measured on-chip optimum is 128 —
    # chunk=64 Grams are too shallow for the MXU (MFU 0.41 -> 0.34)
    # and chunk=256 pays alignment waste (docs/performance.md).
    packed: bool = False
    pack_chunk: int = 128

    def __post_init__(self) -> None:
        if self.packed and self.pack_chunk < 1:
            raise ValueError(f"pack_chunk must be >= 1, got {self.pack_chunk}")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout. Axis sizes of 1 collapse that axis."""

    data: int = -1  # -1: all remaining devices
    seq: int = 1  # sequence (context) parallelism over mesh points
    model: int = 1  # tensor parallelism over heads / FFN hidden
    # Expert parallelism over the stacked soft-MoE expert axis (the
    # gated combine becomes one psum). n_expert % expert == 0.
    expert: int = 1
    # Pipeline parallelism over the attention-block stack (shard_map
    # microbatch pipeline, parallel/pipeline.py). Composes with `data`;
    # requires seq == model == expert == 1 and
    # n_attn_layers % pipe == 0.
    pipe: int = 1
    # Microbatches per pipeline round-trip (pipe > 1 only); the bubble
    # fraction is (pipe-1)/(microbatches+pipe-1). 0 = one microbatch
    # per pipeline stage.
    microbatches: int = 0


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    epochs: int = 100  # reference main.py:23
    loss: str = "rel_l2"  # the reference trains AND evals on rel-L2
    # Train over the MeshConfig device mesh (sharded jit steps; on
    # multi-process runs the mesh spans hosts). False = single device.
    distributed: bool = False
    checkpoint_dir: str = ""
    resume: bool = False
    checkpoint_every: int = 0  # epochs; 0 = best-only (reference behavior)
    log_every: int = 0  # steps; 0 = per-epoch only
    metrics_path: str = ""  # JSONL sink; "" = console only
    # On-device telemetry + health monitors (obs/): grad/param/update
    # norms, per-layer gate load/entropy and padding waste as side
    # outputs of the compiled step (drained every log_every steps — no
    # per-step host syncs), plus recompile detection, slow-step outlier
    # gauges and the NaN watchdog. Off by default: the side outputs
    # change the compiled program (a different executable, extra
    # reductions), so the perf-measurement default stays untouched.
    telemetry: bool = False
    profile_dir: str = ""  # jax.profiler trace output
    # Host-side structured span tracing (obs/tracing.py): request-
    # lifecycle spans on the serve path, per-step phase spans on the
    # train path, exported as Chrome trace-event JSON to trace_path
    # (chrome://tracing / Perfetto-loadable). Off by default ("" = no
    # tracer object is built; the hot paths carry no span sites).
    trace_path: str = ""
    # Head-based sampling rate in [0, 1]: the keep/drop decision is
    # made once per trace (per epoch when training, per request when
    # serving), deterministically — no RNG — so overhead stays bounded
    # and replays sample identically.
    trace_sample_rate: float = 1.0
    # Debug-build numeric guard: jax_debug_nans — the first NaN/inf in
    # any step raises with the producing op's location instead of
    # silently propagating.
    debug_checks: bool = False
    # Dispatch K training steps (over K different batches) as ONE
    # compiled program (lax.scan over stacked batches): host->device
    # dispatch drops to 1/K per step. Numerically identical to K single
    # steps. Batches must share shapes to stack — groups break at
    # bucket-shape changes and epoch ends, and the remainder runs
    # through the single-step path.
    steps_per_dispatch: int = 1
    # Fault injection: stop cleanly after this many epochs (0 = off),
    # simulating a preemption mid-run. The schedule/epoch horizon stays
    # sized by `epochs`, so a --resume run continues the SAME regime —
    # this is how resume correctness is tested. Alias for the
    # ``stop_epoch@N`` entry of `inject_fault` (resilience/faults.py);
    # both drive the same injection framework.
    stop_after_epoch: int = 0
    # Deterministic fault injection spec (resilience/faults.py):
    # comma-separated ``kind@N`` entries — nan_grad@step, bad_sample@
    # step, sigterm@step, ckpt_io@count, corrupt_ckpt@epoch,
    # stop_epoch@epochs. "" = no faults. Every recovery path below is
    # testable on CPU through this knob (docs/robustness.md).
    inject_fault: str = ""
    # Automatic NaN recovery (resilience/supervisor.py): keep a rolling
    # last-good on-device snapshot every `snapshot_every` steps; a
    # detected non-finite loss rolls back to it, quarantines the
    # offending dispatch, and continues — escalating to checkpoint
    # restore after `max_rollbacks`, then to the hard abort. Off by
    # default: recovery CHANGES the training trajectory (skipped
    # batches, replayed steps), so the fail-fast default stays exact.
    recovery: bool = False
    snapshot_every: int = 50  # steps between last-good snapshots
    max_rollbacks: int = 3  # rollback budget before escalating
    # Graceful preemption (resilience/preemption.py): SIGTERM/SIGINT
    # stop the run at the next step boundary — saving `latest` when a
    # checkpointer is present, flushing the sink, exiting resume-ready
    # — instead of dying mid-step. Multi-host runs coordinate the stop
    # step via an allgathered flag every `preempt_sync_every`
    # dispatches (1 = every step boundary; raise it when the per-
    # dispatch collective matters).
    graceful_preempt: bool = True
    preempt_sync_every: int = 1
    seed: int = 0

    def __post_init__(self) -> None:
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {self.snapshot_every}"
            )
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}"
            )
        if self.preempt_sync_every < 1:
            raise ValueError(
                f"preempt_sync_every must be >= 1, got {self.preempt_sync_every}"
            )
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got "
                f"{self.trace_sample_rate}"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Inference-serving policies (gnot_tpu/serve/, docs/serving.md).

    Used by the ``--serve`` entrypoint and library users of
    ``serve.InferenceServer``; training ignores this section."""

    # Dynamic batching: a bucket's queue flushes at max_batch requests
    # or when its oldest request has waited max_wait_ms — the
    # latency/utilization dial. Every dispatch is padded to max_batch
    # rows, so each bucket compiles exactly one program.
    max_batch: int = 4
    max_wait_ms: float = 10.0
    # Bounded-queue admission: at most queue_limit requests in the
    # system; beyond it, submissions fast-fail ("shed_queue_full")
    # instead of growing a backlog that then misses every deadline.
    queue_limit: int = 64
    # Default per-request deadline (ms; 0 = none). Expired requests are
    # shed BEFORE dispatch, and the same budget clamps downstream
    # retries (resilience.retry deadline).
    deadline_ms: float = 0.0
    # Circuit breaker: trips open after `breaker_threshold` consecutive
    # dispatch failures (non-finite outputs / device errors); while
    # open, requests get instant reject-with-reason responses. After
    # breaker_cooldown_s one half-open trial decides recovery.
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 1.0
    # Graceful-drain budget: how long drain() waits for in-flight
    # requests before force-resolving the stragglers.
    drain_timeout_s: float = 30.0
    # Serve-side deterministic fault injection (resilience/faults.py):
    # slow_request@N, nan_output@N, reload_corrupt@N. "" = none.
    inject_fault: str = ""
    # Packed dispatch mode ("pack, don't pad" on the serving hot path,
    # docs/performance.md): plan-fitting requests are first-fit packed
    # as chunk-aligned segments into ONE fixed-shape program
    # (data/batch.py::PackPlan derived from the warmup traffic) instead
    # of one padded row each; oversize requests fall back to the
    # per-bucket padded path. pack_chunk is the segment alignment (and
    # the packed kernel tile) — a multiple of 8; smaller packs small
    # meshes tighter, larger gives the MXU longer contiguous spans.
    packed: bool = False
    pack_chunk: int = 64
    # Replicated serving (serve/router.py + serve/replica.py,
    # docs/serving.md "Replicated serving"): N engine replicas over
    # disjoint device slices behind a compile-affinity router. 1 = the
    # single-server tier (unchanged). Each replica gets its own
    # admission queue/batcher/breaker; max_batch must divide by the
    # per-replica device-slice size.
    replicas: int = 1
    # Router placement policy: "affinity" (prefer the replica that
    # already compiled the request's bucket — cold compiles land on one
    # replica, never the pool), "least_loaded", or "round_robin".
    route_policy: str = "affinity"
    # Seconds of worker-loop silence (with requests in-system) before
    # the router treats a replica as wedged and drains its traffic to
    # siblings.
    wedge_after_s: float = 2.0
    # Serving compute dtype (models/precision.py): "float32" (the
    # historical path, byte-identical) or "bfloat16" — the block stack
    # computes bf16 with f32 einsum accumulation, an f32 attention
    # normalizer and an f32 output head; params stay f32 at rest and
    # the engine publishes a cast copy per reload. Program identity
    # (bucket signatures, PackPlan programs, AOT manifests) is
    # dtype-keyed, so a bf16 deployment refuses f32 snapshots.
    dtype: str = "float32"
    # Autoregressive rollout serving (serve/rollout.py, docs/serving.md
    # "Rollout serving"): with rollout_steps K > 0 the --serve
    # entrypoint drives each test sample as ONE K-step session — K
    # chained dispatches whose carry stays resident on the owning
    # replica, per-step deadlines (deadline_ms applies per step),
    # streaming partial results, and router-driven migration from the
    # rolling host-side snapshot when the owner dies mid-rollout.
    # 0 = one-shot serving (the historical path, unchanged).
    rollout_steps: int = 0
    # Rolling session-snapshot cadence (steps between host-side carry
    # snapshots — the state a migration replays from; the supervisor's
    # last-good pattern applied to serving). 1 = snapshot every step
    # (zero replay on migration); larger trades snapshot copies for
    # at-least-once replayed steps.
    session_snapshot_every: int = 1
    # Live metrics plane (obs/metrics.py, docs/observability.md "Live
    # metrics"): with metrics_interval_s > 0 a MetricsPublisher polls
    # the serving tier's metric registry every interval — windowed
    # log-bucketed latency histograms, shed/route counters, depth/
    # breaker gauges — and publishes each snapshot as a
    # `metrics_snapshot` event, one JSONL time-series row
    # (<metrics-stem>.series.jsonl) and an atomically-rewritten
    # Prometheus-text exposition file (<metrics-stem>.prom), while an
    # SLOEvaluator turns the snapshot history into `slo_alert`
    # fire/clear edges. 0 = off (the historical drain-time-only path;
    # serve_summary itself is unchanged either way).
    metrics_interval_s: float = 0.0
    # SLO objectives the evaluator checks over fast/slow burn-rate
    # windows (both must burn > 1.0 to FIRE; the fast window clearing
    # CLEARS — edges only, never level spam). slo_p99_ms 0 disables
    # the latency objective; slo_shed_frac is the tolerated windowed
    # shed fraction (0 disables). Breaker-open, queue-saturation and
    # session-loss objectives are always on when the plane is.
    slo_p99_ms: float = 0.0
    slo_shed_frac: float = 0.05
    slo_fast_window_s: float = 5.0
    slo_slow_window_s: float = 30.0
    # Self-healing elastic serving (serve/autoscaler.py,
    # docs/serving.md "Elastic capacity"): with autoscale on, an
    # AutoscaleController subscribes to the live metrics plane (the
    # registry + SLO evaluator — requires metrics_interval_s > 0 for
    # the alert signals; the load gauges work either way) and scales
    # the replica pool between autoscale_min and autoscale_max:
    # prewarm-before-join scale-out under SLO pressure / high
    # per-replica load, drain-then-remove scale-in after sustained
    # calm (resident rollout sessions migrate to siblings; the retired
    # replica's latency history stays in the pool rollup), and
    # self-healing replacement of dead/wedged/breaker-stuck replicas.
    # Stability guards: per-direction cooldowns (autoscale_cooldown_s),
    # up/down load hysteresis (autoscale_up_load > autoscale_down_load,
    # per-replica in-system requests+sessions), a consecutive-calm-tick
    # requirement before any scale-in, and a flap suppressor (no
    # scale-in within 3 cooldowns of a scale-out).
    autoscale: bool = False
    autoscale_min: int = 1
    autoscale_max: int = 4
    autoscale_interval_s: float = 0.5
    autoscale_cooldown_s: float = 2.0
    autoscale_up_load: float = 8.0
    autoscale_down_load: float = 1.0
    autoscale_down_ticks: int = 3
    autoscale_heal_after_s: float = 5.0
    # On-disk rollout-session persistence (serve/rollout.py::
    # SessionStore): with a directory set, every client-NAMED session
    # (submit_rollout(name=...)) drained mid-rollout (SIGTERM, restart,
    # pool teardown) persists its final carry snapshot there, and a
    # restarted server/router resumes it from its last snapshotted
    # step (resume_rollout). Auto-id sessions never persist — their
    # ids restart per process, so persisting them would let one run's
    # snapshots clobber another's. "" = off.
    session_dir: str = ""
    # Multi-tenant isolation plane (serve/policies.py::TenantPolicy,
    # docs/serving.md "Multi-tenant isolation"). Each knob is a
    # ``tenant:value,...`` spec; any non-empty spec activates tenant
    # mode (per-tenant WFQ sub-queues, quotas, priority tiers, tenant_*
    # metrics/SLOs). All three empty = the historical single-tenant
    # path, byte-for-byte. Weights are the per-tenant deficit-round-
    # robin shares within a priority tier (integers >= 1; unlisted
    # tenants weigh 1); quotas bound a tenant's in-system request count
    # (fast-fail "shed_tenant_quota" beyond it; unlisted = unlimited);
    # priorities assign "interactive" or "batch" (unlisted tenants are
    # interactive — except one literally named "batch", so
    # `--tenant_weights interactive:3,batch:1` does what it reads).
    tenant_weights: str = ""
    tenant_quotas: str = ""
    tenant_priorities: str = ""
    # Deploy-time AOT prewarm manifest (tools/aot_prewarm.py,
    # docs/serving.md "Deploy-time prewarm"): when set, serving
    # hydrates each engine's executables from the manifest's
    # warm-replica snapshots before warmup — a covered program costs a
    # snapshot load (no trace, no XLA compile); warmup then only
    # compiles buckets the manifest missed. "" = cold warmup (the
    # classical path). The manifest must match the serving topology
    # (replica count) and model; a model mismatch degrades to cold
    # warmup, loudly.
    prewarm_manifest: str = ""
    # Topology-honest federation (serve/federation.py,
    # docs/distributed.md): hosts > 1 splits the replica pool into
    # `hosts` independent ReplicaRouter pools, each behind a HostAgent,
    # and serves through a ClusterRouter over the versioned wire
    # protocol — lease heartbeats, suspicion→dead failure detection,
    # partition-tolerant placement and cross-host session migration.
    # hosts = 1 is the historical single-host path, byte-for-byte.
    # federation_port: 0 = in-proc links (the loopback-deterministic
    # default); a real port makes host 0's agent listen on loopback
    # TCP so external controllers can speak the protocol.
    # heartbeat_interval_s is the controller tick; suspect_after_s /
    # dead_after_s are the detector's lease ages (the gap between them
    # is the dwell — a slow host is drained around, not killed).
    hosts: int = 1
    federation_port: int = 0
    heartbeat_interval_s: float = 0.5
    suspect_after_s: float = 2.0
    dead_after_s: float = 6.0
    # Anomaly flight recorder (obs/dtrace.py, docs/observability.md
    # "Distributed tracing"): keep the last N seconds of ALL spans and
    # events — sampled or not — in a bounded per-host ring, dumped
    # atomically on trigger edges (slo_alert fire, breaker_open,
    # host_dead, non_finite_loss, lockguard inversion). 0 = off (no
    # recorder objects exist; the span paths carry no shadow ids).
    flight_recorder_s: float = 0.0

    def __post_init__(self) -> None:
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.route_policy not in ("affinity", "least_loaded", "round_robin"):
            raise ValueError(
                f"unknown route_policy {self.route_policy!r}; one of "
                "('affinity', 'least_loaded', 'round_robin')"
            )
        if self.wedge_after_s <= 0:
            raise ValueError(
                f"wedge_after_s must be > 0, got {self.wedge_after_s}"
            )
        if self.pack_chunk < 8 or self.pack_chunk % 8:
            raise ValueError(
                f"pack_chunk must be a positive multiple of 8, got "
                f"{self.pack_chunk}"
            )
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}"
            )
        if self.queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {self.queue_limit}"
            )
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got {self.breaker_threshold}"
            )
        if self.rollout_steps < 0:
            raise ValueError(
                f"rollout_steps must be >= 0, got {self.rollout_steps}"
            )
        if self.session_snapshot_every < 1:
            raise ValueError(
                "session_snapshot_every must be >= 1, got "
                f"{self.session_snapshot_every}"
            )
        if self.metrics_interval_s < 0:
            raise ValueError(
                f"metrics_interval_s must be >= 0, got "
                f"{self.metrics_interval_s}"
            )
        if self.slo_p99_ms < 0:
            raise ValueError(
                f"slo_p99_ms must be >= 0, got {self.slo_p99_ms}"
            )
        if not 0.0 <= self.slo_shed_frac <= 1.0:
            raise ValueError(
                f"slo_shed_frac must be in [0, 1], got {self.slo_shed_frac}"
            )
        if not 0 < self.slo_fast_window_s <= self.slo_slow_window_s:
            raise ValueError(
                "need 0 < slo_fast_window_s <= slo_slow_window_s, got "
                f"{self.slo_fast_window_s}/{self.slo_slow_window_s}"
            )
        if not 1 <= self.autoscale_min <= self.autoscale_max:
            raise ValueError(
                "need 1 <= autoscale_min <= autoscale_max, got "
                f"{self.autoscale_min}/{self.autoscale_max}"
            )
        if self.autoscale and not (
            self.autoscale_min <= self.replicas <= self.autoscale_max
        ):
            raise ValueError(
                f"--autoscale needs the founding pool size (replicas="
                f"{self.replicas}) within [autoscale_min, autoscale_max]"
                f" = [{self.autoscale_min}, {self.autoscale_max}]"
            )
        if self.autoscale_interval_s <= 0:
            raise ValueError(
                "autoscale_interval_s must be > 0, got "
                f"{self.autoscale_interval_s}"
            )
        if self.autoscale_cooldown_s < 0:
            raise ValueError(
                "autoscale_cooldown_s must be >= 0, got "
                f"{self.autoscale_cooldown_s}"
            )
        if not 0 <= self.autoscale_down_load < self.autoscale_up_load:
            raise ValueError(
                "autoscale hysteresis needs 0 <= down_load < up_load, "
                f"got {self.autoscale_down_load}/{self.autoscale_up_load}"
            )
        if self.autoscale_down_ticks < 1:
            raise ValueError(
                "autoscale_down_ticks must be >= 1, got "
                f"{self.autoscale_down_ticks}"
            )
        if self.autoscale_heal_after_s <= 0:
            raise ValueError(
                "autoscale_heal_after_s must be > 0, got "
                f"{self.autoscale_heal_after_s}"
            )
        if self.hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")
        if self.hosts > 1 and self.replicas % self.hosts:
            raise ValueError(
                f"replicas ({self.replicas}) must divide evenly across "
                f"hosts ({self.hosts}) — every host pool is identically "
                "sized so the topology key is well-defined"
            )
        if self.federation_port and not (
            1024 <= self.federation_port <= 65535
        ):
            raise ValueError(
                "federation_port must be 0 (in-proc) or in [1024, 65535], "
                f"got {self.federation_port}"
            )
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                "heartbeat_interval_s must be > 0, got "
                f"{self.heartbeat_interval_s}"
            )
        if not 0 < self.suspect_after_s < self.dead_after_s:
            raise ValueError(
                "failure detector needs 0 < suspect_after_s < "
                "dead_after_s (the suspicion dwell), got "
                f"{self.suspect_after_s}/{self.dead_after_s}"
            )
        if self.flight_recorder_s < 0:
            raise ValueError(
                "flight_recorder_s must be >= 0 (0 = off), got "
                f"{self.flight_recorder_s}"
            )
        if self.hosts > 1 and self.autoscale:
            raise ValueError(
                "--autoscale is single-host (the pool-level controller); "
                "with hosts > 1 use the cluster's scale plane "
                "(ClusterRouter.scale / autoscale_target)"
            )
        for t, w in parse_tenant_spec(
            self.tenant_weights, what="weight"
        ).items():
            if not w.isdigit() or int(w) < 1:
                raise ValueError(
                    f"tenant weight for {t!r} must be an integer >= 1, "
                    f"got {w!r}"
                )
        for t, q in parse_tenant_spec(
            self.tenant_quotas, what="quota"
        ).items():
            if not q.isdigit() or int(q) < 1:
                raise ValueError(
                    f"tenant quota for {t!r} must be an integer >= 1, "
                    f"got {q!r}"
                )
        for t, p in parse_tenant_spec(
            self.tenant_priorities, what="priority"
        ).items():
            if p not in ("interactive", "batch"):
                raise ValueError(
                    f"tenant priority for {t!r} must be 'interactive' or "
                    f"'batch', got {p!r}"
                )
        from gnot_tpu.models.precision import SERVE_DTYPES

        if self.dtype not in SERVE_DTYPES:
            raise ValueError(
                f"unknown serve dtype {self.dtype!r}; one of {SERVE_DTYPES}"
            )


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)


def _apply_overrides(cfg: Any, overrides: dict[str, Any]) -> Any:
    """Apply dotted-path overrides, e.g. {"model.n_head": 4}."""
    for key, value in overrides.items():
        parts = key.split(".")
        if len(parts) == 1:
            # Bare keys search sections for a unique match.
            hits = [
                f.name
                for f in dataclasses.fields(cfg)
                if any(g.name == key for g in dataclasses.fields(getattr(cfg, f.name)))
            ]
            if len(hits) != 1:
                raise KeyError(f"ambiguous or unknown config key {key!r}: {hits}")
            parts = [hits[0], key]
        section_name, field_name = parts
        section = getattr(cfg, section_name)
        if not any(f.name == field_name for f in dataclasses.fields(section)):
            raise KeyError(f"unknown config field {section_name}.{field_name}")
        section = dataclasses.replace(section, **{field_name: value})
        cfg = dataclasses.replace(cfg, **{section_name: section})
    return cfg


def make_config(**overrides: Any) -> Config:
    return _apply_overrides(Config(), overrides)
